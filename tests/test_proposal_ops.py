"""RPN/FPN proposal op family vs hand-computed oracles.

The fixtures follow the reference unit tests' shapes
(test_generate_proposals_op.py, test_rpn_target_assign_op.py,
test_distribute_fpn_proposals_op.py, test_collect_fpn_proposals_op.py)
with deterministic settings (use_random=False).
"""
import numpy as np

import paddle_tpu as fluid

from paddle_tpu.ops.proposal_ops import (
    _box_to_delta, _decode_boxes, _iou_matrix)


def test_generate_proposals_end_to_end():
    N, A, H, W = 1, 3, 4, 4
    rng = np.random.RandomState(0)
    scores = rng.rand(N, A, H, W).astype("float32")
    deltas = (rng.randn(N, 4 * A, H, W) * 0.2).astype("float32")
    im_info = np.array([[64.0, 64.0, 1.0]], "float32")
    anchors = np.zeros((H, W, A, 4), "float32")
    sizes = [8.0, 16.0, 24.0]
    for h in range(H):
        for w in range(W):
            for a in range(A):
                cx, cy, s = w * 16 + 8, h * 16 + 8, sizes[a]
                anchors[h, w, a] = [cx - s, cy - s, cx + s, cy + s]
    variances = np.full((H, W, A, 4), 1.0, "float32")

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        sc = fluid.data(name="sc", shape=[N, A, H, W], dtype="float32")
        dl = fluid.data(name="dl", shape=[N, 4 * A, H, W], dtype="float32")
        ii = fluid.data(name="ii", shape=[N, 3], dtype="float32")
        an = fluid.data(name="an", shape=[H, W, A, 4], dtype="float32")
        va = fluid.data(name="va", shape=[H, W, A, 4], dtype="float32")
        rois, probs = fluid.layers.generate_proposals(
            sc, dl, ii, an, va, pre_nms_top_n=20, post_nms_top_n=5,
            nms_thresh=0.7, min_size=2.0)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(prog, feed={"sc": scores, "dl": deltas, "ii": im_info,
                            "an": anchors, "va": variances}, fetch_list=[])
        rois_t = scope.find_var(rois.name).get_tensor()
        probs_t = scope.find_var(probs.name).get_tensor()
    r = rois_t.numpy()
    p = probs_t.numpy()
    assert r.shape[0] == p.shape[0] <= 5
    assert rois_t.lod() == [[0, r.shape[0]]]
    # every roi inside the image, min_size respected
    assert (r[:, 0] >= 0).all() and (r[:, 2] <= 63).all()
    assert (r[:, 1] >= 0).all() and (r[:, 3] <= 63).all()
    assert ((r[:, 2] - r[:, 0] + 1) >= 2).all()
    # scores sorted descending (NMS emits in score order)
    assert (np.diff(p.reshape(-1)) <= 1e-6).all()


def test_generate_proposals_decode_matches_reference_formula():
    anchors = np.array([[0.0, 0.0, 15.0, 15.0]], "float32")
    deltas = np.array([[0.1, -0.2, 0.3, 0.4]], "float32")
    var = np.array([[1.0, 1.0, 1.0, 1.0]], "float32")
    got = _decode_boxes(anchors, deltas, var)
    aw = ah = 16.0
    # reference center = x0 + 0.5*w = 8 (not the midpoint 7.5)
    cx, cy = 8.0 + 0.1 * aw, 8.0 - 0.2 * ah
    w, h = np.exp(0.3) * aw, np.exp(0.4) * ah
    ref = [cx - w / 2, cy - h / 2, cx + w / 2 - 1, cy + h / 2 - 1]
    np.testing.assert_allclose(got[0], ref, rtol=1e-5)


def test_rpn_target_assign_deterministic():
    A = 6
    anchors = np.array(
        [[0, 0, 15, 15], [8, 8, 23, 23], [16, 16, 31, 31],
         [24, 24, 39, 39], [0, 16, 15, 31], [16, 0, 31, 15]], "float32")
    gts = np.array([[1, 1, 14, 14], [17, 17, 30, 30]], "float32")
    crowd = np.zeros((2, 1), "int32")
    im_info = np.array([[40.0, 40.0, 1.0]], "float32")

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        bp = fluid.data(name="bp", shape=[1, A, 4], dtype="float32")
        cl = fluid.data(name="cl", shape=[1, A, 1], dtype="float32")
        an = fluid.data(name="an", shape=[A, 4], dtype="float32")
        av = fluid.data(name="av", shape=[A, 4], dtype="float32")
        gt = fluid.data(name="gt", shape=[2, 4], dtype="float32")
        ic = fluid.data(name="ic", shape=[2, 1], dtype="int32")
        ii = fluid.data(name="ii", shape=[1, 3], dtype="float32")
        outs = fluid.layers.rpn_target_assign(
            bp, cl, an, av, gt, ic, ii, rpn_batch_size_per_im=256,
            rpn_straddle_thresh=0.0, rpn_fg_fraction=0.5,
            rpn_positive_overlap=0.7, rpn_negative_overlap=0.3,
            use_random=False)
        score_pred, loc_pred, tgt_lbl, tgt_bbox, in_w = outs
    scope = fluid.Scope()
    rng = np.random.RandomState(1)
    feed = {"bp": rng.randn(1, A, 4).astype("float32"),
            "cl": rng.randn(1, A, 1).astype("float32"),
            "an": anchors, "av": np.ones((A, 4), "float32"),
            "gt": gts, "ic": crowd, "ii": im_info}
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        (lbl, tb, iw) = exe.run(prog, feed=feed,
                                fetch_list=[tgt_lbl, tgt_bbox, in_w])
    lbl = np.asarray(lbl).reshape(-1)
    tb = np.asarray(tb)
    iw = np.asarray(iw)
    # anchors 0 and 2 have max IoU with the two gts -> fg
    iou = _iou_matrix(anchors, gts)
    expect_fg = set(np.where(
        (np.abs(iou - iou.max(0)[None]) < 1e-5).any(1)
        | (iou.max(1) >= 0.7))[0])
    n_fg = int(lbl.sum())
    assert n_fg == len(expect_fg)
    # regression targets match BoxToDelta for the fg anchors
    fg_anchor_idx = sorted(expect_fg)
    gt_idx = iou[fg_anchor_idx].argmax(1)
    ref_tb = _box_to_delta(anchors[fg_anchor_idx], gts[gt_idx])
    np.testing.assert_allclose(tb, ref_tb, rtol=1e-4, atol=1e-5)
    assert iw.shape == tb.shape and (iw == 1.0).all()


def test_distribute_and_collect_fpn():
    # rois sized to land on distinct levels
    rois = np.array([
        [0, 0, 15, 15],      # small -> min level
        [0, 0, 111, 111],    # sqrt(area)=112 -> level 3 (refer 224@4)
        [0, 0, 223, 223],    # -> level 4
        [0, 0, 447, 447],    # -> level 5
    ], "float32")
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        fr = fluid.data(name="fr", shape=[4, 4], dtype="float32")
        multi, restore = fluid.layers.distribute_fpn_proposals(
            fr, min_level=2, max_level=5, refer_level=4, refer_scale=224)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(prog, feed={"fr": rois}, fetch_list=[])
        outs = [scope.find_var(v.name).get_tensor().numpy() for v in multi]
        rest = scope.find_var(restore.name).get_tensor().numpy()
    assert [o.shape[0] for o in outs] == [1, 1, 1, 1]
    np.testing.assert_allclose(outs[0][0], rois[0])
    np.testing.assert_allclose(outs[3][0], rois[3])
    assert sorted(rest.reshape(-1).tolist()) == [0, 1, 2, 3]

    # collect: take top-3 by score across two levels, restore batch order
    prog2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog2, startup2):
        r1 = fluid.data(name="r1", shape=[2, 4], dtype="float32")
        r2 = fluid.data(name="r2", shape=[2, 4], dtype="float32")
        s1 = fluid.data(name="s1", shape=[2, 1], dtype="float32")
        s2 = fluid.data(name="s2", shape=[2, 1], dtype="float32")
        out = fluid.layers.collect_fpn_proposals(
            [r1, r2], [s1, s2], 2, 3, post_nms_top_n=3)
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(prog2, feed={
            "r1": np.array([[0, 0, 1, 1], [2, 2, 3, 3]], "float32"),
            "r2": np.array([[4, 4, 5, 5], [6, 6, 7, 7]], "float32"),
            "s1": np.array([[0.9], [0.1]], "float32"),
            "s2": np.array([[0.8], [0.7]], "float32")}, fetch_list=[])
        got = scope2.find_var(out.name).get_tensor().numpy()
    # top3 scores: 0.9, 0.8, 0.7 -> rois [0,0,1,1], [4,4,5,5], [6,6,7,7]
    np.testing.assert_allclose(
        got, [[0, 0, 1, 1], [4, 4, 5, 5], [6, 6, 7, 7]])


def test_box_decoder_and_assign():
    prior = np.array([[0, 0, 15, 15]], "float32")
    var = np.array([0.1, 0.1, 0.2, 0.2], "float32")
    target = np.array([[0, 0, 0, 0, 0.1, -0.1, 0.2, 0.3]], "float32")
    score = np.array([[0.2, 0.8]], "float32")
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        pb = fluid.data(name="pb", shape=[1, 4], dtype="float32")
        pv = fluid.data(name="pv", shape=[4], dtype="float32")
        tb = fluid.data(name="tb", shape=[1, 8], dtype="float32")
        bs = fluid.data(name="bs", shape=[1, 2], dtype="float32")
        dec, asg = fluid.layers.box_decoder_and_assign(pb, pv, tb, bs, 4.135)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        (d, a) = exe.run(prog, feed={"pb": prior, "pv": var, "tb": target,
                                     "bs": score}, fetch_list=[dec, asg])
    d, a = np.asarray(d), np.asarray(a)
    pw = ph = 16.0
    # reference center = x0 + w/2 = 8
    cx = 0.1 * 0.1 * pw + 8.0
    cy = 0.1 * -0.1 * ph + 8.0
    w = np.exp(0.2 * 0.2) * pw
    h = np.exp(0.2 * 0.3) * ph
    ref1 = [cx - w / 2, cy - h / 2, cx + w / 2 - 1, cy + h / 2 - 1]
    np.testing.assert_allclose(d[0, 4:], ref1, rtol=1e-4)
    np.testing.assert_allclose(a[0], ref1, rtol=1e-4)  # class 1 is best


def test_polygon_box_transform():
    x = np.random.RandomState(0).randn(1, 8, 2, 3).astype("float32")
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        xv = fluid.data(name="x", shape=[1, 8, 2, 3], dtype="float32")
        out = fluid.layers.polygon_box_transform(xv)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        (o,) = exe.run(prog, feed={"x": x}, fetch_list=[out])
    o = np.asarray(o)
    ref = np.empty_like(x)
    for c in range(8):
        for hh in range(2):
            for ww in range(3):
                base = ww * 4 if c % 2 == 0 else hh * 4
                ref[0, c, hh, ww] = base - x[0, c, hh, ww]
    np.testing.assert_allclose(o, ref, rtol=1e-5)
