"""Single-chip fusion rewrites: fused optimizer update + fused epilogues.

The multi-chip fast path (parallel/collectives.py) made the optimizer
boundary a single flat-buffer op per optimizer instance; this module is
the SINGLE-CHIP mirror, driven by the step profiler's finding that the
optimizer and elementwise-epilogue phases are memory-bound op chains:

- ``apply_fused_optimizer``: each sgd / momentum / adam / adamw
  instance's per-param update ops collapse into ONE ``fused_optimizer``
  op over flattened params/grads, with optimizer state re-laid-out
  into flat vars (the exact mechanism — and restart resync — the
  sharded-update rewrite already proved). One kernel launch per step
  (ops/pallas/fused_optimizer.py) instead of a per-param op chain.
- ``apply_fused_epilogues``: adjacent forward chains
  ``elementwise_add -> {relu,gelu,tanh,sigmoid} [-> dropout]`` and
  ``elementwise_add -> layer_norm`` collapse into the fused epilogue
  ops (ops/fused_ops.py), which re-emit every intermediate the
  pre-built backward still reads — bit-for-bit, fewer traced ops.

Both are ``@checked_rewrite`` passes: under ``PADDLE_TPU_VERIFY_IR``
their declared contracts (analysis/contracts.py — every (param, grad)
pair updated exactly once; no written var lost) run around the pass
and the whole program re-verifies.

Knobs (default OFF; read per call — one env read each, so the
disabled executor hot path stays under the gate-4 overhead budget):

==============================  ===========================================
``PADDLE_TPU_FUSED_OPTIMIZER``  ``1`` fuses optimizer instances on the
                                single-chip executor path
``PADDLE_TPU_FUSED_EPILOGUE``   ``1`` fuses add->act[->dropout] and
                                add->layer_norm epilogues
==============================  ===========================================

``bench.py`` flips both ON for its single-chip configs (the bit-parity
suite in tests/test_single_chip_fusion.py is the license to); the dp
engine refuses a fused-optimizer program (its grads would dodge the
allreduce transpiler) — the mesh-side equivalent is the sharded update.
"""
from __future__ import annotations

import hashlib
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis.contracts import checked_rewrite

__all__ = ["fused_optimizer_enabled", "fused_epilogue_enabled",
           "maybe_rewrite_single_chip", "apply_fused_optimizer",
           "apply_fused_epilogues", "FUSED_OPTIMIZER_TYPES",
           "EPILOGUE_ACTS"]

# optimizer op types the fused update supports — elementwise update
# math only (same precondition as the cross-replica sharded update;
# lars/lamb carry param-norm terms and stay per-param), with the state
# slots each folds into the flat StateA/StateB vars
FUSED_OPTIMIZER_TYPES: Dict[str, Tuple[str, ...]] = {
    "sgd": (),
    "momentum": ("Velocity",),
    "adam": ("Moment1", "Moment2"),
    "adamw": ("Moment1", "Moment2"),
}

EPILOGUE_ACTS = ("relu", "gelu", "tanh", "sigmoid")


_TRUTHY = ("1", "true", "yes", "on")


def _env_on(raw) -> bool:
    return bool(raw) and raw.strip().lower() in _TRUTHY


def fused_optimizer_mode() -> Optional[str]:
    """``PADDLE_TPU_FUSED_OPTIMIZER``: unset/off -> None; truthy ->
    ``"auto"`` (flat layout on TPU backends where the pallas kernel
    runs, chain layout elsewhere); ``flat`` / ``chain`` force a
    layout."""
    raw = os.environ.get("PADDLE_TPU_FUSED_OPTIMIZER")
    if not raw:
        return None
    raw = raw.strip().lower()
    if raw in ("flat", "chain"):
        return raw
    return "auto" if raw in _TRUTHY else None


def fused_optimizer_enabled() -> bool:
    return fused_optimizer_mode() is not None


def fused_epilogue_enabled() -> bool:
    return _env_on(os.environ.get("PADDLE_TPU_FUSED_EPILOGUE"))


def maybe_rewrite_single_chip(program, scope) -> None:
    """Executor entry point, called on every run. The knobs are read
    at a program's FIRST run and baked in (the same contract the
    collective-path knobs keep), so the steady-state cost is ONE
    attribute read + a branch — the gate-4 per-run budget. Applies
    the epilogue pass, then the optimizer pass; a program the
    parallel transpiler already rewrote keeps its collective path."""
    state = getattr(program, "_sc_fusion", None)
    if state is not None:
        if state and scope is not None:
            # restart semantics: a startup re-run re-initializes the
            # retired per-param state vars — rebuild the flat state
            # exactly like the sharded update does (shared layout)
            from ..parallel.collectives import resync_sharded_state

            resync_sharded_state(program, scope)
        return
    mode = fused_optimizer_mode()
    fuse_epi = fused_epilogue_enabled()
    n_opt = 0
    if fuse_epi:
        apply_fused_epilogues(program)
    if mode is not None:
        n_opt = apply_fused_optimizer(program, scope, layout=mode)
    try:
        # flat layout re-laid state into flat vars -> later runs must
        # resync them after a startup re-run; chain layout kept the
        # per-param vars, nothing to resync
        program._sc_fusion = bool(
            n_opt and getattr(program, "_sharded_flat_layout", None))
    except AttributeError:
        pass


def _attrs_sig(attrs) -> Tuple:
    return tuple(sorted((k, repr(v)) for k, v in attrs.items()
                        if not k.startswith("_")))


# ---------------------------------------------------------------------------
# fused optimizer update
# ---------------------------------------------------------------------------


@checked_rewrite("fused_optimizer")
def apply_fused_optimizer(program, scope, use_pallas: bool = True,
                          layout: str = "auto") -> int:
    """Rewrite each supported optimizer instance's per-param update ops
    into ONE ``fused_optimizer`` op. Returns the number of instances
    fused.

    ``layout="chain"`` keeps the per-param state vars and the op
    applies the shared update math pair by pair — the zero-overhead
    layout for backends where XLA fuses the chain anyway (re-laying
    state flat was measured ~40% slower per step on CPU from the
    per-step concats). ``layout="flat"`` re-lays optimizer state into
    flat zero-padded vars (padding to the pallas lane tile) so ONE
    pallas streaming kernel updates the whole buffer — the TPU
    layout. ``"auto"`` picks flat exactly when the pallas kernel
    would actually run (TPU backend).

    Grouping key: (op type, hyperparam attrs, LearningRate var, param
    dtype) — one group per optimizer instance per dtype, mirroring the
    sharded-update pass. Spared (kept per-param): params with sparse /
    dynamic-shaped grads, grad dtype != param dtype (the flat concat
    would change promotion semantics), mesh-sharded params,
    single-member groups (nothing to fuse), and groups whose member
    vars are touched by unrelated ops between the group's first and
    last update (the fused op hoists every update to the first
    position — any interleaved reader would see post-update values).
    """
    if getattr(program, "_fused_optimizer_applied", False):
        return 0
    program._fused_optimizer_applied = True
    if getattr(program, "_grads_allreduced", False) or \
            getattr(program, "_sharded_update_n", None) is not None:
        return 0  # dp-transpiled: the collective path owns the update
    if layout == "auto":
        import jax

        layout = "flat" if jax.default_backend() == "tpu" else "chain"
    if layout not in ("flat", "chain"):
        raise ValueError("fused optimizer layout %r" % (layout,))
    from .. import framework
    from ..parallel.collectives import _splice_flat_state, _src_token
    from ..ops.pallas.fused_optimizer import LANE_PAD

    block = program.global_block()
    ops = block.ops
    shard_specs = getattr(program, "_var_shard_specs", None) or {}

    groups: Dict[Tuple, List[int]] = {}
    for i, op in enumerate(ops):
        if op.type not in FUSED_OPTIMIZER_TYPES:
            continue
        if not op.input("Param") or not op.input("Grad"):
            continue
        p = op.input("Param")[0]
        pv = block._find_var_recursive(p)
        if (p in shard_specs or pv is None or not pv.shape
                or not all(isinstance(s, int) and s > 0
                           for s in pv.shape)
                or getattr(pv, "type", "lod_tensor") != "lod_tensor"):
            continue
        g = op.input("Grad")[0]
        gv = block._find_var_recursive(g)
        if gv is not None and getattr(gv, "type", "") == "selected_rows":
            continue  # sparse grads keep the row-wise per-param kernel
        if gv is not None and getattr(gv, "dtype", None) and \
                str(gv.dtype) != str(pv.dtype):
            continue  # mixed-dtype pair: concat would change promotion
        key = (op.type, _attrs_sig(op.attrs),
               op.input("LearningRate")[0], str(pv.dtype))
        groups.setdefault(key, []).append(i)

    n_groups = 0
    removed = set()
    replace_at: Dict[int, object] = {}
    for key, idxs in sorted(groups.items(), key=lambda kv: kv[1][0]):
        if len(idxs) < 2:
            continue  # a single update op is already one launch
        op_type, _, lr_name, dtype = key
        member_ops = [ops[i] for i in idxs]
        # the fused op lands at the FIRST member's position, so every
        # member's update happens there; an unrelated op interleaved
        # between the members that touches a member's param/state (or
        # rewrites the LR) would observe different values — spare the
        # whole group
        member_set = set(idxs)
        grads_set = {mop.input("Grad")[0] for mop in member_ops}
        guarded = {lr_name}
        for mop in member_ops:
            guarded.update(n for n in mop.input_arg_names if n)
            guarded.update(n for n in mop.output_arg_names if n)
        # reading a member's GRAD between the members is harmless (the
        # update never rewrites it); reading param/state is not, and
        # WRITING anything a member touches (grads included) is not
        read_guard = guarded - grads_set

        def _clashes(j):
            if j in member_set:
                return False
            op_j = ops[j]
            return any(n in read_guard for n in op_j.input_arg_names) \
                or any(n in guarded for n in op_j.output_arg_names)

        if any(_clashes(j) for j in range(idxs[0] + 1, idxs[-1])):
            continue

        params = [op.input("Param")[0] for op in member_ops]
        grads = [op.input("Grad")[0] for op in member_ops]
        sizes = [int(np.prod(block.var(p).shape)) for p in params]
        total = sum(sizes)
        padded = -(-total // LANE_PAD) * LANE_PAD
        n_groups += 1
        sig = hashlib.sha1(("%s|%s" % (op_type, ",".join(
            "%s:%d" % t for t in zip(params, sizes)))).encode())
        gtag = sig.hexdigest()[:8]

        inputs = {"Param": params, "Grad": grads,
                  "LearningRate": [lr_name]}
        outputs = {"ParamOut": params}
        for slot_key, slot in zip(("StateA", "StateB"),
                                  FUSED_OPTIMIZER_TYPES[op_type]):
            state_names = [op.input(slot)[0] for op in member_ops]
            if layout == "chain":
                # per-param accumulators stay exactly where they are
                inputs[slot_key] = state_names
                outputs[slot_key + "Out"] = state_names
                continue
            flat_name = "fused_opt_%s.%s" % (gtag, slot.lower())
            fv = block.create_var(name=flat_name, shape=(padded,),
                                  dtype=dtype, persistable=True)
            fv.stop_gradient = True
            flat = _splice_flat_state(block, scope, state_names,
                                      total, padded, dtype, slot)
            for sn in state_names:
                block.var(sn).persistable = False
            scope.var(flat_name).get_tensor()._array = flat
            # the sharded update's restart-resync machinery is layout-
            # agnostic — register the flat var under the same program
            # attrs so resync_sharded_state rebuilds it after a
            # startup re-run
            for attr in ("_sharded_flat_layout", "_sharded_src_tokens"):
                if getattr(program, attr, None) is None:
                    setattr(program, attr, {})
            program._sharded_flat_layout[flat_name] = (
                tuple(state_names), total, padded, dtype, slot)
            program._sharded_src_tokens[flat_name] = tuple(
                _src_token(scope, sn) for sn in state_names)
            inputs[slot_key] = [flat_name]
            outputs[slot_key + "Out"] = [flat_name]
        for scalar in ("Beta1Pow", "Beta2Pow"):
            names = [op.input(scalar) for op in member_ops]
            if all(n for n in names):
                inputs[scalar] = [n[0] for n in names]
                outputs[scalar + "Out"] = [n[0] for n in names]

        attrs = dict(member_ops[0].attrs)
        attrs.update({"op_type": op_type, "layout": layout,
                      "padded_size": int(padded),
                      "use_pallas": bool(use_pallas)})
        fo = framework.Operator(block, "fused_optimizer", inputs,
                                outputs, attrs)
        fo._id = program._next_op_id()
        replace_at[idxs[0]] = fo
        removed.update(idxs)

    if not n_groups:
        return 0
    new_ops = []
    for i, op in enumerate(ops):
        if i in replace_at:
            new_ops.append(replace_at[i])
        if i not in removed:
            new_ops.append(op)
    block.ops = new_ops
    program._fused_optimizer_groups = n_groups
    from ..parallel.transpiler import _bump_version

    _bump_version(program)
    from .. import observability as _obs

    _obs.inc("fusion.optimizer_groups", n_groups)
    return n_groups


# ---------------------------------------------------------------------------
# fused epilogues
# ---------------------------------------------------------------------------


def _single_writer_names(ops) -> set:
    counts: Dict[str, int] = {}
    for op in ops:
        for n in op.output_arg_names:
            if n:
                counts[n] = counts.get(n, 0) + 1
    return {n for n, c in counts.items() if c == 1}


def _first_backward_index(ops) -> int:
    from .registry import GRAD_SUFFIX

    for i, op in enumerate(ops):
        if "_fwd_op_id" in op.attrs or any(
                GRAD_SUFFIX in n for n in op.output_arg_names if n):
            return i
    return len(ops)


@checked_rewrite("fused_epilogue")
def apply_fused_epilogues(program) -> int:
    """Collapse adjacent forward epilogue chains into the fused ops:

    - ``elementwise_add -> act`` (act in EPILOGUE_ACTS), optionally
      ``-> dropout``  =>  ``fused_bias_act``
    - ``elementwise_add -> layer_norm``  =>  ``fused_residual_layer_norm``

    Only SINGLE-WRITER intermediates fuse (a rebound name means the
    chain is not a private dataflow edge), only in the forward region
    (backward ops recompute through their own wiring), and every
    intermediate name is re-emitted by the fused op — pre-built grad
    ops keep reading the values they were built against. Returns the
    number of chains fused."""
    if getattr(program, "_fused_epilogue_applied", False):
        return 0
    program._fused_epilogue_applied = True
    from .. import framework

    block = program.global_block()
    ops = block.ops
    single = _single_writer_names(ops)
    bwd_start = _first_backward_index(ops)

    fused: List[Tuple[int, int, object]] = []  # (start, end_excl, op)
    i = 0
    while i < bwd_start - 1:
        opA = ops[i]
        if opA.type != "elementwise_add" or len(opA.output("Out")) != 1:
            i += 1
            continue
        a_out = opA.output("Out")[0]
        if a_out not in single:
            i += 1
            continue
        opB = ops[i + 1]
        end = None
        new_op = None
        if opB.type in EPILOGUE_ACTS and opB.input("X") == [a_out] \
                and len(opB.output("Out")) == 1:
            b_out = opB.output("Out")[0]
            if b_out not in single:
                i += 1
                continue
            attrs = {"act": opB.type,
                     "axis": opA.attrs.get("axis", -1),
                     "approximate": bool(opB.attrs.get("approximate",
                                                       False)),
                     "alpha": opB.attrs.get("alpha", 0.02),
                     "dropout_prob": -1.0}
            outputs = {"Out": [b_out], "AddOut": [a_out]}
            end = i + 2
            opC = ops[i + 2] if i + 2 < bwd_start else None
            if (opC is not None and opC.type == "dropout"
                    and opC.input("X") == [b_out]
                    and not opC.input("Seed")
                    and len(opC.output("Out")) == 1
                    and opC.output("Out")[0] in single):
                attrs.update({
                    "dropout_prob": float(
                        opC.attrs.get("dropout_prob", 0.5)),
                    "is_test": bool(opC.attrs.get("is_test", False)),
                    "fix_seed": bool(opC.attrs.get("fix_seed", False)),
                    "seed": int(opC.attrs.get("seed", 0) or 0),
                    "dropout_implementation": opC.attrs.get(
                        "dropout_implementation",
                        "downgrade_in_infer"),
                    # the fused op draws from the ORIGINAL dropout
                    # op's RNG stream, so masks match the pre-built
                    # dropout_grad ops bit-for-bit. NOT spelled
                    # _fwd_op_id: that attr marks BACKWARD ops
                    # (classify_ops keys the phase boundary on it —
                    # carrying it here would flip the rest of the
                    # forward region to "backward" in every profile)
                    "_rng_op_id": opC._id or 0,
                })
                outputs = {"Out": opC.output("Out"),
                           "AddOut": [a_out], "ActOut": [b_out]}
                if opC.output("Mask"):
                    outputs["Mask"] = opC.output("Mask")
                end = i + 3
            new_op = framework.Operator(
                block, "fused_bias_act",
                {"X": opA.input("X"), "Y": opA.input("Y")},
                outputs, attrs)
        elif opB.type == "layer_norm" and opB.input("X") == [a_out] \
                and len(opB.output("Y")) == 1 \
                and opB.output("Y")[0] in single:
            outputs = {"Out": opB.output("Y"), "AddOut": [a_out],
                       "Mean": opB.output("Mean"),
                       "Variance": opB.output("Variance")}
            new_op = framework.Operator(
                block, "fused_residual_layer_norm",
                {"X": opA.input("X"), "Y": opA.input("Y"),
                 "Scale": opB.input("Scale"),
                 "Bias": opB.input("Bias")},
                outputs,
                {"axis": opA.attrs.get("axis", -1),
                 "epsilon": opB.attrs.get("epsilon", 1e-5),
                 "begin_norm_axis": opB.attrs.get("begin_norm_axis",
                                                  1)})
            end = i + 2
        if new_op is None:
            i += 1
            continue
        new_op._id = program._next_op_id()
        fused.append((i, end, new_op))
        i = end

    if not fused:
        return 0
    new_ops: List = []
    k = 0
    for start, end, op in fused:
        new_ops.extend(ops[k:start])
        new_ops.append(op)
        k = end
    new_ops.extend(ops[k:])
    block.ops = new_ops
    from ..parallel.transpiler import _bump_version

    _bump_version(program)
    from .. import observability as _obs

    _obs.inc("fusion.epilogue_chains", len(fused))
    return len(fused)
