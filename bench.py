"""Benchmark driver — prints ONE JSON line on stdout.

Protocol (BASELINE.md): synthetic data, warm-up excluded, timed steps run
fetch-free (results stay on device; a single fetch after the loop syncs)
so host<->device transfer latency does not pollute device throughput.

Headline metric: ResNet-50 ImageNet images/sec on the one available chip
(BASELINE.json north-star config 2). The reference publishes no in-repo
numbers; ``vs_baseline`` is computed against the fluid-era CUDA per-chip
anchor of 360 images/sec (ResNet-50 fp32 on the V100 generation the
reference targets) — the north star asks for >=90% of CUDA per-chip.
Secondary metrics (MNIST MLP steps/sec, MFU estimate) ride in "extras".
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

CUDA_PER_CHIP_ANCHOR_IMG_S = 360.0  # ResNet-50 fp32 per-chip, V100 era


def _build_resnet50(batch, use_bf16=False):
    import paddle_tpu as fluid
    from paddle_tpu import models

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data(name="img", shape=[batch, 3, 224, 224],
                         dtype="float32")
        label = fluid.data(name="label", shape=[batch, 1], dtype="int64")
        pred = models.resnet50(img)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        opt = fluid.optimizer.MomentumOptimizer(learning_rate=0.1,
                                                momentum=0.9)
        if use_bf16:
            try:
                from paddle_tpu.contrib import mixed_precision as mp
            except ImportError:
                use_bf16 = False  # AMP not built yet — measure f32
            else:
                opt = mp.decorate(opt)  # bf16 defaults: no loss scaling
        opt.minimize(loss)
    return main, startup, loss, use_bf16


def _build_mnist_mlp(batch):
    import paddle_tpu as fluid
    from paddle_tpu import models

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[batch, 784], dtype="float32")
        label = fluid.data(name="label", shape=[batch, 1], dtype="int64")
        pred = models.mlp(x)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
    return main, startup, loss


def _time_steps(exe, main, feed, loss, warmup=3, iters=20):
    """Timed steps with device-side sync per step.

    Fetches stay on device (``return_numpy=False``) so only ONE program
    variant compiles and no per-step device->host transfer pollutes the
    measurement (this host's transfer path has a large fixed cost); the
    single untimed d2h at the end reads the final loss for a sanity check.
    """
    import jax

    out = None
    for _ in range(warmup):
        (out,) = exe.run(main, feed=feed, fetch_list=[loss],
                         return_numpy=False)
    jax.block_until_ready(out.array)
    # BASELINE.md protocol: median of 5 windows (the shared remote device
    # pool this runs on has high run-to-run variance).
    windows = []
    per_window = max(1, iters // 5)
    for _ in range(5):
        t0 = time.time()
        for _ in range(per_window):
            (out,) = exe.run(main, feed=feed, fetch_list=[loss],
                             return_numpy=False)
        jax.block_until_ready(out.array)  # drain the async queue
        windows.append((time.time() - t0) / per_window)
    dt = float(np.median(windows))
    return dt, float(np.asarray(out.array).ravel()[0])


def bench_resnet50(batch=64, iters=20, use_bf16=False):
    import paddle_tpu as fluid

    main, startup, loss, use_bf16 = _build_resnet50(batch,
                                                    use_bf16=use_bf16)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {
        "img": rng.rand(batch, 3, 224, 224).astype("float32"),
        "label": rng.randint(0, 1000, (batch, 1)).astype("int64"),
    }
    dt, final_loss = _time_steps(exe, main, feed, loss, iters=iters)
    if not np.isfinite(final_loss):
        raise RuntimeError("resnet50 diverged: loss=%r" % final_loss)
    return {"images_per_sec": batch / dt, "step_ms": dt * 1e3,
            "batch": batch, "loss": final_loss, "bf16": use_bf16}


def bench_mnist_mlp(batch=512, iters=30):
    import paddle_tpu as fluid

    main, startup, loss = _build_mnist_mlp(batch)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {
        "x": rng.rand(batch, 784).astype("float32"),
        "label": rng.randint(0, 10, (batch, 1)).astype("int64"),
    }
    dt, final_loss = _time_steps(exe, main, feed, loss, iters=iters)
    if not np.isfinite(final_loss):
        raise RuntimeError("mnist mlp diverged: loss=%r" % final_loss)
    return {"steps_per_sec": 1.0 / dt, "examples_per_sec": batch / dt,
            "step_ms": dt * 1e3, "batch": batch, "loss": final_loss}


def _run_one(name, use_bf16):
    """Child-process entry: bench one model, print its JSON."""
    if name == "mnist_mlp":
        print(json.dumps(bench_mnist_mlp()))
    elif name == "resnet50":
        rn = bench_resnet50(use_bf16=use_bf16)
        # ResNet-50 train step ~= 3x fwd FLOPs; fwd ~= 4.1 GFLOP/img @224
        flops_per_img = 3 * 4.1e9
        peak = 197e12 if rn["bf16"] else 98.5e12  # v5e MXU peak bf16/fp32
        rn["mfu_est"] = rn["images_per_sec"] * flops_per_img / peak
        print(json.dumps(rn))
    else:
        raise SystemExit("unknown model %r" % name)


def _bench_subprocess(name, use_bf16):
    """Each model benches in its own process: the remote device runtime
    degrades badly when multiple compiled programs share a process (its
    executable cache thrashes), which would corrupt the measurement."""
    import subprocess

    args = [sys.executable, __file__, "--model=" + name]
    if not use_bf16:
        args.append("--no-bf16")
    proc = subprocess.run(args, capture_output=True, text=True, timeout=560)
    if proc.returncode != 0:
        raise RuntimeError("bench %s failed: %s" % (name,
                                                    proc.stderr[-2000:]))
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main():
    use_bf16 = "--no-bf16" not in sys.argv
    for a in sys.argv[1:]:
        if a.startswith("--model="):
            _run_one(a.split("=", 1)[1], use_bf16)
            return

    extras = {}
    t_start = time.time()
    try:
        extras["mnist_mlp"] = _bench_subprocess("mnist_mlp", use_bf16)
    except Exception as e:  # keep the headline alive
        extras["mnist_mlp_error"] = repr(e)
        print("mnist mlp bench failed: %r" % e, file=sys.stderr)
    try:
        rn = _bench_subprocess("resnet50", use_bf16)
    except Exception as e:
        if use_bf16:
            print("bf16 resnet bench failed (%r); retrying f32" % e,
                  file=sys.stderr)
            rn = _bench_subprocess("resnet50", False)
        else:
            raise
    extras["resnet50"] = rn
    extras["wall_s"] = time.time() - t_start
    try:
        import jax

        extras["device"] = str(jax.devices()[0])
    except Exception:
        pass
    result = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(rn["images_per_sec"], 2),
        "unit": "images/sec",
        "vs_baseline": round(rn["images_per_sec"] / CUDA_PER_CHIP_ANCHOR_IMG_S,
                             4),
        "extras": extras,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
