"""Paged KV-cache manager: fixed-size blocks over ONE preallocated arena.

Why paged: an autoregressive batch is ragged and CHURNS — sequences
join, grow one token per step, and leave at unpredictable lengths.
Per-sequence contiguous buffers either over-reserve (max_len for
everyone: memory for the p99 sequence paid by the p50) or re-allocate
and copy as sequences grow. Fixed-size blocks over one arena make both
problems go away: allocation is popping a free-list entry, growth is at
most one new block per token step, and a leaving sequence returns its
blocks for IMMEDIATE reuse by the next admit — which is what lets the
decode engine hold the batch full (the continuous-batching win).

Quantized storage (opt-in, ``dtype="bf16"|"int8"``): the KV cache is
the decode replica's memory bill, so halving/quartering it doubles/
quadruples the sequences a replica can hold. int8 uses EQuARX-style
SHARED scales — one scale per (layer, block, head, k|v), so a block's
codes dequantize with one multiply and the scale rides next to the
block, not next to every value. Appends keep the shared-scale invariant
by requantizing a block in place when a new token raises its amax
(a block is ``block_tokens`` rows — the rescale is a few KB, and it
happens at most once per amax increase). bf16 stores the top 16 bits
of the f32 pattern (round-to-nearest-even), the same transform the
collectives' bf16 wire format uses.

Accounting is strict and self-checking: every block is either on the
free list or owned by exactly one sequence; ``check()`` verifies the
partition and is asserted by the churn tests after every
join/leave/evict/re-admit cycle — a leaked block in a long-running
replica is a slow OOM with no crash to bisect.

Thread contract: the decode engine mutates the cache ONLY from its
step thread; readers of ``stats()``/``occupancy()`` (health endpoint,
metrics) take the same lock the mutators do.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["KVCacheConfig", "PagedKVCache", "KVCacheFull"]


class KVCacheFull(RuntimeError):
    """No free block: the arena is exhausted. The scheduler's move,
    not the cache's — preempt a lower-priority sequence or defer the
    admit; the cache itself never evicts silently."""


class KVCacheConfig:
    """Arena geometry + storage dtype.

    ``num_blocks * block_tokens`` is the total token capacity shared
    by every resident sequence; ``dtype`` is the STORAGE format
    (compute is always float32): ``f32``, ``bf16`` (uint16 bit
    patterns, 2x capacity per byte), or ``int8`` (shared-scale codes,
    4x)."""

    DTYPES = ("f32", "bf16", "int8")

    def __init__(self, num_blocks: int = 64, block_tokens: int = 16,
                 num_layers: int = 1, num_heads: int = 2,
                 head_dim: int = 8, dtype: str = "f32"):
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        if min(self.num_blocks, self.block_tokens, self.num_layers,
               self.num_heads, self.head_dim) < 1:
            raise ValueError("all KVCacheConfig dims must be >= 1")
        if dtype not in self.DTYPES:
            raise ValueError("dtype must be one of %s, got %r"
                             % (self.DTYPES, dtype))
        self.dtype = dtype

    @property
    def capacity_tokens(self) -> int:
        return self.num_blocks * self.block_tokens

    def arena_bytes(self) -> int:
        """Total K+V arena bytes (scales excluded — they are noise)."""
        per_val = {"f32": 4, "bf16": 2, "int8": 1}[self.dtype]
        return (2 * self.num_layers * self.num_blocks
                * self.block_tokens * self.num_heads * self.head_dim
                * per_val)


class _Seq:
    __slots__ = ("blocks", "length")

    def __init__(self):
        self.blocks: List[int] = []
        self.length = 0


def _to_bf16_bits(x: np.ndarray) -> np.ndarray:
    """f32 -> bf16 bit pattern (uint16), round-to-nearest-even."""
    bits = x.astype(np.float32).view(np.uint32)
    rounded = bits + 0x7FFF + ((bits >> 16) & 1)
    return (rounded >> 16).astype(np.uint16)


def _from_bf16_bits(b: np.ndarray) -> np.ndarray:
    return (b.astype(np.uint32) << 16).view(np.float32)


class PagedKVCache:
    """The arena + block tables. K and V arenas are
    ``[num_layers, num_blocks, block_tokens, num_heads, head_dim]`` in
    the storage dtype; int8 scales are
    ``[num_layers, num_blocks, num_heads]`` per side."""

    def __init__(self, config: Optional[KVCacheConfig] = None):
        self.config = c = config or KVCacheConfig()
        storage = {"f32": np.float32, "bf16": np.uint16,
                   "int8": np.int8}[c.dtype]
        shape = (c.num_layers, c.num_blocks, c.block_tokens,
                 c.num_heads, c.head_dim)
        self.k_arena = np.zeros(shape, storage)
        self.v_arena = np.zeros(shape, storage)
        if c.dtype == "int8":
            sshape = (c.num_layers, c.num_blocks, c.num_heads)
            self.k_scale = np.zeros(sshape, np.float32)
            self.v_scale = np.zeros(sshape, np.float32)
        else:
            self.k_scale = self.v_scale = None
        self._free: List[int] = list(range(c.num_blocks - 1, -1, -1))
        self._seqs: Dict[str, _Seq] = {}
        self._lock = threading.Lock()
        self.allocs = 0          # lifetime block allocations
        self.frees = 0           # lifetime block frees

    # -- accounting ---------------------------------------------------------

    def register(self, seq_id: str) -> None:
        with self._lock:
            if seq_id in self._seqs:
                raise ValueError("sequence %r already registered" % seq_id)
            self._seqs[seq_id] = _Seq()

    def release(self, seq_id: str) -> int:
        """Free every block the sequence owns; returns how many."""
        with self._lock:
            seq = self._seqs.pop(seq_id, None)
            if seq is None:
                return 0
            self._free.extend(reversed(seq.blocks))
            self.frees += len(seq.blocks)
            return len(seq.blocks)

    def has(self, seq_id: str) -> bool:
        with self._lock:
            return seq_id in self._seqs

    def seq_len(self, seq_id: str) -> int:
        with self._lock:
            return self._seqs[seq_id].length

    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def blocks_needed(self, seq_id: Optional[str], n_tokens: int) -> int:
        """New blocks appending ``n_tokens`` to ``seq_id`` would take
        (``seq_id=None`` -> a fresh sequence)."""
        with self._lock:
            used = self._seqs[seq_id].length if seq_id in self._seqs else 0
        bt = self.config.block_tokens
        return -(-(used + n_tokens) // bt) - (-(-used // bt))

    def can_fit(self, seq_id: Optional[str], n_tokens: int) -> bool:
        return self.blocks_needed(seq_id, n_tokens) <= self.free_blocks()

    def occupancy(self) -> float:
        with self._lock:
            return 1.0 - len(self._free) / float(self.config.num_blocks)

    def stats(self) -> Dict:
        with self._lock:
            used = self.config.num_blocks - len(self._free)
            return {
                "num_blocks": self.config.num_blocks,
                "used_blocks": used,
                "free_blocks": len(self._free),
                "occupancy": used / float(self.config.num_blocks),
                "sequences": len(self._seqs),
                "resident_tokens": sum(s.length
                                       for s in self._seqs.values()),
                "block_allocs": self.allocs,
                "block_frees": self.frees,
                "dtype": self.config.dtype,
                "arena_bytes": self.config.arena_bytes(),
            }

    def check(self) -> None:
        """Invariant audit: free + owned partitions the arena exactly
        (no leak, no double-own), and every length fits its blocks."""
        with self._lock:
            owned = [b for s in self._seqs.values() for b in s.blocks]
            all_ids = sorted(owned + self._free)
            if all_ids != list(range(self.config.num_blocks)):
                missing = set(range(self.config.num_blocks)) - set(all_ids)
                dupes = {b for b in all_ids if all_ids.count(b) > 1}
                raise AssertionError(
                    "block accounting broken: %d owned + %d free != %d "
                    "(leaked=%s double-owned=%s)"
                    % (len(owned), len(self._free),
                       self.config.num_blocks, sorted(missing)[:8],
                       sorted(dupes)[:8]))
            bt = self.config.block_tokens
            for sid, s in self._seqs.items():
                if len(s.blocks) != -(-s.length // bt) and not (
                        s.length == 0 and not s.blocks):
                    raise AssertionError(
                        "seq %r: length %d needs %d block(s), owns %d"
                        % (sid, s.length, -(-s.length // bt),
                           len(s.blocks)))

    # -- writes -------------------------------------------------------------

    def reserve(self, seq_id: str, n_tokens: int) -> int:
        """Allocate blocks for ``n_tokens`` new positions and advance
        the sequence length; returns the first new position. Atomic:
        raises ``KVCacheFull`` with NOTHING changed when the free list
        cannot cover the whole reservation.

        Reserve-then-write is the decode step's shape: the new token's
        K/V rows are produced LAYER BY LAYER (layer l's row depends on
        layer l-1's attention output), so slots must exist before the
        first layer computes. Between reserve and the last
        ``write_rows`` the tail positions hold stale values — callers
        mask them with an explicit attention length, never the raw
        ``block_table`` lengths, until the write completes."""
        c = self.config
        if n_tokens < 1:
            raise ValueError("reserve needs n_tokens >= 1")
        with self._lock:
            seq = self._seqs.get(seq_id)
            if seq is None:
                raise KeyError("sequence %r not registered" % seq_id)
            need = (-(-(seq.length + n_tokens) // c.block_tokens)
                    - len(seq.blocks))
            if need > len(self._free):
                raise KVCacheFull(
                    "reserving %d token(s) for %r needs %d block(s), "
                    "%d free" % (n_tokens, seq_id, need,
                                 len(self._free)))
            for _ in range(need):
                seq.blocks.append(self._free.pop())
                self.allocs += 1
            start = seq.length
            seq.length += n_tokens
            return start

    def write_rows(self, seq_id: str, layer: int, start: int,
                   k_rows: np.ndarray, v_rows: np.ndarray) -> None:
        """Store ``[T, H, D]`` float32 K/V rows for ONE layer at
        positions ``start .. start+T-1`` (already reserved)."""
        c = self.config
        k_rows = np.asarray(k_rows, np.float32)
        v_rows = np.asarray(v_rows, np.float32)
        T = k_rows.shape[0]
        with self._lock:
            seq = self._seqs.get(seq_id)
            if seq is None:
                raise KeyError("sequence %r not registered" % seq_id)
            if start + T > seq.length:
                raise ValueError(
                    "write_rows [%d, %d) past reserved length %d of %r"
                    % (start, start + T, seq.length, seq_id))
            for t in range(T):
                blk = seq.blocks[(start + t) // c.block_tokens]
                off = (start + t) % c.block_tokens
                self._write(self.k_arena, self.k_scale, layer, blk,
                            off, k_rows[t])
                self._write(self.v_arena, self.v_scale, layer, blk,
                            off, v_rows[t])

    def append(self, seq_id: str, k: np.ndarray, v: np.ndarray) -> None:
        """Append ``T`` tokens' K/V (``[T, num_layers, num_heads,
        head_dim]`` float32) across all layers at once — the
        whole-rows convenience over reserve + write_rows (tests, and
        any caller that has every layer's rows in hand). Raises
        ``KVCacheFull`` with NOTHING written when the free list cannot
        cover the append."""
        c = self.config
        k = np.asarray(k, np.float32)
        v = np.asarray(v, np.float32)
        want = (k.shape[0], c.num_layers, c.num_heads, c.head_dim)
        if k.shape != want or v.shape != want:
            raise ValueError("append expects k/v %s, got %s / %s"
                             % (want, k.shape, v.shape))
        start = self.reserve(seq_id, k.shape[0])
        for layer in range(c.num_layers):
            self.write_rows(seq_id, layer, start, k[:, layer],
                            v[:, layer])

    def _write(self, arena, scales, layer, blk, off, row) -> None:
        """Store one token's [H, D] float32 row in the arena's dtype.
        int8: per-(block, head) shared scale; a row that raises the
        block amax requantizes the block's existing codes in place so
        every code in the block shares ONE scale."""
        d = self.config.dtype
        if d == "f32":
            arena[layer, blk, off] = row
            return
        if d == "bf16":
            arena[layer, blk, off] = _to_bf16_bits(row)
            return
        amax = np.abs(row).max(axis=1)                    # [H]
        cur = scales[layer, blk]                          # [H]
        new_scale = np.maximum(cur, amax / 127.0)
        grew = new_scale > cur * (1.0 + 1e-12)
        if grew.any() and off > 0:
            for h in np.nonzero(grew)[0]:
                if cur[h] > 0:
                    vals = arena[layer, blk, :off, h].astype(
                        np.float32) * cur[h]
                    arena[layer, blk, :off, h] = np.clip(
                        np.rint(vals / new_scale[h]), -127, 127
                    ).astype(np.int8)
        scales[layer, blk] = new_scale
        safe = np.where(new_scale > 0, new_scale, 1.0)
        arena[layer, blk, off] = np.clip(
            np.rint(row / safe[:, None]), -127, 127).astype(np.int8)

    # -- reads --------------------------------------------------------------

    def views(self, layer: int) -> Tuple[np.ndarray, np.ndarray,
                                         object, object]:
        """The attention kernel's operands for one layer:
        ``(k_arena, v_arena, k_scales, v_scales)`` where the scales
        slot is None (f32), ``"bf16"`` (bit patterns), or the
        per-(block, head) scale array (int8) — exactly the contract of
        ``ops.pallas.paged_attention``."""
        d = self.config.dtype
        if d == "f32":
            return self.k_arena[layer], self.v_arena[layer], None, None
        if d == "bf16":
            return (self.k_arena[layer], self.v_arena[layer],
                    "bf16", "bf16")
        return (self.k_arena[layer], self.v_arena[layer],
                self.k_scale[layer], self.v_scale[layer])

    def block_table(self, seq_ids) -> Tuple[np.ndarray, np.ndarray]:
        """``([B, max_blocks] int32 table (-1 padded), [B] int32
        lengths)`` over the given sequences — the kernel's ragged-batch
        rectangle. Unknown ids get an empty row (len 0), which the
        kernel masks to zeros; that is how padded batch slots ride."""
        with self._lock:
            rows = [self._seqs[s].blocks if s in self._seqs else []
                    for s in seq_ids]
            lens = [self._seqs[s].length if s in self._seqs else 0
                    for s in seq_ids]
        width = max(1, max((len(r) for r in rows), default=1))
        table = np.full((len(rows), width), -1, np.int32)
        for i, r in enumerate(rows):
            table[i, :len(r)] = r
        return table, np.asarray(lens, np.int32)

    def gather(self, seq_id: str, layer: int
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Dense float32 ``([T, H, D] k, [T, H, D] v)`` for one
        sequence/layer — the prefill path's operand (causal attention
        over the whole prefix) and the tests' oracle."""
        c = self.config
        with self._lock:
            seq = self._seqs[seq_id]
            blocks = list(seq.blocks)
            n = seq.length
        if n == 0:
            z = np.zeros((0, c.num_heads, c.head_dim), np.float32)
            return z, z.copy()
        ids = np.asarray(blocks, np.int64)
        tok_blocks = np.repeat(ids, c.block_tokens)[:n]
        out = []
        for arena, scales in ((self.k_arena, self.k_scale),
                              (self.v_arena, self.v_scale)):
            flat = arena[layer, ids].reshape(
                -1, c.num_heads, c.head_dim)[:n]
            if c.dtype == "f32":
                out.append(flat.astype(np.float32))
            elif c.dtype == "bf16":
                out.append(_from_bf16_bits(flat))
            else:
                s = scales[layer][tok_blocks]             # [T, H]
                out.append(flat.astype(np.float32) * s[:, :, None])
        return out[0], out[1]
