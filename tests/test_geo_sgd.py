"""Geo-SGD transpiler: program-rewrite asserts (reference
test_dist_transpiler style) + an end-to-end delta push through the
emulated PS runtime.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.ops.distributed_ops import (
    reset_emulated_servers, reset_geo_counters)
from paddle_tpu.transpiler import (
    DistributeTranspilerConfig, GeoSgdTranspiler, memory_optimize,
    release_memory)


def _build():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[4, 3], dtype="float32")
        y = fluid.data(name="y", shape=[4, 1], dtype="float32")
        pred = fluid.layers.fc(x, size=1,
                               param_attr=fluid.ParamAttr(name="w"),
                               bias_attr=False)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return prog, startup, loss


def test_geo_transpile_op_sequence():
    prog, startup, _ = _build()
    cfg = DistributeTranspilerConfig()
    cfg.geo_sgd_need_push_nums = 2
    t = GeoSgdTranspiler(cfg)
    t.transpile(trainer_id=0, program=prog, startup_program=startup,
                pservers="ep0", trainers=1)
    types = [op.type for op in prog.global_block().ops]
    # optimizer stays local (unlike sync PS), delta push appended
    assert "sgd" in types
    assert types[-1] == "geo_send"
    assert "w.geo.snapshot" in prog.global_block().vars
    # startup initializes snapshot = freshly-initialized param
    s_ops = [(op.type, op.output("Out")) for op in
             startup.global_block().ops]
    assert ("assign", ["w.geo.snapshot"]) in s_ops

    server = t.get_pserver_program("ep0")
    stypes = [op.type for op in server.global_block().ops]
    assert stypes == ["listen_and_serv"]
    # the delta-apply sub-blocks hang off listen_and_serv
    sub_types = [op.type for b in server.blocks[1:] for op in b.ops]
    assert "elementwise_add" in sub_types


def test_geo_delta_sync_end_to_end():
    reset_emulated_servers()
    reset_geo_counters()
    prog, startup, loss = _build()
    cfg = DistributeTranspilerConfig()
    cfg.geo_sgd_need_push_nums = 2
    t = GeoSgdTranspiler(cfg)
    t.transpile(trainer_id=0, program=prog, startup_program=startup,
                pservers="ep0", trainers=1)
    server_prog = t.get_pserver_program("ep0")

    trainer_scope = fluid.Scope()
    server_scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())

    rng = np.random.RandomState(0)
    W = rng.randn(3, 1).astype("float32")

    with fluid.scope_guard(server_scope):
        # server starts from the same init as the trainer (zeros here)
        server_scope.var("w").get_tensor().set(
            np.zeros((3, 1), "float32"))
        exe.run(server_prog)  # registers listen_and_serv endpoint

    with fluid.scope_guard(trainer_scope):
        exe.run(startup)
        trainer_scope.var("w").get_tensor().set(
            np.zeros((3, 1), "float32"))
        trainer_scope.var("w.geo.snapshot").get_tensor().set(
            np.zeros((3, 1), "float32"))
        for step in range(4):
            xb = rng.randn(4, 3).astype("float32")
            exe.run(prog, feed={"x": xb, "y": xb @ W},
                    fetch_list=[loss])
        w_trainer = np.asarray(
            trainer_scope.find_var("w").raw().array)
        snap = np.asarray(
            trainer_scope.find_var("w.geo.snapshot").raw().array)

    w_server = np.asarray(server_scope.find_var("w").raw().array)
    # 4 steps, push every 2 -> pushes at steps 2 and 4 carrying
    # (w2 - 0) and (w4 - w2); the server sum telescopes to w4, and the
    # snapshot equals the trainer weights at the last push
    np.testing.assert_allclose(snap, w_trainer, rtol=1e-6)
    np.testing.assert_allclose(w_server, w_trainer, rtol=1e-6)


def test_memory_optimize_shims():
    prog, _, _ = _build()
    memory_optimize(prog)
    release_memory(prog)
