"""Loss ops.

Parity: /root/reference/paddle/fluid/operators/{cross_entropy_op.cc,
softmax_with_cross_entropy_op.cc, sigmoid_cross_entropy_with_logits_op.cc,
squared_l2_distance_op.cc, log_loss_op.cc, huber_loss_op.cc, smooth_l1_loss,
bce_loss, kldiv_loss, hinge_loss, margin_rank_loss, mse_loss (via ops),
nce (sampled softmax, simplified dense)}.

softmax_with_cross_entropy keeps the fused-stable formulation (the
reference's CUDA kernel does the same log-sum-exp trick); XLA fuses it
with the surrounding matmul epilogue.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import In, Out, register_op


def _label_xent(logp, label, ignore_index=-100, soft=False):
    if soft:
        return -jnp.sum(label * logp, axis=-1, keepdims=True)
    lbl = label
    if lbl.ndim == logp.ndim and lbl.shape[-1] == 1:
        lbl = lbl.squeeze(-1)
    picked = jnp.take_along_axis(logp, lbl[..., None].astype(jnp.int32), axis=-1)
    loss = -picked
    mask = (lbl != ignore_index)[..., None]
    return jnp.where(mask, loss, jnp.zeros_like(loss))


@register_op(
    "cross_entropy",
    inputs=[In("X"), In("Label", no_grad=True)],
    outputs=[Out("Y")],
    attrs={"soft_label": False, "ignore_index": -100},
)
def _cross_entropy(ins, attrs):
    x, label = ins["X"], ins["Label"]
    logp = jnp.log(jnp.clip(x, 1e-20, 1.0))
    y = _label_xent(logp, label, attrs.get("ignore_index", -100),
                    attrs.get("soft_label", False))
    return {"Y": y}


@register_op(
    "cross_entropy2",
    inputs=[In("X"), In("Label", no_grad=True)],
    outputs=[Out("Y"), Out("XShape", no_grad=True), Out("MatchX", no_grad=True)],
    attrs={"ignore_index": -100},
)
def _cross_entropy2(ins, attrs):
    x, label = ins["X"], ins["Label"]
    logp = jnp.log(jnp.clip(x, 1e-20, 1.0))
    y = _label_xent(logp, label, attrs.get("ignore_index", -100), False)
    lbl = label.squeeze(-1) if label.shape[-1] == 1 else label
    match = jnp.take_along_axis(x, lbl[..., None].astype(jnp.int32), axis=-1)
    return {"Y": y, "XShape": jnp.zeros((0,) + x.shape, dtype=x.dtype),
            "MatchX": match}


@register_op(
    "softmax_with_cross_entropy",
    inputs=[In("Logits"), In("Label", no_grad=True)],
    outputs=[Out("Softmax"), Out("Loss")],
    attrs={"soft_label": False, "ignore_index": -100, "numeric_stable_mode": True,
           "axis": -1},
)
def _softmax_with_cross_entropy(ins, attrs):
    logits, label = ins["Logits"], ins["Label"]
    axis = attrs.get("axis", -1)
    logp = jax.nn.log_softmax(logits, axis=axis)
    softmax = jnp.exp(logp)
    if axis not in (-1, logits.ndim - 1):
        # move class axis last for the gather, then restore
        logp_m = jnp.moveaxis(logp, axis, -1)
        lbl = jnp.moveaxis(label, axis, -1) if attrs.get("soft_label") else label
        loss = _label_xent(logp_m, lbl, attrs.get("ignore_index", -100),
                           attrs.get("soft_label", False))
        loss = jnp.moveaxis(loss, -1, axis)
    else:
        loss = _label_xent(logp, label, attrs.get("ignore_index", -100),
                           attrs.get("soft_label", False))
    return {"Softmax": softmax, "Loss": loss}


@register_op(
    "sigmoid_cross_entropy_with_logits",
    inputs=[In("X"), In("Label", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"ignore_index": -100, "normalize": False},
)
def _sigmoid_xent(ins, attrs):
    x, label = ins["X"], ins["Label"]
    ignore = attrs.get("ignore_index", -100)
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    mask = (label != ignore).astype(x.dtype)
    loss = loss * mask
    if attrs.get("normalize", False):
        loss = loss / jnp.maximum(jnp.sum(mask), 1.0)
    return {"Out": loss}


@register_op(
    "square_error_cost",
    inputs=[In("X"), In("Y")],
    outputs=[Out("Out")],
)
def _square_error_cost(ins, attrs):
    return {"Out": jnp.square(ins["X"] - ins["Y"])}


@register_op(
    "squared_l2_distance",
    inputs=[In("X"), In("Y")],
    outputs=[Out("Out"), Out("sub_result", no_grad=True)],
)
def _squared_l2_distance(ins, attrs):
    sub = ins["X"] - ins["Y"]
    return {"Out": jnp.sum(jnp.square(sub), axis=-1, keepdims=True),
            "sub_result": sub}


@register_op(
    "log_loss",
    inputs=[In("Predicted"), In("Labels", no_grad=True)],
    outputs=[Out("Loss")],
    attrs={"epsilon": 1e-4},
)
def _log_loss(ins, attrs):
    p, l = ins["Predicted"], ins["Labels"]
    eps = attrs.get("epsilon", 1e-4)
    return {"Loss": -l * jnp.log(p + eps) - (1 - l) * jnp.log(1 - p + eps)}


@register_op(
    "huber_loss",
    # reference huber_loss_op.h:108,116 emits BOTH dX (sign -1) and dY
    inputs=[In("X"), In("Y")],
    outputs=[Out("Out"), Out("Residual", no_grad=True)],
    attrs={"delta": 1.0},
)
def _huber_loss(ins, attrs):
    d = attrs.get("delta", 1.0)
    r = ins["Y"] - ins["X"]  # residual = label - input? reference: Y - X
    a = jnp.abs(r)
    out = jnp.where(a <= d, 0.5 * jnp.square(r), d * (a - 0.5 * d))
    return {"Out": out, "Residual": r}


@register_op(
    "smooth_l1_loss",
    inputs=[In("X"), In("Y", no_grad=True),
            In("InsideWeight", dispensable=True, no_grad=True),
            In("OutsideWeight", dispensable=True, no_grad=True)],
    outputs=[Out("Out"), Out("Diff", no_grad=True)],
    attrs={"sigma": 1.0},
)
def _smooth_l1(ins, attrs):
    sigma2 = attrs.get("sigma", 1.0) ** 2
    diff = ins["X"] - ins["Y"]
    if ins.get("InsideWeight") is not None:
        diff = diff * ins["InsideWeight"]
    a = jnp.abs(diff)
    val = jnp.where(a < 1.0 / sigma2, 0.5 * sigma2 * jnp.square(diff),
                    a - 0.5 / sigma2)
    if ins.get("OutsideWeight") is not None:
        val = val * ins["OutsideWeight"]
    out = jnp.sum(val.reshape(val.shape[0], -1), axis=1, keepdims=True)
    return {"Out": out, "Diff": diff}


@register_op(
    "bce_loss",
    inputs=[In("X"), In("Label", no_grad=True)],
    outputs=[Out("Out")],
)
def _bce_loss(ins, attrs):
    x, l = ins["X"], ins["Label"]
    x = jnp.clip(x, 1e-12, 1.0 - 1e-12)
    return {"Out": -(l * jnp.log(x) + (1 - l) * jnp.log(1 - x))}


@register_op(
    "kldiv_loss",
    inputs=[In("X"), In("Target", no_grad=True)],
    outputs=[Out("Loss")],
    attrs={"reduction": "mean"},
)
def _kldiv_loss(ins, attrs):
    x, t = ins["X"], ins["Target"]
    loss = jnp.where(t > 0, t * (jnp.log(t) - x), jnp.zeros_like(t))
    red = attrs.get("reduction", "mean")
    if red == "mean":
        return {"Loss": jnp.mean(loss)}
    if red == "sum":
        return {"Loss": jnp.sum(loss)}
    if red == "batchmean":
        return {"Loss": jnp.sum(loss) / x.shape[0]}
    return {"Loss": loss}


@register_op(
    "hinge_loss",
    inputs=[In("Logits"), In("Labels", no_grad=True)],
    outputs=[Out("Loss")],
)
def _hinge_loss(ins, attrs):
    y = 2.0 * ins["Labels"] - 1.0
    return {"Loss": jnp.maximum(1.0 - ins["Logits"] * y, 0.0)}


@register_op(
    "margin_rank_loss",
    inputs=[In("X1"), In("X2"), In("Label", no_grad=True)],
    outputs=[Out("Out"), Out("Activated", no_grad=True)],
    attrs={"margin": 0.0},
)
def _margin_rank_loss(ins, attrs):
    m = attrs.get("margin", 0.0)
    raw = -ins["Label"] * (ins["X1"] - ins["X2"]) + m
    out = jnp.maximum(raw, 0.0)
    return {"Out": out, "Activated": (raw > 0).astype(out.dtype)}


@register_op(
    "rank_loss",
    inputs=[In("Label", no_grad=True), In("Left"), In("Right")],
    outputs=[Out("Out")],
)
def _rank_loss(ins, attrs):
    d = ins["Left"] - ins["Right"]
    return {"Out": jnp.log1p(jnp.exp(d)) - ins["Label"] * d}


@register_op(
    "mean_absolute_error",
    inputs=[In("X"), In("Y")],
    outputs=[Out("Out")],
)
def _mae(ins, attrs):
    return {"Out": jnp.abs(ins["X"] - ins["Y"])}
