"""DynamicRNN over LoD sequences (reference layers/control_flow.py
DynamicRNN on lod_tensor_to_array + shrink_rnn_memory + while)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.tensor import LoDTensor


def _lod_input(rng, lengths, dim):
    total = sum(lengths)
    x = rng.randn(total, dim).astype("float32")
    t = LoDTensor()
    t.set(x)
    t.set_recursive_sequence_lengths([list(lengths)])
    return x, t


def test_dynamic_rnn_matches_padded_oracle():
    """tanh-RNN over ragged sequences == the numpy per-sequence RNN."""
    D, H = 5, 7
    lengths = [4, 1, 3]
    rng = np.random.RandomState(3)
    x_np, x_t = _lod_input(rng, lengths, D)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="seq", shape=[-1, D], dtype="float32",
                       lod_level=1)
        drnn = fluid.layers.DynamicRNN()
        with drnn.block():
            word = drnn.step_input(x)
            prev = drnn.memory(shape=[H], value=0.0)
            hidden = fluid.layers.fc(
                [word, prev], size=H, act="tanh",
                param_attr=[fluid.ParamAttr(name="rwx"),
                            fluid.ParamAttr(name="rwh")],
                bias_attr=fluid.ParamAttr(name="rb"))
            drnn.update_memory(prev, hidden)
            drnn.output(hidden)
        out = drnn()

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        exe.run(main, feed={"seq": x_t}, fetch_list=[])
        result = scope.find_var(out.name).raw()
        got = np.asarray(result.array)
        got_lod = result.lod()
        # fc over [word, prev] keeps two weights W_x [D,H], W_h [H,H]
        wx = np.asarray(scope.find_var("rwx").raw().array)
        wh = np.asarray(scope.find_var("rwh").raw().array)
        b = np.asarray(scope.find_var("rb").raw().array)

    # numpy oracle: per-sequence tanh RNN
    expect = []
    off = 0
    for ln in lengths:
        h = np.zeros(H, "float32")
        for t in range(ln):
            h = np.tanh(x_np[off + t] @ wx + h @ wh + b)
            expect.append(h.copy())
        off += ln
    expect = np.stack(expect)
    assert got_lod == [[0, 4, 5, 8]]
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


def test_dynamic_rnn_static_input_and_init_memory():
    """static_input rows follow the rank order; memory(init=) boots
    from the reordered initial state."""
    D = 3
    lengths = [1, 3]  # rank order: seq1 (len 3) first, then seq0
    rng = np.random.RandomState(9)
    x_np, x_t = _lod_input(rng, lengths, D)
    init_np = rng.randn(2, D).astype("float32")
    stat_np = rng.randn(2, D).astype("float32")
    stat_t = LoDTensor()
    stat_t.set(stat_np)
    stat_t.set_recursive_sequence_lengths([[1, 1]])

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="seq2", shape=[-1, D], dtype="float32",
                       lod_level=1)
        init = fluid.data(name="init", shape=[2, D], dtype="float32")
        stat = fluid.data(name="stat", shape=[2, D], dtype="float32",
                          lod_level=1)
        drnn = fluid.layers.DynamicRNN()
        with drnn.block():
            word = drnn.step_input(x)
            prev = drnn.memory(init=init)
            sv = drnn.static_input(stat)
            nxt = fluid.layers.elementwise_add(word, prev)
            drnn.update_memory(prev, nxt)
            drnn.output(nxt)
        out = drnn()

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        exe.run(main, feed={"seq2": x_t, "init": init_np,
                            "stat": stat_t}, fetch_list=[])
        result = scope.find_var(out.name).raw()
        got = np.asarray(result.array)
        # the reordered static input landed in rank order
        sv_got = np.asarray(scope.find_var(sv.name).raw().array)

    np.testing.assert_array_equal(sv_got, stat_np[[1, 0]])
    # oracle: running sums of each sequence, seeded by its init row
    expect = []
    off = 0
    for s, ln in enumerate(lengths):
        h = init_np[s].copy()
        for t in range(ln):
            h = h + x_np[off + t]
            expect.append(h.copy())
        off += ln
    np.testing.assert_allclose(got, np.stack(expect), rtol=1e-6)
