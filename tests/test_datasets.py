"""Dataset package: every reader serves its reference sample contract
(offline synthetic mode), deterministically.
"""
import numpy as np

from paddle_tpu import dataset


def _take(reader, n):
    out = []
    for i, s in enumerate(reader()):
        if i >= n:
            break
        out.append(s)
    return out


def test_mnist_contract():
    s = _take(dataset.mnist.train(), 5)
    img, lbl = s[0]
    assert img.shape == (784,) and img.dtype == np.float32
    assert 0 <= lbl < 10
    # deterministic
    s2 = _take(dataset.mnist.train(), 5)
    np.testing.assert_array_equal(s[0][0], s2[0][0])


def test_cifar_contract():
    for reader, nclass in [(dataset.cifar.train10(), 10),
                           (dataset.cifar.train100(), 100)]:
        img, lbl = _take(reader, 1)[0]
        assert img.shape == (3072,) and img.dtype == np.float32
        assert 0 <= lbl < nclass
        assert 0.0 <= img.min() and img.max() <= 1.0


def test_imdb_contract():
    wd = dataset.imdb.word_dict()
    assert "<unk>" in wd
    samples = _take(dataset.imdb.train(wd), 10)
    for ids, lbl in samples:
        assert all(0 <= i < len(wd) for i in ids)
        assert lbl in (0, 1)
    assert {l for _, l in samples} == {0, 1} or len(samples) < 4


def test_imikolov_contract():
    wd = dataset.imikolov.build_dict()
    grams = _take(dataset.imikolov.train(wd, 5), 5)
    assert all(len(g) == 5 for g in grams)
    seqs = _take(dataset.imikolov.train(wd, -1, dataset.imikolov.SEQ), 3)
    for src, trg in seqs:
        assert len(src) == len(trg)


def test_movielens_contract():
    s = _take(dataset.movielens.train(), 5)
    uid, gender, age, job, mid, cats, title, rating = s[0]
    assert 1 <= uid <= dataset.movielens.max_user_id()
    assert 1 <= mid <= dataset.movielens.max_movie_id()
    assert gender in (0, 1)
    assert 0 <= age < len(dataset.movielens.age_table)
    assert 0 <= job <= dataset.movielens.max_job_id()
    assert isinstance(cats, list) and isinstance(title, list)
    assert 1.0 <= rating <= 5.0


def test_flowers_contract():
    img, lbl = _take(dataset.flowers.train(), 1)[0]
    assert img.shape == (3, 32, 32) and img.dtype == np.float32
    assert 0 <= lbl < 102


def test_wmt_contracts():
    for mod, mk in [(dataset.wmt14, lambda m: m.train(30)),
                    (dataset.wmt16, lambda m: m.train(30, 30))]:
        src, trg_in, trg_next = _take(mk(mod), 1)[0]
        assert trg_in[0] == 0            # <s>
        assert trg_next[-1] == 1         # <e>
        assert trg_in[1:] == trg_next[:-1]
        assert all(t >= 3 for t in src)
    sd, td = dataset.wmt14.get_dict(30)
    assert sd[0] == "<s>" and sd[1] == "<e>"


def test_conll05_contract():
    wd, vd, ld = dataset.conll05.get_dict()
    sample = _take(dataset.conll05.test(), 1)[0]
    assert len(sample) == 9
    L = len(sample[0])
    for part in sample[1:]:
        assert len(part) == L
    emb = dataset.conll05.get_embedding()
    assert emb.shape[0] == len(wd)


def test_voc2012_contract():
    img, mask = _take(dataset.voc2012.train(), 1)[0]
    assert img.shape[0] == 3 and img.dtype == np.float32
    assert mask.dtype == np.uint8 and mask.max() >= 1


def test_sentiment_contract():
    ids, lbl = _take(dataset.sentiment.train(), 1)[0]
    assert lbl in (0, 1) and len(ids) > 0


def test_image_transforms():
    im = (np.random.RandomState(0).rand(40, 60, 3) * 255).astype("uint8")
    out = dataset.image.simple_transform(im, 32, 28, is_train=False)
    assert out.shape == (3, 28, 28) and out.dtype == np.float32
    short = dataset.image.resize_short(im, 32)
    assert min(short.shape[:2]) == 32
