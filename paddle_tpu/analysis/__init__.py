"""Static analysis over the Program/Block/Operator/Variable IR.

Three analyses (ISSUE 12), the roles graph validators and
torch.distributed's debug-level checks play in production stacks:

- **Well-formedness verification** (``verifier.verify_program``):
  def-before-use per block, no dangling var references, op slot-arity /
  attr-type / dtype consistency against the op registry, duplicate-write
  aliasing hazards, unreachable-op and dead-var detection. Violations
  surface as structured ``IRVerificationError``s naming the op, the
  block, and the violated invariant.

- **Collective-consistency checking** (``collective.
  check_collective_schedule``): the static sequence of collective ops a
  rank would issue (kind, ring/axis, payload numel + dtype, bucket id)
  is extracted per program and cross-checked across ranks — a
  mismatched order/kind is a would-DEADLOCK finding, a mismatched
  payload/dtype a would-CORRUPT finding, and a collective under a
  conditional sub-block is divergence waiting to happen. The engine's
  first-run path and ``bench.py --multichip`` run the single-program
  form; the cross-rank form takes one schedule (or program) per rank.

- **Rewrite-invariant contracts** (``contracts``): each program-rewrite
  pass declares pre/post contracts (bucket pass: same multiset of
  reduced grads + consumer-barrier ordering preserved; sharded update:
  every spared param still sees its reduced grad). The
  ``checked_rewrite`` decorator snapshots the contract state before the
  pass, checks it after, and re-verifies the whole program — a future
  pass author gets invariant checking for free by decorating their
  pass.

Gate: ``PADDLE_TPU_VERIFY_IR`` (default OFF in prod — the disabled hook
is one env read + a branch, budgeted <1us by the CI overhead gate;
forced ON for the test suite via tests/conftest.py and for CI gates via
ci/check.sh).
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

from .verifier import (Finding, IRVerificationError,  # noqa: F401
                       verify_lazy_graph, verify_program)
from .collective import (CollectiveMismatchError,  # noqa: F401
                         CollectiveSig, check_collective_schedule,
                         check_cross_rank, extract_collective_schedule,
                         schedule_record)
from .contracts import (ContractViolation, RewriteContract,  # noqa: F401
                        check_pipeline_split, checked_rewrite,
                        register_contract)

__all__ = [
    "verify_enabled", "maybe_verify_program", "verify_program",
    "verify_lazy_graph", "Finding", "IRVerificationError",
    "CollectiveMismatchError", "CollectiveSig",
    "check_collective_schedule", "check_cross_rank",
    "extract_collective_schedule", "schedule_record",
    "ContractViolation", "RewriteContract", "checked_rewrite",
    "register_contract", "check_pipeline_split",
]

_TRUTHY = ("1", "true", "yes", "on")

# Fast path: probe os.environ's backing dict directly. The full
# os.environ.get goes through the _Environ mapping (encodekey + method
# dispatch, ~0.5-1.5us under load) — too close to the <1us/program-run
# budget ci gate 4 enforces. The backing dict probe is ~50ns and stays
# correct under monkeypatch.setenv/putenv (both write through
# __setitem__ into _data). Falls back to the mapping on interpreters
# without the CPython _Environ internals.
try:
    _ENV_DATA = os.environ._data
    _ENV_KEY = os.environ.encodekey("PADDLE_TPU_VERIFY_IR")
except Exception:  # non-CPython / exotic platform
    _ENV_DATA = None
    _ENV_KEY = None


def verify_enabled() -> bool:
    """One dict probe + a membership test — the whole disabled-path
    cost of every verify hook (ci gate 4 budgets it under 1us)."""
    if _ENV_DATA is not None:
        raw = _ENV_DATA.get(_ENV_KEY)
    else:
        raw = os.environ.get("PADDLE_TPU_VERIFY_IR")
    if raw is None:
        return False
    if isinstance(raw, bytes):
        raw = raw.decode("utf-8", "ignore")
    return raw.strip().lower() in _TRUTHY


def maybe_verify_program(program, where: str,
                         fetch_names: Optional[Sequence[str]] = None,
                         nranks: Optional[int] = None,
                         scope=None, recheck_shapes: bool = False):
    """The hook rewrite passes / engines / loaders call: no-op unless
    ``PADDLE_TPU_VERIFY_IR`` is set, else full well-formedness
    verification (raising ``IRVerificationError`` on error-severity
    findings) plus the single-program collective-schedule check.
    Returns the finding list (errors raise before returning)."""
    # enabled-check first: the disabled path costs exactly one env read
    # + a branch whatever the arguments (ci gate 4 benches this)
    if not verify_enabled() or program is None:
        return None
    findings = verify_program(program, fetch_names=fetch_names,
                              pass_name=where,
                              recheck_shapes=recheck_shapes)
    check_collective_schedule(program, nranks=nranks, where=where,
                              scope=scope)
    from .. import observability as _obs

    _obs.inc("analysis.verify_runs", where=where)
    for f in findings:
        _obs.inc("analysis.findings", severity=f.severity)
    return findings
