"""Dygraph DataParallel.

Parity: /root/reference/python/paddle/fluid/dygraph/parallel.py
(DataParallel :223, scale_loss :290, apply_collective_grads :382) and the
C++ NCCLParallelContext (imperative/nccl_context.cc:117).

TPU-native: rank/world come from jax.distributed (coordination service
over DCN — replacing the TCP ncclUniqueId broadcast); gradient allreduce
is a psum across processes expressed with jax collectives when a
multiprocess mesh is live, or an identity on world=1. Gradients are
coalesced before the allreduce, mirroring the reference's
_coalesce_tensors.
"""
from __future__ import annotations

import os

import numpy as np

from .layers import Layer
from .varbase import VarBase

__all__ = ["prepare_context", "ParallelEnv", "DataParallel", "Env"]


class ParallelEnv:
    def __init__(self):
        self._nranks = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self._local_rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._dev_id = int(os.getenv("FLAGS_selected_tpus",
                                     os.getenv("FLAGS_selected_gpus", "0")))
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = eps.split(",") if eps else []
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def dev_id(self):
        return self._dev_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints


Env = ParallelEnv


def prepare_context(strategy=None):
    """Initialize the multi-process context (reference: NCCL id broadcast
    + ncclCommInitRank). Here: jax.distributed.initialize when launched by
    paddle_tpu.distributed.launch / TPU pod runtime."""
    env = ParallelEnv()
    if env.nranks > 1:
        import jax

        coord = env.trainer_endpoints[0] if env.trainer_endpoints else None
        try:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=env.nranks,
                process_id=env.local_rank,
            )
        except (RuntimeError, ValueError):
            pass  # already initialized (or single-host simulation)
    return env


class DataParallel(Layer):
    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy or ParallelEnv()
        nr = getattr(self._strategy, "nranks", None)
        self._nranks = nr if nr is not None else ParallelEnv().nranks

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        if self._nranks <= 1:
            return loss
        from .tracer import current_tracer

        return current_tracer().trace_op(
            "scale", {"X": loss},
            {}, {"scale": 1.0 / self._nranks, "bias": 0.0})["Out"][0]

    @property
    def _sub(self):
        return self._layers

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_dict(self, *args, **kwargs):
        return self._layers.set_dict(*args, **kwargs)

    set_state_dict = set_dict

    def apply_collective_grads(self):
        """Coalesce + allreduce gradients across processes (reference
        dygraph/parallel.py:382 _coalesce_tensors + allreduce): all grads
        flatten into ONE buffer (one collective instead of one per
        param), the buffer all-reduces on device, and the slices scatter
        back."""
        if self._nranks <= 1:
            return
        params = [p for p in self.parameters() if p._grad is not None]
        if not params:
            return
        flat = _coalesce([p._grad for p in params])
        summed = _allreduce_across_processes(flat, self._nranks)
        for p, g in zip(params, _split_like(summed,
                                            [p._grad for p in params])):
            p._grad = g


def _coalesce(grads):
    """One flat f32 buffer (mixed grad dtypes upcast for the collective;
    _split_like restores each grad's own dtype — the reference groups
    by dtype instead, one collective per group)."""
    import jax.numpy as jnp

    return jnp.concatenate([g.astype(jnp.float32).ravel() for g in grads])


def _split_like(flat, refs):
    out = []
    off = 0
    for r in refs:
        n = int(np.prod(r.shape)) if r.ndim else 1
        out.append(flat[off:off + n].reshape(r.shape).astype(r.dtype))
        off += n
    return out


def _allreduce_across_processes(flat, nranks):
    """On-device cross-process sum: the local buffer becomes one shard
    of a global [nranks, n] array (one device per process); a psum under
    shard_map makes XLA insert the all-reduce over ICI/DCN (Gloo on the
    CPU backend). The output keeps the P('dp') sharding — every row
    holds the sum, so each process reads its OWN local shard and no
    cross-process gather of a replicated array is ever needed (a
    replicated out_sharding would be non-fully-addressable under
    multi-process jax and unreadable locally). Host-gather fallback only
    if global-array construction is unsupported by the runtime."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..parallel.mesh_utils import shard_map_compat

    try:
        devs = np.array(jax.devices()[:nranks])
        mesh = Mesh(devs, ("dp",))
        dist = NamedSharding(mesh, P("dp"))
        local = jnp.asarray(flat)[None, :]
        garr = jax.make_array_from_single_device_arrays(
            (nranks,) + flat.shape, dist,
            [jax.device_put(local, jax.local_devices()[0])])
        psummed = shard_map_compat(
            lambda x: jax.lax.psum(x, "dp"), mesh,
            in_specs=P("dp"), out_specs=P("dp"))
        out = jax.jit(psummed)(garr)
        [shard] = [s.data for s in out.addressable_shards]
        return shard[0]
    except Exception as e:
        import warnings

        warnings.warn(
            "on-device cross-process allreduce unavailable (%s); falling "
            "back to host-gather — expect much slower DP steps" % e)
        try:
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(flat,
                                                         tiled=True)
            return gathered.reshape(nranks, -1).sum(axis=0)
        except Exception:
            # process_allgather is itself a jitted cross-process
            # computation, so a backend that refused the psum above
            # (jaxlib's CPU backend: "Multiprocess computations
            # aren't implemented") refuses this too
            return _kv_allreduce(np.asarray(flat), nranks)


_kv_allreduce_seq = [0]


def _kv_allreduce(flat: np.ndarray, nranks: int) -> np.ndarray:
    """Last-resort cross-process sum over the jax.distributed
    coordinator's key-value store: every rank publishes its buffer,
    reads every peer's, sums on host. No XLA computation crosses a
    process boundary, so this works where the CPU backend refuses
    multiprocess programs outright. Correctness leans on the DP
    contract that every rank traces the SAME program — collective
    call N on rank 0 is collective call N everywhere, so a per-call
    sequence number keys the exchange."""
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError(
            "cross-process allreduce needs jax.distributed to be "
            "initialized (no coordinator client)")
    rank = int(distributed.global_state.process_id or 0)
    seq = _kv_allreduce_seq[0]
    _kv_allreduce_seq[0] += 1
    base = "paddle_tpu/allreduce/%d" % seq
    flat = np.ascontiguousarray(flat)
    client.key_value_set_bytes("%s/%d" % (base, rank), flat.tobytes())
    out = np.zeros_like(flat)
    for r in range(nranks):
        raw = client.blocking_key_value_get_bytes(
            "%s/%d" % (base, r), 120_000)
        out += np.frombuffer(raw, dtype=flat.dtype).reshape(flat.shape)
    # every rank holds the sum before anyone deletes, or a slow
    # reader races a cleaned-up key
    client.wait_at_barrier("%s/read" % base, 120_000)
    if rank == 0:
        for r in range(nranks):
            try:
                client.key_value_delete("%s/%d" % (base, r))
            except RuntimeError:
                # XlaRuntimeError from the coordinator: stale keys
                # only cost coordinator memory, never the allreduce
                pass
    return out
