"""Worker for the fault-tolerance multiprocess tests + CI smokes.

Role from PADDLE_ROLE (the launch supervisor sets it) or FT_ROLE:

- ``pserver`` — serve dense params (SGD lr 0.1) behind the
  RunSyncLoop round protocol with heartbeat eviction armed
  (PADDLE_PS_EVICT_AFTER); blocks until a shutdown rpc or SIGTERM.
  Multi-server mode: PADDLE_PSERVER_ENDPOINTS (this server's GROUP) +
  PSERVER_ENDPOINT (own) make index 0 the replication primary and the
  rest backups; PADDLE_PS_REJOIN=1 (launcher, on relaunch) rejoins as
  a catching-up backup. Sharded mode (ISSUE 8):
  PADDLE_PSERVER_SHARDS + PADDLE_PSERVER_SHARD — each shard serves
  the one var that routes to it (ps_shard.shard_for_key), so the
  2-shard drills exercise real key-range partitioning.
  FT_SERVER_DIE_AT_ROUND makes the INITIAL PRIMARY of shard
  FT_DIE_SHARD (default 0) SIGKILL itself while applying that round
  (grads in, round applied locally, never replicated — the worst
  spot) on its first incarnation. Every server also holds a STATIC
  ``ballast`` var (FT_BALLAST_FLOATS float32s, default 4096): it
  rides full anchors but never a delta, so the drills can assert
  ``ps.replication_bytes{mode=delta}`` strictly below the full-blob
  bytes for the same workload.
- ``trainer`` — FT_ROUNDS sync rounds of deterministic grads against
  the live server(s), checkpointing after every completed round via
  CheckpointManager.save_incremental (atomic + rotated; the static
  ballast shard is fingerprint-reused so ``checkpoint.delta_bytes`` /
  ``checkpoint.shards_reused`` are exercised end to end), resuming
  from the newest valid checkpoint on restart. FT_DIE_AT_ROUND +
  FT_DIE_RANK make one rank SIGKILL itself mid-round (after
  send_grad, before the barrier) on its first incarnation; with
  FT_DIE_MODE=partial_barrier it instead dies AFTER its phase-1
  barrier reached shard 0 only (the per-shard fanin-disagreement
  drill). FT_RESTART_DELAY makes a relaunched incarnation sleep
  before reconnecting (pins eviction races in drills). Every
  send_grad/send_barrier is stamped with the TRAINING round so a
  shard that already applied it (eviction) answers stale_round
  instead of contaminating the next round.
  PSERVER_ENDPOINT may be a comma-separated endpoint list (PSClient
  fails over along it); with PADDLE_PSERVER_SHARDS > 1 the trainer
  routes through ps_shard.client_from_env and runs the TWO-PHASE
  round barrier across shards. FT_MIGRATE_AT_ROUND > 0 makes
  trainer 0 trigger a LIVE MIGRATION of shard FT_MIGRATE_FROM_SHARD's
  var to FT_MIGRATE_TO_SHARD after that round's fetch barrier
  (re-triggered two rounds later if the shard map never bumped —
  the donor may have been killed mid-migration; that is the drill).
- ``witness`` — a ``PSWitness`` quorum endpoint on PSERVER_ENDPOINT
  (no parameter state; every shard's primaries renew with it via
  PADDLE_PS_WITNESSES).

ISSUE 18 mode (``FT_MIGRATE_RANGE=1``, requires shards > 1): every
shard additionally serves its LOCAL slice of one sparse table ``emb``
(height FT_EMB_HEIGHT, width FT_EMB_WIDTH, global rows sliced by
``row_range``) behind a row-local sparse-SGD block; trainers push
deterministic per-row grads every round — balanced across shards
until FT_MR_BASE_ROUND, then hammering the hot quarter of shard
FT_MR_HOT_SHARD's span (``emb_rows_for``/``emb_vals_for`` are pure,
so ``emb_oracle`` replays the whole schedule bit-for-bit). With
``FT_STEER_RANGE=1`` trainer 0 also runs the PR-16 SteeringDaemon
over the job's own merged telemetry: the ``row_load_rule`` skew
breach proposes a ``migrate_range`` plan, and the canary applies it
through the LIVE ``ShardedPSClient.migrate_range`` protocol —
promotion/rollback audited in ``<metrics>/steering``.

FT_EVICT_SHARD (pserver side): arm PADDLE_PS_EVICT_AFTER only on
that shard's servers — the sharded eviction drill's disagreeing
effective fanin.

Env contract: PSERVER_ENDPOINT, PADDLE_TRAINER_ID (the launcher sets
it), PADDLE_RESTART_COUNT (launcher, on relaunch), FT_OUT (result JSON
path, trainer), FT_CKPT_ROOT (checkpoint root, trainer).

The pserver side needs no framework program: PSServer only asks its
executor for _read_var/_write_var/run_block, so a dict-scope shim
keeps worker startup lean.
"""
import io
import json
import os
import signal
import sys
import time

import numpy as np

from paddle_tpu.checkpoint import CheckpointManager, manifest_extra
from paddle_tpu.distributed.ps_rpc import (PSClient, PSServer,
                                           PSWitness)
from paddle_tpu.distributed.ps_shard import (client_from_env,
                                             shard_for_key)

LR = 0.1
DIM = 4


class MiniScope(dict):
    def local_var_names(self):
        return list(self)


class MiniExec:
    """The minimal executor surface PSServer drives."""

    def _read_var(self, scope, name):
        return scope.get(name)

    def _write_var(self, scope, name, val):
        scope[name] = np.asarray(val)

    def run_block(self, block, scope):
        block(scope)


def _sgd_block_for(name):
    def block(scope):
        scope[name] = scope[name] - LR * scope[name + "@GRAD"]
    return block


def grad_for(tid: int, rnd: int, var: int = 0) -> np.ndarray:
    """Deterministic per-(trainer, round, var) gradient — survivors
    and oracles recompute the exact same values. ``var=0`` keeps the
    legacy single-var values bit-identical."""
    return np.full(DIM, (tid + 1) * 0.01 * rnd + var * 0.001,
                   dtype=np.float32)


def _nshards() -> int:
    return max(1, int(os.environ.get("PADDLE_PSERVER_SHARDS", "1")))


def var_names(nshards: int):
    """One trained var per shard, names chosen so var i ROUTES to
    shard i (searched deterministically — every process agrees). One
    shard keeps the legacy name 'w'."""
    if nshards <= 1:
        return ["w"]
    names = []
    for s in range(nshards):
        i = 0
        while True:
            cand = "w%d" % i
            if shard_for_key(cand, nshards) == s and cand not in names:
                names.append(cand)
                break
            i += 1
    return names


def _ballast() -> np.ndarray:
    n = int(os.environ.get("FT_BALLAST_FLOATS", "4096"))
    return np.zeros(max(0, n), dtype=np.float32)


# -- ISSUE 18: sparse table + deterministic hot-row workload -----------------


def _mr_mode() -> bool:
    return (os.environ.get("FT_MIGRATE_RANGE") == "1"
            and _nshards() > 1)


def _emb_dims():
    return (int(os.environ.get("FT_EMB_HEIGHT", "16")),
            int(os.environ.get("FT_EMB_WIDTH", "4")))


def emb_init(height: int, width: int) -> np.ndarray:
    """Global initial table: row r = r everywhere (each shard serves
    its ``row_range`` slice of this)."""
    return (np.arange(height, dtype=np.float32).reshape(-1, 1)
            * np.ones((1, width), dtype=np.float32))


def _sparse_sgd(scope):
    g = scope["emb@GRAD"]
    rows = np.asarray(g.rows(), dtype=np.int64)
    vals = np.asarray(g._value)
    emb = np.array(scope["emb"], copy=True)
    emb[rows] -= np.float32(0.1) * vals  # row-local, like pslib sgd
    scope["emb"] = emb


def _block_for_grad(gname):
    if gname.split("@", 1)[0] == "emb":
        return _sparse_sgd
    return _sgd_block_for(gname.split("@", 1)[0])


class SparseExec(MiniExec):
    def _write_var(self, scope, name, val):
        scope[name] = val  # keep SelectedRows grads un-coerced


def emb_rows_for(tid: int, rnd: int, base_round: int, height: int,
                 nshards: int, hot_shard: int):
    """Row-id arrays for trainer ``tid``'s round-``rnd`` sparse pushes
    (one ``push_sparse`` call per array). Rows are DISJOINT per
    trainer (tid 0 even ids, tid 1 odd) so per-row float op order is
    a pure function of the schedule; past ``base_round`` the hot
    quarter of ``hot_shard``'s span is hammered 8 extra times per
    round — the per-shard row-touch skew the steerer must catch."""
    from paddle_tpu.distributed.ps_shard import row_range

    mine = np.arange(tid % 2, height, 2, dtype=np.int64)
    pushes = [mine]
    if rnd > base_round:
        lo, hi = row_range(hot_shard, height, nshards)
        hlo = lo + 3 * (hi - lo) // 4
        hot = np.arange(hlo, hi, dtype=np.int64)
        hot_mine = hot[hot % 2 == tid % 2]
        if len(hot_mine):
            pushes.extend([hot_mine] * 8)
    return pushes


def emb_vals_for(rnd: int, rows, width: int) -> np.ndarray:
    rows = np.asarray(rows, dtype=np.int64)
    return (np.float32(0.01) * np.float32(rnd)
            * (rows.astype(np.float32) + 1.0)[:, None]
            * np.ones((1, width), dtype=np.float32))


def emb_oracle(rounds: int, base_round: int, height: int, width: int,
               nshards: int, hot_shard: int) -> np.ndarray:
    """The bit-for-bit oracle: replay both trainers' push schedules in
    per-row order (rows are trainer-disjoint, so trainer-major replay
    preserves every row's own float op sequence — the only order that
    matters for the row-local sgd block)."""
    emb = emb_init(height, width)
    for rnd in range(1, rounds + 1):
        for tid in (0, 1):
            for rows in emb_rows_for(tid, rnd, base_round, height,
                                     nshards, hot_shard):
                emb[rows] = emb[rows] - np.float32(0.1) \
                    * emb_vals_for(rnd, rows, width)
    return emb


def run_witness():
    w = PSWitness(os.environ["PSERVER_ENDPOINT"])
    w.serve_forever()


def run_pserver():
    endpoints_raw = os.environ.get("PADDLE_PSERVER_ENDPOINTS", "")
    endpoints = [e.strip() for e in endpoints_raw.split(",")
                 if e.strip()]
    endpoint = os.environ.get("PSERVER_ENDPOINT")
    if not endpoint:
        idx = int(os.environ.get("PADDLE_PSERVER_INDEX", "0"))
        endpoint = endpoints[idx]
    fanin = int(os.environ.get("PADDLE_TRAINERS_NUM", "2"))
    rejoin = os.environ.get("PADDLE_PS_REJOIN") == "1"
    die_round = int(os.environ.get("FT_SERVER_DIE_AT_ROUND", "0"))
    die_shard = int(os.environ.get("FT_DIE_SHARD", "0"))
    index = endpoints.index(endpoint) if endpoint in endpoints else 0
    nshards = _nshards()
    my_shard = int(os.environ.get("PADDLE_PSERVER_SHARD", "0"))
    evict_shard = os.environ.get("FT_EVICT_SHARD")
    evict_after = None
    if evict_shard is not None and evict_shard != "":
        # sharded eviction drill: only ONE shard's servers arm the
        # heartbeat monitor — per-shard effective fanin disagreeing
        # mid-round is exactly the case under test
        evict_after = (float(os.environ.get("FT_EVICT_AFTER", "1.0"))
                       if my_shard == int(evict_shard) else 0.0)

    scope = MiniScope()
    grad_to_block = {}
    for s, name in enumerate(var_names(nshards)):
        if nshards > 1 and s != my_shard:
            continue  # key-range partition: not this shard's var
        scope[name] = np.zeros(DIM, dtype=np.float32)
        grad_to_block[name + "@GRAD"] = _sgd_block_for(name)
    # static ballast: in every anchor, never in a delta — the
    # delta-vs-full evidence the drills gate on
    scope["ballast"] = _ballast()
    if _mr_mode():
        # ISSUE 18: this shard's LOCAL slice of the global sparse
        # table (the sharded router pushes/pulls LOCAL row ids)
        from paddle_tpu.distributed.ps_shard import row_range

        h, w = _emb_dims()
        lo, hi = row_range(my_shard, h, nshards)
        scope["emb"] = emb_init(h, w)[lo:hi]

    applied = {"rounds": 0}
    suicidal = (die_round > 0 and index == 0 and not rejoin
                and my_shard == die_shard)

    def _wrap(block):
        def inner(scope):
            block(scope)
            applied["rounds"] += 1
            if suicidal and applied["rounds"] == die_round:
                # die while APPLYING the round: grads are summed and
                # the local optimize ran, but the round was never
                # replicated — the trainers must rebuild it on the
                # promoted backup from their replay logs
                os.kill(os.getpid(), signal.SIGKILL)
        return inner

    grad_to_block = {g: _wrap(b) for g, b in grad_to_block.items()}
    if _mr_mode():
        # sparse pushes apply immediately (async, row-local): keep
        # them OUT of the round-counted suicide wrapper, and keep
        # SelectedRows grads un-coerced in the scope
        grad_to_block["emb@GRAD"] = _sparse_sgd
    execer = SparseExec() if _mr_mode() else MiniExec()

    server = PSServer(endpoint, execer, scope, grad_to_block,
                      fanin=fanin, sync_mode=True,
                      endpoints=endpoints or None, rejoin=rejoin,
                      evict_after=evict_after,
                      # a live migration ships state, never code: the
                      # recipient rebuilds the optimize block for an
                      # adopted var (or row range) from the shared
                      # definition
                      block_factory=_block_for_grad)
    server.serve_forever()
    server.stop()


def _steer_rounds(client, one_round, rounds, height, nshards,
                  base_round, hot_shard):
    """Trainer 0's ISSUE 18 driver: balanced rounds -> baseline poll,
    hot rounds -> sustained row-load skew -> a PROPOSED migrate_range
    plan, then a LIVE canary whose ``apply_fn`` is the real
    ``ShardedPSClient.migrate_range`` protocol. Every phase drives the
    shared fanin-2 round barrier (trainer 1 runs its plain loop), so
    the steering never stalls training; the canary measure is the
    counter-derived row-load skew, which is deterministic under the
    drill's injected chaos. Returns a summary the drill asserts on."""
    from paddle_tpu.observability import ps_steering
    from paddle_tpu.observability.canary import (AuditTrail, PlanStore,
                                                 run_canary)
    from paddle_tpu.observability.steering_daemon import SteeringDaemon

    mdir = os.environ["PADDLE_TPU_METRICS_DIR"]
    # steering artifacts live in a SUBDIR: merge_job_dir sweeps every
    # top-level *.json in the metrics dir as a process dump
    steer_dir = os.path.join(mdir, "steering")
    daemon = SteeringDaemon(
        mdir,
        rules=[ps_steering.row_load_rule(threshold=0.3, floor=0.1,
                                         table="emb")],
        hysteresis=2, cooldown=1, merge=True, out_dir=steer_dir,
        context={ps_steering.STEERER_NAME: {
            "metrics_dir": mdir, "height": height,
            "nshards": nshards, "by": "row_heat"}})
    info = {"proposed": None, "promoted": None, "plan": None,
            "decision": None, "polls": 0, "error": None}
    state = {"rnd": 1}

    def drive(n):
        for _ in range(n):
            if state["rnd"] > rounds:
                raise RuntimeError("steering phases exhausted the "
                                   "round budget (FT_ROUNDS=%d)"
                                   % rounds)
            one_round(state["rnd"])
            state["rnd"] += 1

    def poll():
        time.sleep(0.7)  # let every process's 0.5s dump cadence land
        props = daemon.poll_once()
        info["polls"] = daemon.polls
        return props

    def finish():
        while state["rnd"] <= rounds:
            one_round(state["rnd"])
            state["rnd"] += 1

    try:
        drive(base_round)        # balanced phase
        poll()                   # baseline (skew ~1.0)
        proposal = None
        for _ in range(3):       # hot phase: 2 breaches -> proposal
            drive(1)
            props = poll()
            if props:
                proposal = props[0]
                break
        if proposal is None:
            info["error"] = ("daemon never proposed (polls=%d)"
                             % daemon.polls)
            finish()
            return info
        info["proposed"] = proposal.get("plan_digest")
        info["plan"] = proposal.get("plan")

        def skew_record(n):
            # drive n rounds so the CURRENT ownership's push pattern
            # lands, then read the cumulative row-load skew off the
            # merged counters. Wall-clock throughput is hopeless as a
            # canary metric here — the drill SIGKILLs the donor mid
            # apply, so the head window would sit right inside the
            # rejoin catch-up + injected delay faults — but the skew
            # is counter-derived: it only RISES while the hot quarter
            # sits on one shard and decays toward balance once the
            # rows actually move (measure rounds run post-commit, the
            # apply_fn blocks until the map version bumps)
            drive(n)
            time.sleep(0.7)  # let every process's dump cadence land
            skew = ps_steering.row_load_skew_value(table="emb")(
                daemon.read_merged() or {})
            if skew is None:
                raise RuntimeError("no row-load skew in merged "
                                   "metrics during canary measure")
            return {"configs": {"ps_rebalance":
                                {"ps_row_load_skew": skew}}}

        incumbent = skew_record(3)

        def apply_fn(plan):
            client.migrate_range(plan["table"], plan["lo"],
                                 plan["hi"], plan["to_shard"],
                                 height=plan["height"])
            t = state["rnd"]
            while client.map_version < 1:
                if state["rnd"] - t >= 6:
                    raise RuntimeError("shard map never bumped after "
                                       "migrate_range")
                drive(1)
                if client.map_version < 1 and state["rnd"] - t == 2:
                    # the donor died mid-migration (the drill's kill
                    # hook): re-trigger against its promoted backup
                    try:
                        client.migrate_range(
                            plan["table"], plan["lo"], plan["hi"],
                            plan["to_shard"], height=plan["height"])
                        print("[trainer 0] re-triggered migrate_range"
                              " at round %d" % state["rnd"],
                              file=sys.stderr, flush=True)
                    except (ValueError, RuntimeError, OSError) as e:
                        print("[trainer 0] re-trigger failed: %s" % e,
                              file=sys.stderr, flush=True)

        dec = run_canary(
            proposal, incumbent, lambda plan: skew_record(3),
            threshold=0.5, apply_fn=apply_fn,
            plan_store=PlanStore(steer_dir, ps_steering.STEERER_NAME),
            audit=AuditTrail(steer_dir))
        info["promoted"] = dec.promoted
        info["decision"] = dec.decision
        finish()
    except Exception as e:  # noqa: BLE001 — the drill reads `error`
        info["error"] = "%s: %s" % (type(e).__name__, e)
        finish()
    return info


def run_trainer():
    endpoint = os.environ["PSERVER_ENDPOINT"]
    tid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    rounds = int(os.environ.get("FT_ROUNDS", "6"))
    restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
    die_round = int(os.environ.get("FT_DIE_AT_ROUND", "0"))
    die_rank = int(os.environ.get("FT_DIE_RANK", "-1"))
    die_mode = os.environ.get("FT_DIE_MODE", "")
    migrate_round = int(os.environ.get("FT_MIGRATE_AT_ROUND", "0"))
    migrate_from = int(os.environ.get("FT_MIGRATE_FROM_SHARD", "0"))
    migrate_to = int(os.environ.get("FT_MIGRATE_TO_SHARD", "1"))
    if restart > 0:
        # drills that pin an eviction race: the relaunched
        # incarnation must come back AFTER the evicting shard's
        # monitor fired, or the drill's oracle would be racy
        time.sleep(float(os.environ.get("FT_RESTART_DELAY", "0")))
    # per-rank result file: the launcher gives every rank the same env
    out_path = "%s.t%d.json" % (os.environ["FT_OUT"], tid)
    ckpt_root = os.environ.get("FT_CKPT_ROOT", "")
    nshards = _nshards()
    names = var_names(nshards)
    ballast_bytes = _ballast().tobytes()

    mgr = None
    start = 1
    resumed_from = None
    resumed_map = None
    if ckpt_root:
        mgr = CheckpointManager(os.path.join(ckpt_root, "t%d" % tid),
                                keep=3)
        state = {}

        def _load(d):
            data = np.load(os.path.join(d, "state.npz"))
            state["w"] = data["w"]
            # advisory routing state: the shard map this incarnation's
            # predecessor had adopted (checkpoint.manifest_extra)
            state["shard_map"] = manifest_extra(d).get("shard_map")

        restore_cut = os.environ.get("PADDLE_PS_RESTORE_ROUND", "")
        if restore_cut:
            # whole-job cold restart (ISSUE 19): load local state AT
            # OR BELOW the job restore cut, never past it — after a
            # corrupt-newest fallback the trainer's own newest
            # checkpoint can be AHEAD of the round the servers
            # restored, and local state derived from a round the
            # servers lost must not leak into the resumed run (the
            # training loop fast-forwards to cut+1 below either way)
            step = mgr.load_at_or_before(int(restore_cut), _load)
        else:
            step = mgr.load_latest(_load)
        if step is not None:
            resumed_from = step
            start = step + 1
            resumed_map = state.get("shard_map")
            print("[trainer %d] resumed from checkpoint round %d%s"
                  % (tid, step,
                     " (clamped to job restore cut %s)" % restore_cut
                     if restore_cut else ""),
                  file=sys.stderr, flush=True)

    if nshards > 1:
        client = client_from_env(trainer_id=tid)
        if resumed_map:
            # resume ROUTING too: migrations the dead incarnation saw
            # apply immediately instead of via wrong_shard redirects
            client.apply_shard_map(resumed_map)
    else:
        client = PSClient.for_endpoint(endpoint, trainer_id=tid)
    restore_cut_env = os.environ.get("PADDLE_PS_RESTORE_ROUND", "")
    if restore_cut_env:
        # whole-job cold restart: every round <= the cut is durably
        # folded into EVERY shard (that is what made it the cut), so
        # re-driving from an older checkpoint would only produce
        # stale re-sends — and stale barrier acks don't synchronize
        # trainers, so two resumed trainers can desync until one's
        # real round deadlocks against the other's stale-round
        # get_param. Fast-forward straight to cut+1 (grads are pure
        # functions of (tid, round), so rounds the servers fell back
        # past re-drive bit-identically) and seed the staleness-guard
        # counter to the cut — exactly the servers' applied round.
        cut = int(restore_cut_env)
        start = max(start, cut + 1)
        client.seed_round(cut)
    ws = {}
    mr = _mr_mode()
    emb_h, emb_w = _emb_dims()
    mr_base = int(os.environ.get("FT_MR_BASE_ROUND", "3"))
    mr_hot = int(os.environ.get("FT_MR_HOT_SHARD", str(nshards - 1)))

    def one_round(rnd):
        nonlocal ws
        if mr:
            # sparse workload first: row heat lands before the round
            # barrier, so the steerer's census is round-aligned
            for rows in emb_rows_for(tid, rnd, mr_base, emb_h,
                                     nshards, mr_hot):
                client.push_sparse("emb@GRAD", rows,
                                   emb_vals_for(rnd, rows, emb_w),
                                   height=emb_h, param="emb")
        for vi, name in enumerate(names):
            client.send_grad(name + "@GRAD", grad_for(tid, rnd, vi),
                             round=rnd)
        if restart == 0 and tid == die_rank and rnd == die_round:
            if die_mode == "partial_barrier" and nshards > 1:
                # phase-1 barrier reached shard 0 ONLY, then death:
                # shard 0 can apply the round with this trainer in,
                # the sister shard cannot — the per-shard effective
                # fanin disagreement the eviction drill reconciles
                client.shards[0].barrier_prepare(round=rnd)
            # mid-round death: grad in, barrier never (fully) sent —
            # the worst spot, servers are left waiting on this rank
            os.kill(os.getpid(), signal.SIGKILL)
        client.send_barrier(round=rnd)
        ws = {name: client.get_param(name) for name in names}
        client.fetch_barrier()
        if (migrate_round and tid == 0 and nshards > 1
                and (rnd == migrate_round
                     or (rnd >= migrate_round + 2
                         and getattr(client, "map_version", 1) == 0))):
            # live migration rides the NEXT round's barrier; the
            # re-trigger two rounds later covers a donor killed
            # mid-migration before the intent ever replicated (the
            # rollback path the --migrate chaos drill drills)
            try:
                client.migrate(names[migrate_from], migrate_to)
                print("[trainer %d] requested migration of %s -> "
                      "shard %d at round %d" % (tid,
                                                names[migrate_from],
                                                migrate_to, rnd),
                      file=sys.stderr, flush=True)
            except (RuntimeError, OSError) as e:
                print("[trainer %d] migrate request failed (will "
                      "retry): %s" % (tid, e), file=sys.stderr,
                      flush=True)
        if mgr is not None:
            buf = io.BytesIO()
            np.savez(buf, w=ws[names[0]], round=rnd,
                     **{"v_%s" % n: w for n, w in ws.items()})
            # the static ballast shard is fingerprint-reused: the
            # incremental save writes only what changed this round.
            # The adopted shard map rides the manifest (advisory) so
            # a relaunched incarnation resumes routing with it.
            extra = None
            if nshards > 1 and getattr(client, "map_version", 0):
                extra = {"shard_map": {
                    "version": client.map_version,
                    "overrides": dict(client.map_overrides),
                    "ranges": {
                        t: [list(r) for r in rs] for t, rs in
                        getattr(client, "map_ranges", {}).items()}}}
            mgr.save_incremental(
                rnd, {"state.npz": buf.getvalue(),
                      "ballast.bin": ballast_bytes},
                fingerprints={"ballast.bin": "static-v1"},
                extra=extra)

    steer = None
    if (mr and tid == 0 and start == 1
            and os.environ.get("FT_STEER_RANGE") == "1"):
        steer = _steer_rounds(client, one_round, rounds, emb_h,
                              nshards, mr_base, mr_hot)
    else:
        for rnd in range(start, rounds + 1):
            one_round(rnd)

    if nshards > 1:
        hbs = client.heartbeat_full()  # per shard, index-aligned
        hb = hbs[0]
        shard_info = [
            {"endpoint": c.endpoint, "ep_idx": c._ep_idx,
             "failovers": c._failover_count,
             "server_active": h.get("active"),
             "server_round": h.get("round"),
             "server_promotions": h.get("promotions")}
            for c, h in zip(client.shards, hbs)]
        ep_idx = client.shards[0]._ep_idx
        failovers = sum(c._failover_count for c in client.shards)
        endpoint_now = ",".join(c.endpoint for c in client.shards)
        evicted = set()
        for c, h in zip(client.shards, hbs):
            evicted |= c.evicted_peers | set(h.get("evicted", []))
    else:
        hb = client.heartbeat_full()
        hbs = [hb]
        shard_info = None
        ep_idx = client._ep_idx
        failovers = client._failover_count
        endpoint_now = client.endpoint
        evicted = client.evicted_peers | set(hb.get("evicted", []))
    with open(out_path, "w") as f:
        json.dump({
            "tid": tid,
            "rounds_done": rounds - start + 1,
            "resumed_from": resumed_from,
            "restart": restart,
            "w": np.asarray(ws[names[0]]).tolist(),
            "vars": {n: np.asarray(w).tolist() for n, w in ws.items()},
            "evicted_peers": sorted(evicted),
            "evictions": hb.get("evictions"),
            "readmissions": hb.get("readmissions"),
            # failover telemetry: which endpoint the client ended on,
            # how many times it advanced, and the serving side's view
            "endpoint": endpoint_now,
            "ep_idx": ep_idx,
            "failovers": failovers,
            "server_active": hb.get("active"),
            "server_round": hb.get("round"),
            "server_promotions": sum(
                h.get("promotions") or 0 for h in hbs),
            "shards": shard_info,
            # live-migration telemetry: the router's adopted map and
            # the servers' own view of it (drill-gated)
            "map_version": getattr(client, "map_version", 0),
            "map_overrides": getattr(client, "map_overrides", {}),
            "server_map_versions": [
                (h.get("shard_map") or {}).get("version", 0)
                for h in hbs],
            # ISSUE 18 telemetry: the final sparse table as pulled
            # through the (possibly range-split) router, the adopted
            # per-range map, and trainer 0's steering summary
            "emb": (np.asarray(client.pull_sparse(
                "emb", np.arange(emb_h, dtype=np.int64),
                height=emb_h)).tolist() if mr else None),
            "map_ranges": ({t: [list(r) for r in rs] for t, rs in
                            getattr(client, "map_ranges", {}).items()}
                           if mr else None),
            "steer": steer,
        }, f)


def main():
    role = os.environ.get("PADDLE_ROLE") or os.environ["FT_ROLE"]
    if role == "pserver":
        run_pserver()
    elif role == "trainer":
        run_trainer()
    elif role == "witness":
        run_witness()
    else:
        raise SystemExit("unknown FT_ROLE %r" % role)


if __name__ == "__main__":
    main()
