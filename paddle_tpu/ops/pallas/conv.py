"""Block-tuned implicit-GEMM conv2d as a Pallas TPU kernel.

Parity intent: the reference's conv hot path is cuDNN algorithm search
(operators/conv_cudnn_op.cu) plus hand-fused conv+bias+relu
(operators/fused/conv_fusion_op.cu). This is the TPU-native analog:
one kernel computes conv(+folded scale/shift)(+residual)(+relu) for
the NHWC ResNet hot shapes, expressed as KH*KW accumulated MXU
matmuls over [block_h * W_out, Cin] x [Cin, block_n] tiles — the
im2col never materializes in HBM, and the elementwise epilogue runs
in VMEM on the accumulator, saving one full activation round-trip.

Blocking lesson from the flash-attention kernels (BASELINE.md r4):
block size is the whole game. block_h is chosen so the GEMM M-dim
(block_h * W_out) lands in the 448-1024 row range and block_n caps at
256 lanes; K = Cin per tap (128-aligned for every ResNet stage except
the 3-channel stem, which stays on XLA).

Grid = (B, H_out/block_h, Cout/block_n), all parallel: the full
KH*KW*Cin reduction happens inside one grid instance, so the fp32
accumulator lives in registers/VMEM with no cross-step carry.

Scope: stride 1 and 2, square kernels (1x1/3x3 are the ResNet mix),
groups=1, NHWC. Everything else routes to lax.conv_general_dilated.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _kernel(x_ref, w_ref, scale_ref, shift_ref, *rest,
            block_h, w_out, kh, kw, stride, relu, has_residual):
    from jax.experimental import pallas as pl

    if has_residual:
        res_ref, o_ref = rest
    else:
        (o_ref,) = rest
    i = pl.program_id(1)
    h0 = i * block_h * stride
    cin = x_ref.shape[3]
    bn = o_ref.shape[3]
    rows = block_h * w_out
    acc = jnp.zeros((rows, bn), jnp.float32)
    # input rows needed for output rows [i*bh, i*bh+bh) at tap r:
    # h*stride + r  ->  contiguous span of (bh-1)*stride + 1 rows
    span = (block_h - 1) * stride + 1
    for r in range(kh):
        xs_full = x_ref[0, pl.ds(h0 + r, span), :, :]
        for c in range(kw):
            if stride == 1:
                xs = jax.lax.slice(
                    xs_full, (0, c, 0),
                    (block_h, c + w_out, cin))    # [bh, w_out, cin]
            else:
                # Mosaic only supports unit strides in extract_
                # strided_slice: decimate via reshape instead. Rows:
                # pad span (2bh-1) to 2bh, fold the stride into a new
                # axis, keep phase 0. Cols: same on the width axis.
                wspan = c + (w_out - 1) * stride + 1
                xs = jax.lax.slice(
                    xs_full, (0, c, 0), (span, wspan, cin))
                xs = jnp.pad(xs, ((0, 2 * block_h - span),
                                  (0, 2 * w_out - (wspan - c)), (0, 0)))
                xs = xs.reshape(block_h, 2, 2 * w_out, cin)[:, 0]
                xs = xs.reshape(block_h, w_out, 2, cin)[:, :, 0]
            acc += jax.lax.dot_general(
                xs.reshape(rows, cin), w_ref[r, c],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    y = acc * scale_ref[:] + shift_ref[:]
    if has_residual:
        y = y + res_ref[0].reshape(rows, bn).astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    o_ref[0] = y.reshape(block_h, w_out, bn).astype(o_ref.dtype)


def _pick_block_h(h_out, w_out):
    """Largest divisor of h_out keeping the GEMM M-dim <= ~1024 rows."""
    best = 1
    for bh in range(1, h_out + 1):
        if h_out % bh == 0 and bh * w_out <= 1024:
            best = bh
    return best


def _pick_block_n(cout):
    for bn in (256, 128, cout):
        if cout % bn == 0:
            return bn
    return cout


def conv2d_bn_act(x, w, scale=None, shift=None, *, stride=1, padding=0,
                  relu=False, residual=None, block_h=None, block_n=None,
                  interpret=None):
    """Fused conv(+scale/shift)(+residual)(+relu), NHWC.

    x: [B, H, W, Cin]; w: [KH, KW, Cin, Cout]; scale/shift: [Cout]
    (pass None for a pure conv); residual: [B, H_out, W_out, Cout].
    Returns [B, H_out, W_out, Cout] in x.dtype.
    """
    from jax.experimental import pallas as pl

    if stride not in (1, 2):
        # the kernel's decimation path folds the stride into a
        # hard-coded factor-2 reshape (_kernel: pad-to-2bh + keep
        # phase 0); any other stride would run to completion with
        # wrong output instead of failing
        raise ValueError("conv2d_bn_act supports stride 1 or 2, got %r"
                         % (stride,))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, H, W, Cin = x.shape
    KH, KW, _, Cout = w.shape
    if padding:
        x = jnp.pad(x, ((0, 0), (padding, padding), (padding, padding),
                        (0, 0)))
        H, W = H + 2 * padding, W + 2 * padding
    H_out = (H - KH) // stride + 1
    W_out = (W - KW) // stride + 1
    bh = block_h or _pick_block_h(H_out, W_out)
    bn = block_n or _pick_block_n(Cout)
    if H_out % bh or Cout % bn:
        raise ValueError("block_h/block_n must divide H_out/Cout")
    if scale is None:
        scale = jnp.ones((Cout,), jnp.float32)
    if shift is None:
        shift = jnp.zeros((Cout,), jnp.float32)
    scale2 = jnp.asarray(scale, jnp.float32).reshape(1, Cout)
    shift2 = jnp.asarray(shift, jnp.float32).reshape(1, Cout)

    kernel = functools.partial(
        _kernel, block_h=bh, w_out=W_out, kh=KH, kw=KW, stride=stride,
        relu=relu, has_residual=residual is not None)
    in_specs = [
        # full (padded) image rows for one batch element: halo slicing
        # happens inside the kernel (overlap is not expressible with
        # blocked index maps)
        pl.BlockSpec((1, H, W, Cin), lambda b, i, n: (b, 0, 0, 0)),
        pl.BlockSpec((KH, KW, Cin, bn), lambda b, i, n: (0, 0, 0, n)),
        pl.BlockSpec((1, bn), lambda b, i, n: (0, n)),
        pl.BlockSpec((1, bn), lambda b, i, n: (0, n)),
    ]
    args = [x, w, scale2, shift2]
    if residual is not None:
        in_specs.append(
            pl.BlockSpec((1, bh, W_out, bn), lambda b, i, n: (b, i, 0, n)))
        args.append(residual)
    out = pl.pallas_call(
        kernel,
        grid=(B, H_out // bh, Cout // bn),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bh, W_out, bn),
                               lambda b, i, n: (b, i, 0, n)),
        out_shape=jax.ShapeDtypeStruct((B, H_out, W_out, Cout), x.dtype),
        interpret=interpret,
    )(*args)
    return out


def _xla_conv_nhwc(x, w, stride, padding):
    from jax import lax

    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NHWC", "HWIO", "NHWC"))
    return lax.conv_general_dilated(
        x, w, (stride, stride), [(padding, padding), (padding, padding)],
        dimension_numbers=dn)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def pallas_conv(x, w, stride=1, padding=0):
    """Differentiable pallas conv, NHWC x [B,H,W,Cin], w HWIO.

    Forward runs the pallas implicit-GEMM kernel; backward uses XLA's
    conv transpose forms (the bwd shapes flip the win class — e.g. an
    expansion conv's dx is a reduction conv, where XLA measured faster;
    see BASELINE.md round-5 table)."""
    return conv2d_bn_act(x, w, stride=stride, padding=padding)


def _pallas_conv_fwd(x, w, stride, padding):
    return pallas_conv(x, w, stride, padding), (x, w)


def _pallas_conv_bwd(stride, padding, res, g):
    x, w = res
    _, vjp = jax.vjp(
        lambda x, w: _xla_conv_nhwc(x, w, stride, padding), x, w)
    return vjp(g)


pallas_conv.defvjp(_pallas_conv_fwd, _pallas_conv_bwd)


def route_pallas(flag_value, x_shape, w_shape, stride, groups, dilations,
                 data_format):
    """Routing decision for the conv op: 'off' never; 'all' any viable
    shape; 'auto' only the measured-win class (stride-1 1x1 expansion
    convs, Cout >= 2*Cin — the shapes where the fused epilogue beats
    XLA 1.4-1.5x on v5e; every other class measured at or below parity,
    BASELINE.md round 5)."""
    if flag_value == "off" or not pallas_conv_viable(
            x_shape, w_shape, stride, groups, dilations, data_format):
        return False
    if flag_value == "all":
        return True
    KH, KW, Cin, Cout = w_shape
    return KH == 1 and stride == 1 and Cout >= 2 * Cin


def pallas_conv_viable(x_shape, w_shape, stride, groups, dilations,
                       data_format):
    """True when the pallas kernel covers this conv (NHWC, groups=1,
    square small kernel, 128-aligned Cin, stride 1/2)."""
    if data_format != "NHWC" or groups != 1:
        return False
    if any(d != 1 for d in dilations):
        return False
    KH, KW, Cin, _ = w_shape
    if KH != KW or KH not in (1, 3):
        return False
    if Cin % 128:
        return False          # the 3-channel stem stays on XLA
    return stride in (1, 2)
