"""Crash flight recorder: a bounded ring of recent structured events.

The black box for the distributed runtime. Metrics answer "how many
failovers happened"; spans answer "how long did the apply take"; what
neither answers after a process is SIGKILLed mid-drill is *what was it
doing right before* — which rpc token was in flight, which round was
being applied, which backup had just been dropped from the replication
stream. This module records exactly that: every interesting decision in
``ps_rpc`` / ``fault`` / ``checkpoint`` / ``launch`` appends one small
tuple to a process-wide ring (``PADDLE_TPU_FLIGHT_RING`` entries,
default 2048 — old history falls off, the recent past survives).

Recording is UNCONDITIONAL and cheap (one ``deque.append`` under the
GIL, no lock, no timestamp formatting) — a black box that must be armed
in advance is not a black box. What is gated is *persistence*: the ring
reaches disk only through ``observability.distributed`` (periodic +
at-exit + on-signal dumps into ``$PADDLE_TPU_METRICS_DIR``) or an
explicit ``dump()``. On a fatal uncaught exception the tail of the ring
is additionally printed to stderr (``install_excepthook``) so even a
process with no metrics dir leaves a postmortem in its worker log.

Event shape: ``(ts_us, kind, fields)`` — ``ts_us`` is
``time.perf_counter()`` microseconds (the span clock; the per-process
dump carries the wall-clock offset that rebases both), ``kind`` is a
dotted string (``rpc.send``, ``ps.promotion``, ``fault.injected``,
``checkpoint.commit``, ``launch.exit``), ``fields`` a small dict of
json-safe scalars or None.

Disaster-recovery kinds (ISSUE 19) narrate a whole-job crash and cold
restart end to end: ``launch.cold_start`` (the relaunched supervisor
found durable rounds and computed the job restore cut),
``ps.round_durable`` (a shard primary persisted an applied round's
frame), ``ps.restore`` (a server loaded the cut from disk and re-armed
its fencing epoch), ``ps.fence_refused`` (a straggler from the dead
incarnation was refused by the restored epoch). ``tools/ft_timeline``
highlights exactly this causal chain in the postmortem.
"""
from __future__ import annotations

import collections
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["record", "events", "clear", "stats", "tail_lines",
           "install_excepthook"]

_RING_CAP = max(16, int(os.environ.get("PADDLE_TPU_FLIGHT_RING",
                                       "2048") or "2048"))
_ring: "collections.deque[Tuple]" = collections.deque(maxlen=_RING_CAP)
_recorded = 0  # total ever recorded (recorded - len(ring) = dropped)


def record(kind: str, /, **fields) -> None:
    """Append one event to the ring. Hot-path safe: one deque append;
    callers pass only small json-safe scalars in ``fields`` (a
    ``kind=`` field is fine — the positional event kind won't collide
    with it)."""
    global _recorded
    _ring.append((time.perf_counter() * 1e6, kind, fields or None))
    _recorded += 1


def events() -> List[Tuple]:
    """Snapshot of the ring, oldest first."""
    return list(_ring)


def clear() -> None:
    global _recorded
    _ring.clear()
    _recorded = 0


def stats() -> Dict[str, int]:
    n = len(_ring)
    return {"recorded": _recorded, "buffered": n,
            "dropped": _recorded - n, "capacity": _RING_CAP}


def tail_lines(n: int = 50) -> List[str]:
    """The newest ``n`` events formatted one per line (the stderr
    postmortem shape; ``tools/ft_timeline.py`` renders the merged
    cross-process version of the same thing)."""
    out = []
    for ts_us, kind, fields in list(_ring)[-n:]:
        kv = "" if not fields else " " + " ".join(
            "%s=%s" % (k, fields[k]) for k in sorted(fields))
        out.append("[flight +%12.3fms] %s%s" % (ts_us / 1e3, kind, kv))
    return out


def install_excepthook() -> None:
    """Chain a hook onto ``sys.excepthook`` that prints the flight-ring
    tail to stderr before the normal traceback — the last thing a
    crashing worker says is what it was doing. Idempotent."""
    prev = sys.excepthook
    if getattr(prev, "_flight_hook", False):
        return

    def hook(exc_type, exc, tb):
        try:
            lines = tail_lines(50)
            if lines:
                print("-- flight recorder (last %d of %d events) --"
                      % (len(lines), _recorded),
                      file=sys.stderr, flush=True)
                for ln in lines:
                    print(ln, file=sys.stderr)
        except Exception:
            pass
        try:
            from . import distributed as _dist

            _dist.dump_process()  # best-effort: no-op without a dir
        except Exception:
            pass
        prev(exc_type, exc, tb)

    hook._flight_hook = True
    sys.excepthook = hook
