"""Broadcasted binary elementwise ops.

Parity with /root/reference/paddle/fluid/operators/elementwise/ (add, sub,
mul, div, min, max, mod, pow, floordiv) including the Fluid ``axis``
broadcast rule (elementwise_op_function.h): with ``axis >= 0``, Y's dims
align to X starting at ``axis`` (trailing size-1 dims of Y trimmed);
``axis == -1`` is numpy-style right alignment. Gradients come from the
auto-VJP maker — XLA fuses the reduce-to-shape transposes the reference
hand-writes in elementwise_*_grad kernels.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import In, Out, register_op


def _align(x, y, axis):
    if x.ndim == y.ndim:
        return x, y
    if x.ndim < y.ndim:
        # paddle requires rank(X) >= rank(Y); be permissive and mirror.
        y2, x2 = _align(y, x, axis)
        return x2, y2
    yshape = list(y.shape)
    while len(yshape) > 1 and yshape[-1] == 1:
        yshape.pop()
    if axis == -1:
        axis = x.ndim - len(yshape)
    new_shape = [1] * x.ndim
    new_shape[axis : axis + len(yshape)] = yshape
    return x, y.reshape(new_shape)


def _binary(name, f):
    @register_op(
        name,
        inputs=[In("X"), In("Y")],
        outputs=[Out("Out")],
        attrs={"axis": -1, "use_mkldnn": False, "scale_x": 1.0, "scale_y": 1.0,
               "scale_out": 1.0},
    )
    def _op(ins, attrs, _f=f):
        x, y = _align(ins["X"], ins["Y"], attrs.get("axis", -1))
        return {"Out": _f(x, y)}

    return _op


_binary("elementwise_add", jnp.add)
_binary("elementwise_sub", jnp.subtract)
_binary("elementwise_mul", jnp.multiply)
_binary("elementwise_div", jnp.divide)
_binary("elementwise_min", jnp.minimum)
_binary("elementwise_max", jnp.maximum)
_binary("elementwise_pow", jnp.power)
# C++ truncated-modulo semantics (sign of dividend), both int and float.
_binary("elementwise_mod", jnp.fmod)
_binary("elementwise_floordiv", jnp.floor_divide)
