"""Typed errors + enforce helpers.

Parity: /root/reference/paddle/fluid/platform/enforce.h:261
(PADDLE_ENFORCE / EnforceNotMet) and errors.h's typed error taxonomy.
Framework raise sites funnel through these so users get op/var context
instead of bare KeyErrors from deep in the registry.
"""
from __future__ import annotations

__all__ = [
    "EnforceNotMet",
    "InvalidArgumentError",
    "NotFoundError",
    "OutOfRangeError",
    "AlreadyExistsError",
    "PermissionDeniedError",
    "UnimplementedError",
    "PreconditionNotMetError",
    "ExecutionTimeoutError",
    "enforce",
    "enforce_not_none",
]


class EnforceNotMet(RuntimeError):
    """Base framework error (reference EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    def __str__(self):  # KeyError quotes its arg; keep it readable
        return RuntimeError.__str__(self)


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


def enforce(cond, message, error_cls=EnforceNotMet):
    if not cond:
        raise error_cls(message)


def enforce_not_none(value, message, error_cls=NotFoundError):
    if value is None:
        raise error_cls(message)
    return value
