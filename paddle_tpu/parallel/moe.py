"""Expert parallelism: switch-routed MoE over a mesh axis.

The reference snapshot has no expert parallelism (SURVEY §2.5 "NOT
present" row); the collective layer here was designed so new mesh axes
drop in, and this module is the EP drop-in, GShard/Switch style:

- top-1 gating with a fixed per-expert capacity (static shapes — XLA
  needs them; overflow tokens are dropped exactly as Switch does);
- dispatch is einsum against a one-hot dispatch mask, then ONE
  ``lax.all_to_all`` over the expert axis moves token slots to the
  devices owning their experts (this is the canonical EP collective —
  not an all_gather: each device keeps only its experts' slots);
- experts run their FFN on local slots; a second all_to_all routes
  results back; the combine weights the outputs by gate probability.

``expert_parallel_moe`` is the collective-level entry (call inside
shard_map with tokens sharded over the axis and one expert group per
device); ``moe_reference`` is the single-device oracle with identical
routing/drop semantics for tests.
"""
from __future__ import annotations

from typing import Optional


def _top1_dispatch(x, gate_w, num_experts, capacity):
    """Returns (dispatch [E, C, T], combine [T, E, C], gate_probs [T])."""
    import jax
    import jax.numpy as jnp

    logits = x @ gate_w                               # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)               # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    # position of each token within its expert's queue
    onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.int32)  # [T,E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1      # [T, E], -1 if not
    pos_in_expert = pos.max(axis=1)                    # [T]
    keep = pos_in_expert < capacity
    disp = (jax.nn.one_hot(expert, num_experts, dtype=x.dtype)[:, :, None]
            * jax.nn.one_hot(jnp.clip(pos_in_expert, 0, capacity - 1),
                             capacity, dtype=x.dtype)[:, None, :])
    disp = disp * keep[:, None, None].astype(x.dtype)  # [T, E, C]
    return jnp.swapaxes(disp, 0, 1).swapaxes(1, 2), disp, gate


def expert_parallel_moe(x, gate_w, w_in, w_out, axis_name: str,
                        capacity_factor: float = 1.0,
                        axis_size: Optional[int] = None):
    """Switch-MoE layer inside shard_map.

    Args:
      x: local token shard ``[T_local, D]`` (tokens sharded over
        ``axis_name``).
      gate_w: ``[D, E_total]`` replicated gate weights.
      w_in / w_out: LOCAL expert weights ``[E_local, D, H]`` /
        ``[E_local, H, D]`` (experts sharded over ``axis_name``,
        E_total = E_local * axis_size).
      capacity_factor: per-expert slots per sending device =
        ceil(T_local * cf / E_total).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    if axis_size:
        n = int(axis_size)
    else:
        from ..ops.collective_ops import static_axis_size

        n = static_axis_size(axis_name)
    T, D = x.shape
    e_local = w_in.shape[0]
    e_total = e_local * n
    capacity = max(1, int(-(-T * capacity_factor // e_total)))  # ceil

    disp_ect, disp_tec, gate = _top1_dispatch(x, gate_w, e_total,
                                              capacity)
    # tokens into per-expert slots: [E_total, C, D]
    slots = jnp.einsum("ect,td->ecd", disp_ect, x)
    # group experts by owning device and all_to_all the device axis:
    # [n, E_local, C, D] local -> receive MY experts' slots from all
    # devices: [n, E_local, C, D] (sender-major)
    slots = slots.reshape(n, e_local, capacity, D)
    slots = lax.all_to_all(slots, axis_name, split_axis=0, concat_axis=0,
                           tiled=False)
    # slots: [n_senders, E_local, C, D] — flatten sender into the slot
    # dim and run the local experts
    h = jnp.einsum("secd,edh->sech", slots, w_in)
    h = jax.nn.relu(h)
    out = jnp.einsum("sech,ehd->secd", h, w_out)
    # route back: inverse all_to_all, then combine
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)
    out = out.reshape(e_total, capacity, D)
    y = jnp.einsum("tec,ecd->td", disp_tec, out)
    return y * gate[:, None]


def moe_reference(x, gate_w, w_in_full, w_out_full,
                  capacity_factor: float = 1.0, axis_size: int = 1):
    """Single-device oracle with the same top-1 + capacity semantics.

    w_in_full/w_out_full: ``[E_total, D, H]`` / ``[E_total, H, D]``.
    ``x`` here is the FULL token set processed in per-shard chunks of
    ``T_local = T / axis_size`` so capacity math matches the sharded
    run exactly.
    """
    import jax
    import jax.numpy as jnp

    T, D = x.shape
    e_total = w_in_full.shape[0]
    if T % axis_size:
        raise ValueError(
            "moe_reference: token count %d must divide by axis_size %d "
            "(the sharded run it mirrors requires equal shards)"
            % (T, axis_size))
    t_local = T // axis_size
    outs = []
    for s in range(axis_size):
        xs = x[s * t_local:(s + 1) * t_local]
        capacity = max(1, int(-(-t_local * capacity_factor // e_total)))
        disp_ect, disp_tec, gate = _top1_dispatch(xs, gate_w, e_total,
                                                  capacity)
        slots = jnp.einsum("ect,td->ecd", disp_ect, xs)
        h = jax.nn.relu(jnp.einsum("ecd,edh->ech", slots, w_in_full))
        out = jnp.einsum("ech,ehd->ecd", h, w_out_full)
        y = jnp.einsum("tec,ecd->td", disp_tec, out)
        outs.append(y * gate[:, None])
    return jnp.concatenate(outs, axis=0)
