"""paddle.grad partial grads, higher-order grads, TracedLayer save,
dygraph_to_static tests.

Contracts: reference test_imperative_double_grad.py (grad/second
order), test_traced_layer..., dygraph_to_static tests."""
import tempfile

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.dygraph import Linear, to_variable


class TestPartialGrad:
    def test_first_order_matches_formula(self):
        with fluid.dygraph.guard():
            x = to_variable(np.array([2.0, 3.0], dtype="float32"))
            x.stop_gradient = False
            y = fluid.layers.reduce_sum(
                fluid.layers.elementwise_mul(x, x))  # sum(x^2)
            (gx,) = fluid.dygraph.grad(y, x)
        np.testing.assert_allclose(np.asarray(gx.numpy()),
                                   [4.0, 6.0], rtol=1e-6)

    def test_does_not_touch_grad_accumulators(self):
        with fluid.dygraph.guard():
            x = to_variable(np.ones(3, dtype="float32"))
            x.stop_gradient = False
            y = fluid.layers.reduce_sum(fluid.layers.square(x))
            fluid.dygraph.grad(y, x, retain_graph=True)
            assert x._grad is None  # partial grads leave .grad alone

    def test_unreachable_input(self):
        with fluid.dygraph.guard():
            x = to_variable(np.ones(2, dtype="float32"))
            x.stop_gradient = False
            z = to_variable(np.ones(2, dtype="float32"))
            z.stop_gradient = False
            y = fluid.layers.reduce_sum(fluid.layers.square(x))
            with pytest.raises(ValueError):
                fluid.dygraph.grad(y, z)
            (gz,) = fluid.dygraph.grad(y, z, allow_unused=True,
                                       retain_graph=True)
            assert gz is None

    def test_second_order(self):
        """d2/dx2 of sum(x^3) = 6x (reference double-grad contract)."""
        with fluid.dygraph.guard():
            x = to_variable(np.array([1.0, 2.0], dtype="float32"))
            x.stop_gradient = False
            x2 = fluid.layers.elementwise_mul(x, x)
            x3 = fluid.layers.elementwise_mul(x2, x)
            y = fluid.layers.reduce_sum(x3)
            (gx,) = fluid.dygraph.grad(y, x, create_graph=True)
            gsum = fluid.layers.reduce_sum(gx)
            (ggx,) = fluid.dygraph.grad(gsum, x)
        np.testing.assert_allclose(np.asarray(ggx.numpy()),
                                   [6.0, 12.0], rtol=1e-5)


class TestTracedLayerSave:
    def test_trace_save_load_serve(self):
        from paddle_tpu.dygraph import TracedLayer
        from paddle_tpu.inference import AnalysisConfig, create_predictor

        with fluid.dygraph.guard():
            layer = Linear(4, 2)
            x = to_variable(np.random.RandomState(0).rand(
                3, 4).astype("float32"))
            outs, traced = TracedLayer.trace(layer, [x])
            ref = np.asarray(outs[0].numpy())
            # recorded program exists and contains the matmul
            types = [op.type for op in
                     traced.program.global_block().ops]
            assert "mul" in types or "matmul" in types
            with tempfile.TemporaryDirectory() as d:
                traced.save_inference_model(d)
                config = AnalysisConfig(d)
                config.disable_gpu()
                predictor = create_predictor(config)
                (out,) = predictor.run(
                    {predictor.get_input_names()[0]: x.numpy()})
        np.testing.assert_allclose(out.as_ndarray(), ref, rtol=1e-5,
                                   atol=1e-6)


class TestDygraphToStatic:
    def test_declarative_matches_eager(self):
        from paddle_tpu.dygraph import declarative

        with fluid.dygraph.guard():
            layer = Linear(4, 3, act="tanh")

            def f(x):
                return fluid.layers.reduce_sum(layer(x), dim=-1)

            static_f = declarative(f)
            x = np.random.RandomState(1).rand(2, 4).astype("float32")
            eager = f(to_variable(x)).numpy()
            static1 = static_f(to_variable(x)).numpy()
            static2 = static_f(to_variable(x)).numpy()  # cached program
        np.testing.assert_allclose(np.asarray(static1), np.asarray(eager),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(static2),
                                   np.asarray(static1), rtol=1e-6)

    def test_translator_disable_falls_back_to_eager(self):
        from paddle_tpu.dygraph import ProgramTranslator, declarative

        calls = []

        with fluid.dygraph.guard():
            @declarative
            def f(x):
                calls.append(1)
                return fluid.layers.scale(x, scale=2.0)

            x = to_variable(np.ones(2, dtype="float32"))
            ProgramTranslator().enable(False)
            try:
                out = f(x)
            finally:
                ProgramTranslator().enable(True)
        np.testing.assert_allclose(np.asarray(out.numpy()), [2.0, 2.0])

    def test_get_program(self):
        from paddle_tpu.dygraph import ProgramTranslator

        with fluid.dygraph.guard():
            def f(x):
                return fluid.layers.scale(x, scale=3.0)

            prog = ProgramTranslator().get_program(
                f, to_variable(np.ones(2, dtype="float32")))
        assert any(op.type == "scale"
                   for op in prog.global_block().ops)
