"""Collective-schedule rewrite passes (placement synthesis, ISSUE 15).

Three ``@checked_rewrite`` passes over an already-bucketed program —
the rewrite vocabulary the placement search (paddle_tpu/placement/)
enumerates over, each usable standalone via an env knob:

- **Async start/await scheduling** (``schedule_async_collectives``,
  ``PADDLE_TPU_ASYNC_COLLECTIVES=1``): each ``c_bucket_allreduce``
  splits into a ``c_bucket_allreduce_start`` op at the bucket's
  availability anchor (issuing the flat psum into a Pending buffer)
  and a ``c_bucket_allreduce_await`` op placed just before the
  earliest consumer of any member grad. Everything between the pair is
  data-independent of the collective, so overlap is SCHEDULED in the
  IR rather than left to XLA's hoisting heuristics. With a profile
  report the split is gated by measured slack: a bucket with no
  backward compute left after its anchor (a tail bucket) stays fused —
  splitting it buys nothing and costs an op.

- **Reduction-strategy swap** (``swap_reduction_strategy``,
  ``PADDLE_TPU_REDUCE_STRATEGY=ring|tree|two_stage``): re-spells every
  bucket reduction per ``ops.collective_ops.strategy_psum`` without
  moving an op. Integer (int8-code) payloads are exact under every
  spelling; float payloads may re-associate — the documented
  bit-for-bit-or-bounded contract.

- **Per-bucket quantization + EQuARX error feedback**
  (``configure_bucket_quant``, ``PADDLE_TPU_QUANT_ERROR_FEEDBACK=1``):
  overrides the ``quant`` attr per bucket op (the search decides
  int8/bf16 per bucket where wire bytes dominate) and, for quantized
  buckets under error feedback, wires a per-replica Residual var —
  dp-sharded, one rounding-error shard per replica — so the
  quantization bias cancels across steps instead of compounding.

All three register contracts in ``analysis/contracts.py``, so the
PR-12 invariant net (and ``tools/ir_mutate.py``) extends to them.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from ..analysis.contracts import checked_rewrite
from ..ops.collective_ops import REDUCTION_STRATEGIES
from .transpiler import _bump_version

__all__ = [
    "reduce_strategy_mode", "async_collectives_enabled",
    "quant_error_feedback", "swap_reduction_strategy",
    "configure_bucket_quant", "schedule_async_collectives",
    "BUCKET_OP_TYPES",
]

# ops a strategy/quant reconfiguration may touch; the await carries no
# payload and no strategy (it only slices the Pending buffer back)
BUCKET_OP_TYPES = ("c_bucket_allreduce", "c_bucket_allreduce_start")

_TRUTHY = ("1", "true", "yes", "on")


def reduce_strategy_mode() -> str:
    """``PADDLE_TPU_REDUCE_STRATEGY``: ring (default) | tree |
    two_stage."""
    raw = os.environ.get("PADDLE_TPU_REDUCE_STRATEGY", "").strip().lower()
    if raw in ("", "auto", "ring"):
        return "ring"
    if raw in REDUCTION_STRATEGIES:
        return raw
    raise ValueError("PADDLE_TPU_REDUCE_STRATEGY=%r (want one of %s)"
                     % (raw, ", ".join(REDUCTION_STRATEGIES)))


def async_collectives_enabled() -> bool:
    """``PADDLE_TPU_ASYNC_COLLECTIVES=1``: split bucket reductions into
    start/await pairs at first mesh run."""
    raw = os.environ.get("PADDLE_TPU_ASYNC_COLLECTIVES", "").strip()
    return raw.lower() in _TRUTHY


def quant_error_feedback() -> bool:
    """``PADDLE_TPU_QUANT_ERROR_FEEDBACK=1``: arm the EQuARX residual
    on quantized bucket reductions."""
    raw = os.environ.get("PADDLE_TPU_QUANT_ERROR_FEEDBACK", "").strip()
    return raw.lower() in _TRUTHY


# ---------------------------------------------------------------------------
# reduction-strategy swap
# ---------------------------------------------------------------------------


@checked_rewrite("reduction_swap")
def swap_reduction_strategy(program, strategy: str) -> int:
    """Re-spell every bucket reduction with ``strategy`` (attr-only —
    no op is added, removed, or moved; the contract pins exactly
    that). Returns the number of ops re-spelled. Idempotent in effect:
    re-applying the same strategy is a no-op version bump."""
    if strategy not in REDUCTION_STRATEGIES:
        raise ValueError("unknown reduction strategy %r (want one of %s)"
                         % (strategy, ", ".join(REDUCTION_STRATEGIES)))
    block = program.global_block()
    n = 0
    changed = False
    for op in block.ops:
        if op.type not in BUCKET_OP_TYPES:
            continue
        if op.attrs.get("strategy", "ring") != strategy:
            op.attrs["strategy"] = strategy
            changed = True
        n += 1
    if changed:
        _bump_version(program)
    return n


# ---------------------------------------------------------------------------
# per-bucket quantization + EQuARX error-feedback residuals
# ---------------------------------------------------------------------------


def _bucket_numel(block, scope, op) -> Optional[int]:
    from .collectives import _numel_and_dtype

    total = 0
    for n in op.input("X"):
        k, _dt = _numel_and_dtype(block, scope, n)
        if k is None:
            return None
        total += k
    return total


@checked_rewrite("bucket_quant")
def configure_bucket_quant(program, scope, nranks: int, axis: str,
                           modes=None, error_feedback: bool = False,
                           materialize: bool = True) -> int:
    """Reconfigure quantization on the program's bucket ops.

    ``modes``: None keeps each op's baked-in quant; a string applies
    uniformly; a sequence applies per bucket op in program order
    (shorter sequences leave the tail untouched — the search emits one
    entry per bucket). With ``error_feedback`` every bucket left
    quantized gets a Residual/ResidualOut pair bound to a fresh
    persistable var of ``nranks * bucket_numel`` zeros, sharded over
    ``axis`` — each replica owns its rounding-error shard.
    ``materialize=False`` skips writing the zero arrays into the scope
    (the placement search rewrites candidates SYMBOLICALLY — a
    resnet-scale residual per candidate would allocate hundreds of MB
    nobody ever runs; the engine's first-run path materializes).
    Returns the number of ops reconfigured or wired."""
    from ..ops.collective_ops import QUANT_WIRE_ITEMSIZE

    block = program.global_block()
    bucket_ops = [op for op in block.ops if op.type in BUCKET_OP_TYPES]
    if not bucket_ops:
        return 0
    if isinstance(modes, str):
        modes = [modes] * len(bucket_ops)
    touched = 0
    for i, op in enumerate(bucket_ops):
        if modes is not None and i < len(modes) and modes[i] is not None:
            mode = modes[i]
            if mode not in QUANT_WIRE_ITEMSIZE:
                raise ValueError("bucket %d: unknown quant mode %r"
                                 % (i, mode))
            if op.attrs.get("quant", "none") != mode:
                op.attrs["quant"] = mode
                touched += 1
        quant = op.attrs.get("quant", "none")
        has_res = bool(op.input("Residual"))
        if error_feedback and quant != "none" and not has_res:
            total = _bucket_numel(block, scope, op)
            if total is None:
                continue  # unknown payload: leave unwired, stay exact
            dtype = "float32"
            v = block._find_var_recursive(op.input("X")[0])
            if v is not None and v.dtype:
                dtype = str(v.dtype)
            rname = "bucket_ar_residual_%d" % op._id
            rv = block.create_var(name=rname,
                                  shape=(int(nranks) * int(total),),
                                  dtype=dtype, persistable=True)
            rv.stop_gradient = True
            if materialize and scope is not None:
                scope.var(rname).get_tensor()._array = np.zeros(
                    int(nranks) * int(total), dtype=np.dtype(dtype))
            specs = getattr(program, "_var_shard_specs", None)
            if specs is None:
                specs = {}
                program._var_shard_specs = specs
            specs[rname] = (axis,)
            op.inputs["Residual"] = [rname]
            op.outputs["ResidualOut"] = [rname]
            touched += 1
    if touched:
        _bump_version(program)
    return touched


# ---------------------------------------------------------------------------
# async start/await scheduling
# ---------------------------------------------------------------------------


def _measured_slack_ok(report, compute_pos, anchor_idx) -> bool:
    """With a report: does measured backward compute remain after this
    bucket's availability point? A tail bucket (budget 0) stays fused."""
    if report is None:
        return True
    segs = [s for s in (report.get("backward_segments") or [])
            if isinstance(s, (list, tuple)) and len(s) == 3]
    if not segs:
        return True
    pos = compute_pos[anchor_idx]
    return any(float(ms) > 0 and end > pos for _s, end, ms in segs)


@checked_rewrite("async_collective")
def schedule_async_collectives(program, report=None, scope=None) -> int:
    """Split each ``c_bucket_allreduce`` into a start/await pair: the
    start stays at the bucket's availability anchor, the await lands
    just before the earliest consumer of any member grad — maximal
    scheduled overlap under the consumer barrier. Buckets with no room
    (first consumer immediately follows, or the report says zero
    hideable budget at the anchor) stay fused. Returns the number of
    buckets split; the decision record lands on
    ``program._async_schedule``."""
    if getattr(program, "_async_scheduled", False):
        return 0
    program._async_scheduled = True
    from .. import framework
    from .collectives import _numel_and_dtype

    block = program.global_block()
    ops = block.ops
    cand = [i for i, op in enumerate(ops)
            if op.type == "c_bucket_allreduce"]
    if not cand:
        program._async_schedule = {"split": 0, "kept": 0}
        return 0

    # every later TOUCH bounds the await: a reader before the await
    # would see the unreduced value, and an op that WRITES a member
    # between the pair would be clobbered by the await's write-back of
    # the (stale-input) reduction
    consumed_at: Dict[str, List[int]] = {}
    for j, op in enumerate(ops):
        for nm in set(op.input_arg_names) | set(op.output_arg_names):
            consumed_at.setdefault(nm, []).append(j)
    # compute-sequence positions (the report's coordinate system)
    compute_pos = []
    k = 0
    for op in ops:
        compute_pos.append(k)
        if not op.type.startswith("c_"):
            k += 1
    if report is not None and int(report.get("n_compute") or -1) != k:
        report = None  # stale report: split on structure alone

    import bisect

    split = 0
    kept = 0
    replace_at: Dict[int, object] = {}   # bucket idx -> start op
    before: Dict[int, List] = {}         # op idx -> [await ops]
    tail: List = []                      # awaits with no consumer
    for i in cand:
        op = ops[i]
        members = op.input("X")
        first_use = len(ops)
        for g in members:
            c = consumed_at.get(g, ())
            kk = bisect.bisect_right(c, i)
            if kk < len(c):
                first_use = min(first_use, c[kk])
        total = 0
        dtype = None
        unknown = False
        for g in members:
            n_el, dt = _numel_and_dtype(block, scope, g)
            if n_el is None:
                unknown = True
                break
            total += n_el
            dtype = dtype or dt
        if (unknown or first_use <= i + 1
                or not _measured_slack_ok(report, compute_pos, i)):
            kept += 1
            continue
        pname = "bucket_ar_pending_%d" % op._id
        pv = block.create_var(name=pname, shape=(int(total),),
                              dtype=dtype or "float32")
        pv.stop_gradient = True
        attrs = {"ring_id": op.attrs.get("ring_id", 0),
                 "quant": op.attrs.get("quant", "none"),
                 "strategy": op.attrs.get("strategy", "ring"),
                 "use_calc_stream": True}
        s_in = {"X": list(members)}
        s_out = {"Pending": [pname]}
        if op.input("Residual"):
            s_in["Residual"] = list(op.input("Residual"))
            s_out["ResidualOut"] = list(op.output("ResidualOut"))
        start = framework.Operator(block, "c_bucket_allreduce_start",
                                   s_in, s_out, attrs)
        start._id = program._next_op_id()
        await_op = framework.Operator(
            block, "c_bucket_allreduce_await",
            {"Pending": [pname], "X": list(members)},
            {"Out": list(members)},
            {"ring_id": op.attrs.get("ring_id", 0),
             "use_calc_stream": True})
        await_op._id = program._next_op_id()
        replace_at[i] = start
        if first_use < len(ops):
            before.setdefault(first_use, []).append(await_op)
        else:
            tail.append(await_op)
        split += 1

    if split:
        new_ops = []
        for i, op in enumerate(ops):
            new_ops.extend(before.get(i, ()))
            new_ops.append(replace_at.get(i, op))
        new_ops.extend(tail)
        block.ops = new_ops
        _bump_version(program)
    program._async_schedule = {"split": split, "kept": kept}
    from .. import observability as _obs

    _obs.inc("parallel.async_buckets", split, state="split")
    if kept:
        _obs.inc("parallel.async_buckets", kept, state="kept")
    return split
