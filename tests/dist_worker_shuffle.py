"""Worker for the multi-process global-shuffle test: each of two worker
processes loads its OWN file shard (labels tag the origin worker), runs
Dataset.global_shuffle — records migrate between processes through
distributed/record_shuffle — and writes the labels it ended up owning.
"""
import json
import os
import sys

import numpy as np

import paddle_tpu as fluid


def main():
    out_path, data_file = sys.argv[1], sys.argv[2]
    B = 2
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.data(name="x", shape=[B, 4], dtype="float32")
        y = fluid.data(name="y", shape=[B, 1], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(B)
    ds.set_use_var([x, y])
    ds.set_filelist([data_file])
    ds.load_into_memory()
    before = sorted(int(np.asarray(r["y"]).ravel()[0])
                    for r in ds._records)
    ds.global_shuffle()
    after = sorted(int(np.asarray(r["y"]).ravel()[0])
                   for r in ds._records)
    with open(out_path, "w") as f:
        f.write(json.dumps({"before": before, "after": after}))


if __name__ == "__main__":
    main()
