"""Lazy (queued) eager execution — async/batched dygraph dispatch.

Parity intent: the reference attacks per-op eager overhead with
generated C++ fast paths (pybind/op_function_generator.cc); on TPU the
cost is not Python but PER-OP DEVICE DISPATCH — through a remote
tunnel each eager op is a ~10ms round trip, so a ~40-op training step
pays ~40 RTTs (BASELINE.md round-4 dygraph row). The TPU-native fix is
the lazy-tensor pattern (torch/XLA's mark_step): ops queue into a
graph of LazyNodes; VarBase arrays become PendingValues; a FLUSH
compiles the queued graph into ONE jitted XLA call (cached by graph
structure, so steady-state training is one dispatch per step) and
materializes only values still referenced by live VarBases.

Flush triggers: any host read (``numpy()``/``float``/``__array__``),
``optimizer.minimize`` (the natural step boundary — like mark_step),
program recording, or a node-count safety valve.

Enable with ``fluid.dygraph.guard(lazy=True)`` or
``FLAGS_dygraph_lazy=true``.
"""
from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PendingValue", "LazyEngine", "is_pending", "aval_of",
           "plan_lazy_policy", "apply_lazy_policy", "JIT_CACHE_CAP_MAX"]

_obs_cache: List = []


def _obs():
    """Lazy module ref (importing per flush is cheap, but force() sits
    on value-read paths; mirror executor_core's cached-ref pattern)."""
    if not _obs_cache:
        from .. import observability

        _obs_cache.append(observability)
    return _obs_cache[0]


def is_pending(x) -> bool:
    return isinstance(x, PendingValue)


_sds_memo: Dict = {}


def _sds(shape, dtype):
    """Memoized jax.ShapeDtypeStruct — construction dominates the
    per-op host cost at BERT scale (jax __setattr__ checks x thousands
    of ops/step), and the distinct (shape, dtype) set is tiny."""
    key = (shape, dtype)
    s = _sds_memo.get(key)
    if s is None:
        import jax

        s = jax.ShapeDtypeStruct(shape, dtype)
        if len(_sds_memo) < 4096:
            _sds_memo[key] = s
    return s


def aval_of(h):
    """jax.ShapeDtypeStruct of a handle (concrete array or pending)."""
    if isinstance(h, PendingValue):
        return h.aval
    return _sds(tuple(np.shape(h)), h.dtype)


class PendingValue:
    """Placeholder for a not-yet-computed array. Duck-types the shape/
    dtype surface so shape-reading code works without forcing; any
    value read (``__array__``) forces a flush."""

    __slots__ = ("aval", "value", "_resolved", "engine", "_owners",
                 "_pinned", "__weakref__")

    def __init__(self, aval, engine):
        self.aval = aval          # jax.ShapeDtypeStruct
        self.value = None
        self._resolved = False
        self.engine = engine
        self._owners: List = []   # [(weakref(obj), attr or None)]
        self._pinned = False      # force() in flight: must materialize

    # -- shape surface ----------------------------------------------------
    @property
    def shape(self):
        return tuple(self.aval.shape)

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def size(self):
        n = 1
        for s in self.aval.shape:
            n *= s
        return n

    # -- ownership (decides what a flush must materialize) ----------------
    def add_owner(self, obj, attr: Optional[str]):
        """attr None means "needed while obj is alive" (tape records);
        otherwise needed while ``getattr(obj, attr) is self``."""
        self._owners.append((weakref.ref(obj), attr))

    def is_needed(self) -> bool:
        if self._pinned:
            return True
        for ref, attr in self._owners:
            o = ref()
            if o is None:
                continue
            if attr is None or getattr(o, attr, None) is self:
                return True
        return False

    # -- forcing ----------------------------------------------------------
    def force(self):
        if not self._resolved:
            # pin BEFORE flushing: a value held only by local dicts
            # (mid-backward cotangents on a mixed eager/lazy tape) has
            # no VarBase owner, but the very act of forcing proves it
            # is needed — without the pin the flush would skip its
            # materialization and the read below would hit the
            # "dead at flush time" RuntimeError
            self._pinned = True
            self.engine.flush()
        if not self._resolved:
            raise RuntimeError("pending value did not resolve on flush")
        if self.value is None:
            raise RuntimeError(
                "pending value was dead at flush time (no live owner) "
                "but was read later — please report")
        return self.value

    def __array__(self, dtype=None):
        a = np.asarray(self.force())
        return a.astype(dtype) if dtype is not None else a

    def __repr__(self):
        return "PendingValue(shape=%s, dtype=%s, resolved=%s)" % (
            self.shape, self.dtype, self._resolved)


class _LazyNode:
    __slots__ = ("fn", "ins", "outs", "sig")

    def __init__(self, fn, ins, outs, sig):
        self.fn = fn      # list of arrays -> tuple of arrays
        self.ins = ins    # handles: concrete arrays or PendingValues
        self.outs = outs  # [PendingValue]
        self.sig = sig    # structural signature (hashable)


class LazyEngine:
    """Queue of LazyNodes + structure-keyed jit cache."""

    MAX_NODES = 4000      # safety valve: auto-flush beyond this
    JIT_CACHE_CAP = 64

    def __init__(self):
        self.nodes: List[_LazyNode] = []
        self._jit_cache: "OrderedDict" = OrderedDict()
        self._flushing = False
        # optimizer-op shape cache (backward_utils._lazy_opt_op)
        self._opt_aval_cache: Dict = {}

    # -- graph building ---------------------------------------------------
    def add_node(self, fn, in_handles, out_avals, sig) -> List[PendingValue]:
        outs = [PendingValue(a, self) for a in out_avals]
        self.nodes.append(_LazyNode(fn, list(in_handles), outs, sig))
        if len(self.nodes) >= self.MAX_NODES:
            # safety valve mid-structure: owners are not attached yet
            # (the caller binds outs to VarBases AFTER add_node), and
            # mid-backward cotangent handles live only in local dicts —
            # liveness is unknowable here, so materialize EVERYTHING
            self.flush(conservative=True)
        return outs

    def constant_node(self, make, aval, sig) -> PendingValue:
        """Zero-input node (ones/zeros seeds etc.)."""
        return self.add_node(lambda vals: (make(),), [], [aval], sig)[0]

    def binop_node(self, fn, a, b, sig_kind) -> PendingValue:
        """Elementwise two-arg node (e.g. gradient accumulation) —
        shared by BasicEngine._backward_lazy and
        PartialGradEngine._run_lazy."""
        av = aval_of(a)
        return self.add_node(lambda vals: (fn(vals[0], vals[1]),),
                             [a, b], [av],
                             (sig_kind, tuple(av.shape),
                              str(av.dtype)))[0]

    def add(self, a, b) -> PendingValue:
        return self.binop_node(lambda x, y: x + y, a, b, "grad_add")

    def ones_like(self, h) -> PendingValue:
        import jax.numpy as jnp

        av = aval_of(h)
        return self.constant_node(
            lambda: jnp.ones(av.shape, av.dtype), av,
            ("ones", tuple(av.shape), str(av.dtype)))

    def zeros_like(self, h) -> PendingValue:
        import jax.numpy as jnp

        av = aval_of(h)
        return self.constant_node(
            lambda: jnp.zeros(av.shape, av.dtype), av,
            ("zeros", tuple(av.shape), str(av.dtype)))

    # -- flush ------------------------------------------------------------
    def flush(self, conservative=False):
        if self._flushing or not self.nodes:
            return
        self._flushing = True
        try:
            self._flush_impl(conservative)
        finally:
            self._flushing = False

    def _flush_impl(self, conservative=False):
        import jax

        obs = _obs()
        if obs.enabled():
            obs.inc("lazy.flushes")
            obs.observe("lazy.graph_nodes", len(self.nodes))
        nodes, self.nodes = self.nodes, []
        pos: Dict[int, Tuple[int, int]] = {}
        for ni, nd in enumerate(nodes):
            for oj, p in enumerate(nd.outs):
                pos[id(p)] = (ni, oj)

        ext: List = []
        ext_ids: Dict[int, int] = {}
        wiring: List[Tuple] = []
        sig_parts: List = []
        for nd in nodes:
            w = []
            for h in nd.ins:
                if isinstance(h, PendingValue) and not h._resolved:
                    # unresolved ⇒ produced in THIS batch (every prior
                    # flush resolves all of its pendings)
                    w.append(("n",) + pos[id(h)])
                    continue
                if isinstance(h, PendingValue):
                    h = h.force()   # raises if dead-at-flush
                k = ext_ids.get(id(h))
                if k is None:
                    k = len(ext)
                    ext_ids[id(h)] = k
                    ext.append(h)
                w.append(("e", k))
            wiring.append(tuple(w))
            sig_parts.append((nd.sig, tuple(w)))

        needed = tuple(sorted(
            pos[id(p)]
            for nd in nodes for p in nd.outs
            if conservative or p.is_needed()))
        ext_avals = tuple(
            (tuple(np.shape(a)), str(getattr(a, "dtype", type(a))))
            for a in ext)
        key = (tuple(sig_parts), needed, ext_avals)

        fn = self._jit_cache.get(key)
        if fn is not None:
            self._jit_cache.move_to_end(key)
            obs.inc("lazy.cache_hits")
        else:
            from ..analysis import verify_enabled as _verify_enabled

            if _verify_enabled():
                # flush graphs are the lazy path's "rewritten program":
                # structurally verify the wiring before jitting it
                from ..analysis import verify_lazy_graph

                verify_lazy_graph(wiring,
                                  [len(nd.outs) for nd in nodes],
                                  len(ext), needed)
            # a structural cache miss == a retrace + XLA recompile of
            # the whole queued step: the metric that catches signature
            # churn (varying shapes/attrs) killing steady-state perf
            obs.inc("lazy.recompiles")
            node_fns = tuple(nd.fn for nd in nodes)
            wiring_t = tuple(wiring)
            needed_t = needed

            def replay(ext_vals):
                results: List = []
                for nf, w in zip(node_fns, wiring_t):
                    vals = [ext_vals[e[1]] if e[0] == "e"
                            else results[e[1]][e[2]] for e in w]
                    results.append(nf(vals))
                return tuple(results[ni][oj] for (ni, oj) in needed_t)

            fn = jax.jit(replay)
            self._jit_cache[key] = fn
            while len(self._jit_cache) > self.JIT_CACHE_CAP:
                self._jit_cache.popitem(last=False)

        with obs.tracing.span("lazy/flush", cat="step",
                              nodes=len(nodes)):
            out_vals = fn(ext)
        by_pos = dict(zip(needed, out_vals))
        for ni, nd in enumerate(nodes):
            for oj, p in enumerate(nd.outs):
                p.value = by_pos.get((ni, oj))
                p._resolved = True
                p._owners = []


# -- recompile-vs-reuse policy steering (self-driving runtime) --------------
#
# The structural jit cache trades memory for retraces: a cap smaller
# than the program's working set of flush signatures turns steady
# state into an eviction→recompile treadmill (lazy.recompiles grows,
# lazy.cache_hits stalls). The steering daemon watches that ratio;
# this steerer turns it into a plan {"jit_cache_cap": N} the canary
# can try on one replica before the fleet adopts it.

JIT_CACHE_CAP_MAX = 512


def plan_lazy_policy(recompiles, cache_hits, cache_cap=None):
    """Propose a jit-cache cap from observed recompile/hit counts:
    double the cap (bounded by ``JIT_CACHE_CAP_MAX``) while recompiles
    dominate AND exceed the cap (signature working set larger than the
    cache); keep it otherwise."""
    cap = int(cache_cap if cache_cap is not None
              else LazyEngine.JIT_CACHE_CAP)
    r, h = max(0, int(recompiles)), max(0, int(cache_hits))
    total = r + h
    frac = (r / total) if total else 0.0
    new_cap = cap
    if total and frac > 0.5 and r > cap:
        new_cap = min(JIT_CACHE_CAP_MAX, cap * 2)
    return {"jit_cache_cap": new_cap, "prev_cap": cap,
            "recompile_frac": round(frac, 6),
            "recompiles": r, "cache_hits": h}


def _steer_lazy_policy(report, recompiles=None, cache_hits=None,
                       cache_cap=None, **_ctx):
    """``report → plan`` steerer: counts come from context (the daemon
    reads them off the merged counters); falls back to the live
    process registry so a manual ``steer("lazy_policy", None)`` works
    inside a running job."""
    if recompiles is None or cache_hits is None:
        obs = _obs()
        recompiles = obs.counter_value("lazy.recompiles")
        cache_hits = obs.counter_value("lazy.cache_hits")
    return plan_lazy_policy(recompiles, cache_hits,
                            cache_cap=cache_cap)


def apply_lazy_policy(plan, engine_cls=None):
    """Install a promoted policy plan: sets the (class-level) jit
    cache cap. The canary's apply/rollback hooks call this with the
    proposed and the incumbent plan respectively."""
    cls = engine_cls or LazyEngine
    cap = int(plan["jit_cache_cap"])
    if not 1 <= cap <= JIT_CACHE_CAP_MAX:
        raise ValueError("jit_cache_cap %d outside [1, %d]"
                         % (cap, JIT_CACHE_CAP_MAX))
    cls.JIT_CACHE_CAP = cap
    return cap


from ..observability import steering as _steering  # noqa: E402

_steering.register_steerer(
    "lazy_policy", _steer_lazy_policy,
    "recompile-vs-reuse jit-cache policy from flush counters (ISSUE 16)")
