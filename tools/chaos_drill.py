"""Chaos drill: seeded randomized fault schedules against the
replicated PS job, gated on the bit-for-bit dedup invariant.

Each drill derives, from one seed, a randomized schedule:

- a random ``PADDLE_TPU_FAULTS`` plan (``fault.random_plan`` — the
  recoverable drop/dup/delay menu),
- a random SIGKILL of one trainer at a random round (supervised
  relaunch + checkpoint resume), and
- a random SIGKILL of the PRIMARY pserver at a random round
  (client failover to the backup + replay + server rejoin).

It then runs the 2-trainer / 2-server sync job under the launch
supervisor and asserts the final params match the CLEAN single-server
computation bit-for-bit: retry + ``(cid, round, seq)`` dedup +
replication watermark must make every gradient count exactly once, no
matter which frames the injector ate and which processes died.

The schedule is a pure function of the seed (``make_schedule``), so a
failing drill replays exactly: rerun with the printed seed.

Each drill also runs with ``PADDLE_TPU_METRICS_DIR`` armed and gates
on the job's merged telemetry (ISSUE 5): a job-level ``metrics.json``
and merged chrome-trace ``trace.json`` must exist, the injected faults
and the backup promotion must be visible in them, and the kill ->
failover (``ps.failovers`` span) -> promotion -> first-applied-round
chain must read in causal order across >= 3 processes
(``check_telemetry``; the human-readable version is printed via
``tools/ft_timeline.py``).

Usage: python tools/chaos_drill.py [--rounds 1] [--sync-rounds 6]
       [--seed 1234]

``--rounds`` is the number of randomized drills (CI runs 1);
``--sync-rounds`` is the training length of each drill.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import socket
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dist_worker_ft.py")
if REPO not in sys.path:  # script-dir sys.path[0] is tools/
    sys.path.insert(0, REPO)
_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:  # imported by tests, not only run directly
    sys.path.insert(0, _TOOLS)

import ft_timeline  # noqa: E402 — the cross-process postmortem
from ft_smoke import oracle_w  # noqa: E402 — ONE bit-for-bit oracle


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def make_schedule(seed: int, sync_rounds: int = 6) -> dict:
    """The randomized fault schedule as a pure function of the seed —
    two calls with the same seed MUST return the same dict (asserted
    by tests/test_fault_tolerance.py)."""
    from paddle_tpu.distributed import fault

    rng = random.Random(int(seed))
    hi = max(1, int(sync_rounds) - 1)
    return {
        "seed": int(seed),
        "sync_rounds": int(sync_rounds),
        "plan": fault.random_plan(rng),
        "trainer_kill_rank": rng.randint(0, 1),
        "trainer_kill_round": rng.randint(1, hi),
        "server_kill_round": rng.randint(1, hi),
    }


def _env(sched: dict, tmp: str, eps: str) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PADDLE_PS_HEARTBEAT_MS", None)
    env.update({
        "FT_ROLE": "trainer",
        "PSERVER_ENDPOINT": eps,
        "FT_ROUNDS": str(sched["sync_rounds"]),
        "FT_DIE_AT_ROUND": str(sched["trainer_kill_round"]),
        "FT_DIE_RANK": str(sched["trainer_kill_rank"]),
        "FT_SERVER_DIE_AT_ROUND": str(sched["server_kill_round"]),
        "FT_OUT": os.path.join(tmp, "out"),
        "FT_CKPT_ROOT": os.path.join(tmp, "ckpt"),
        "PADDLE_TPU_FAULTS": sched["plan"],
        "PADDLE_TPU_FAULT_SEED": str(sched["seed"]),
        # the drill is gated on BIT-FOR-BIT parity with the clean run:
        # eviction deliberately trades exactness for availability
        # (survivor-only rounds diverge from the 2-trainer oracle), so
        # it is OFF here — the supervisor guarantees every death is
        # followed by a relaunch, and the sync barrier simply waits
        # for the relaunched rank to re-send its round (the dedup
        # keyed pending buffer makes the re-send idempotent)
        "PADDLE_PS_EVICT_AFTER": "0",
        # faults must be absorbed by RETRY, never converted into a
        # spurious failover off a healthy primary: a deep per-endpoint
        # retry budget keeps P(exhaustion by injected drops) ~ 0 while
        # a genuinely dead server still fails fast (conn refused)
        "PADDLE_PS_RPC_RETRIES": "12",
        "PADDLE_PS_RPC_BACKOFF_MS": "30",
        # short per-attempt deadline: a server-side recv.drop eats the
        # request frame, and only this deadline converts that silence
        # into a retry — at the default (round timeout + 30s) one
        # dropped frame would stall the whole round into eviction
        # territory. Retried barriers are safe: the dedup cache parks
        # the duplicate on the in-flight original. 12 x 8s also covers
        # every LEGITIMATE block (a barrier waiting out a ~3s relaunch)
        "PADDLE_PS_RPC_DEADLINE": "8",
        "PADDLE_PS_CONNECT_TIMEOUT": "4",
        "PADDLE_PS_FAILOVER_CONNECT_TIMEOUT": "3",
        "PADDLE_PS_REPL_DEADLINE": "5",
        # job-level telemetry: every process dumps registry + spans +
        # flight ring here (dir implies metrics armed); a short cadence
        # so even a SIGKILLed process leaves a fresh black box, and the
        # launch supervisor merges the lot into metrics.json +
        # trace.json at job end
        "PADDLE_TPU_METRICS_DIR": os.path.join(tmp, "metrics"),
        "PADDLE_TPU_DUMP_PERIOD": "0.5",
    })
    return env


def run_drill(sched: dict) -> int:
    tmp = tempfile.mkdtemp(prefix="chaos_drill_")
    eps = "127.0.0.1:%d,127.0.0.1:%d" % (_free_port(), _free_port())
    print("[chaos] schedule %s" % json.dumps(sched, sort_keys=True))
    sup = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node=2", "--max_restarts=3",
         "--started_port=%d" % _free_port(),
         "--server_script=%s" % WORKER,
         "--pserver_endpoints=%s" % eps, WORKER],
        env=_env(sched, tmp, eps), timeout=420, cwd=REPO)
    if sup.returncode != 0:
        print("[chaos] FAIL: job exited %d under schedule seed=%d "
              "(rerun: tools/chaos_drill.py --seed %d --sync-rounds %d)"
              % (sup.returncode, sched["seed"], sched["seed"],
                 sched["sync_rounds"]))
        return 1
    expected = oracle_w(sched["sync_rounds"])
    ok = True
    for tid in (0, 1):
        r = json.load(open(os.path.join(tmp, "out.t%d.json" % tid)))
        got = np.asarray(r["w"], dtype=np.float32)
        bitwise = got.tobytes() == expected.tobytes()
        print("[chaos] %s: trainer %d params %s the clean run "
              "(failovers=%s, evictions=%s)"
              % ("PASS" if bitwise else "FAIL", tid,
                 "match" if bitwise else "DIVERGE FROM",
                 r.get("failovers"), r.get("evictions")))
        ok = ok and bitwise
    ok = check_telemetry(sched, os.path.join(tmp, "metrics")) and ok
    if not ok:
        print("[chaos] reproduce with: tools/chaos_drill.py --seed %d "
              "--sync-rounds %d" % (sched["seed"], sched["sync_rounds"]))
    return 0 if ok else 1


def check_telemetry(sched: dict, mdir: str) -> bool:
    """The drill's second gate (ISSUE 5): the job must leave ONE merged
    picture in which the primary's kill, the trainers' failover
    (``ps.failovers`` span), and the promoted backup's first applied
    round are visible in causal order across >= 3 processes — and the
    injected faults must show up in it."""
    ok = True

    def chk(what, passed):
        nonlocal ok
        print("[chaos] %s: %s" % ("PASS" if passed else "FAIL", what))
        ok = ok and passed

    # the postmortem itself (also re-merges metrics.json + trace.json)
    ft_timeline.print_postmortem(mdir, limit=40)
    mpath = os.path.join(mdir, "metrics.json")
    tpath = os.path.join(mdir, "trace.json")
    chk("job-level metrics.json + trace.json merged",
        os.path.exists(mpath) and os.path.exists(tpath))
    if not ok:
        return False
    merged = json.load(open(mpath))
    chk("merged metrics preserve per-rank sections (%d processes)"
        % len(merged["processes"]), len(merged["processes"]) >= 4)
    n_faults = sum(v for k, v in merged["counters_total"].items()
                   if k.startswith("fault.injected"))
    chk("injected faults visible in merged counters (%d)" % n_faults,
        n_faults > 0)
    trace = json.load(open(tpath))
    names = {}
    for ev in trace.get("traceEvents", []):
        names.setdefault(ev.get("name"), []).append(ev)
    chk("merged timeline has injected-fault events",
        bool(names.get("fault.injected")))
    chk("merged timeline has the promotion event",
        bool(names.get("ps.promotion")))
    chk("merged timeline has the ps.failovers span",
        any(ev.get("ph") == "X"
            for ev in names.get("ps.failovers", [])))

    # causal chain: kill -> failover -> promotion -> first applied
    # round on the promoted backup, across >= 3 distinct processes
    events = ft_timeline.load_events(mdir)

    def first(pred):
        for e in events:
            if pred(e):
                return e
        return None

    kill = first(lambda e: e["kind"] == "launch.exit"
                 and e["fields"].get("role") == "pserver"
                 and e["fields"].get("signal") == 9)
    fo = first(lambda e: e["kind"] == "rpc.failover.begin"
               and e["proc"].startswith("trainer"))
    promo = first(lambda e: e["kind"] == "ps.promotion")
    chk("supervisor observed the primary's SIGKILL", kill is not None)
    chk("a trainer failed over", fo is not None)
    chk("a backup was promoted", promo is not None)
    if not ok:
        return False
    applied = first(lambda e: e["kind"] == "ps.round_applied"
                    and e["proc"] == promo["proc"]
                    and e["fields"].get("round")
                    == sched["server_kill_round"]
                    and e["t_us"] > promo["t_us"])
    chk("promoted backup (%s) applied the killed round %d"
        % (promo["proc"], sched["server_kill_round"]),
        applied is not None)
    if applied is not None:
        chk("causal order: failover < promotion < first applied round",
            fo["t_us"] < promo["t_us"] < applied["t_us"])
        procs = {fo["proc"], promo["proc"], applied["proc"],
                 kill["proc"]}
        chk("chain spans >= 3 processes (%s)" % sorted(procs),
            len(procs) >= 3)
    return ok


def main() -> int:
    ap = argparse.ArgumentParser("chaos_drill")
    ap.add_argument("--rounds", type=int, default=1,
                    help="number of randomized drills to run")
    ap.add_argument("--sync-rounds", type=int, default=6,
                    help="training rounds per drill")
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("PADDLE_TPU_FAULT_SEED",
                                               "1234")),
                    help="base seed (drill i uses seed + i)")
    args = ap.parse_args()
    rc = 0
    for i in range(args.rounds):
        rc |= run_drill(make_schedule(args.seed + i, args.sync_rounds))
    if rc == 0:
        print("[chaos] ALL %d DRILL(S) PASS" % args.rounds)
    return rc


if __name__ == "__main__":
    sys.exit(main())
