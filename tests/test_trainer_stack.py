"""Trainer / DeviceWorker stack tests.

Parity: /root/reference/paddle/fluid/framework/trainer.h:38,
device_worker.h:111, trainer_desc.proto:21 and the
train_from_dataset path (python executor.py:1187). Multi-worker
Hogwild training over dataset shards, TrainerDesc plumbing, and the
dump_fields debug output.
"""
import os
import tempfile

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.trainer_factory import (HogwildWorker, MultiTrainer,
                                        TrainerDesc, TrainerFactory)


def _write_multislot(path, n, seed=0):
    """x: 4 floats whose sum decides y (learnable mapping)."""
    rng = np.random.RandomState(seed)
    with open(path, "w") as f:
        for _ in range(n):
            x = rng.rand(4)
            y = int(x.sum() > 2.0)
            f.write("4 " + " ".join("%.6f" % v for v in x)
                    + " 1 %d\n" % y)


def _program(B):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[B, 4], dtype="float32")
        y = fluid.data(name="y", shape=[B, 1], dtype="int64")
        pred = fluid.layers.fc(x, 2, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
        fluid.optimizer.SGD(0.2).minimize(loss)
    return main, startup, x, y, loss


def _dataset(files, vars_, B):
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(B)
    ds.set_use_var(list(vars_))
    ds.set_filelist(list(files))
    return ds


class TestSharding:
    def test_file_shards_are_disjoint_and_complete(self):
        with tempfile.TemporaryDirectory() as d:
            files = []
            for i in range(4):
                p = os.path.join(d, "part-%d" % i)
                _write_multislot(p, 8, seed=i)
                files.append(p)
            B = 4
            main, startup, x, y, loss = _program(B)
            ds = _dataset(files, [x, y], B)
            shards = ds._iter_batches_sharded(2)
            assert len(shards) == 2
            counts = [sum(1 for _ in s) for s in shards]
            assert counts == [4, 4]  # 2 files x 8 rows / batch 4 each

    def test_more_workers_than_files_caps(self):
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "part-0")
            _write_multislot(p, 8)
            main, startup, x, y, loss = _program(4)
            ds = _dataset([p], [x, y], 4)
            shards = ds._iter_batches_sharded(8)
            assert len(shards) == 1


class TestMultiTrainer:
    def _run(self, thread, dump_path=None):
        with tempfile.TemporaryDirectory() as d:
            files = []
            for i in range(4):
                p = os.path.join(d, "part-%d" % i)
                _write_multislot(p, 32, seed=i)
                files.append(p)
            B = 8
            main, startup, x, y, loss = _program(B)
            if dump_path:
                main._fleet_opt = {
                    "dump_fields": [loss.name],
                    "dump_fields_path": dump_path,
                }
            ds = _dataset(files, [x, y], B)
            scope = fluid.Scope()
            exe = fluid.Executor(fluid.TPUPlace(0))
            with fluid.scope_guard(scope):
                exe.run(startup)
                w = main.global_block().all_parameters[0].name
                before = np.asarray(scope.find_var(w).raw().array).copy()
                stats = exe.train_from_dataset(
                    main, ds, thread=thread, fetch_list=[loss])
                after = np.asarray(scope.find_var(w).raw().array)
            return stats, before, after

    def test_single_worker_trains(self):
        stats, before, after = self._run(thread=1)
        assert stats["total_steps"] == 16  # 4 files x 32 rows / B8
        assert not np.allclose(before, after)

    def test_two_workers_share_params_hogwild(self):
        stats, before, after = self._run(thread=2)
        assert stats["total_steps"] == 16
        assert len(stats["steps_per_worker"]) == 2
        assert all(s == 8 for s in stats["steps_per_worker"])
        assert not np.allclose(before, after)

    def test_dump_fields_written_per_worker(self):
        with tempfile.TemporaryDirectory() as dump:
            stats, _, _ = self._run(thread=2, dump_path=dump)
            files = sorted(os.listdir(dump))
            assert files == ["worker_0.txt", "worker_1.txt"]
            lines = open(os.path.join(dump, "worker_1.txt")).read()
            assert "mean" in lines or "\t" in lines
            assert len(lines.strip().splitlines()) > 0


class TestTrainerDesc:
    def test_factory_rejects_unknown_class(self):
        import pytest

        desc = TrainerDesc()
        desc.class_name = "NoSuchTrainer"
        with pytest.raises(ValueError):
            TrainerFactory().create_trainer(desc)

    def test_worker_class_from_fleet_opt(self):
        desc = TrainerDesc()
        desc.device_worker = "Downpour"
        trainer = TrainerFactory().create_trainer(desc)
        assert isinstance(trainer, MultiTrainer)

    def test_infer_from_dataset_does_not_mutate(self):
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "part-0")
            _write_multislot(p, 32)
            B = 8
            main, startup, x, y, loss = _program(B)
            ds = _dataset([p], [x, y], B)
            scope = fluid.Scope()
            exe = fluid.Executor(fluid.TPUPlace(0))
            with fluid.scope_guard(scope):
                exe.run(startup)
                w = main.global_block().all_parameters[0].name
                before = np.asarray(scope.find_var(w).raw().array).copy()
                exe.infer_from_dataset(main, ds, thread=2)
                after = np.asarray(scope.find_var(w).raw().array)
            np.testing.assert_array_equal(before, after)
