"""WMT16 en-de reader creators (reference
python/paddle/dataset/wmt16.py).

Sample contract: (src_ids, trg_ids, trg_ids_next) with per-language
dict sizes and <s>/<e>/<unk> = 0/1/2. Synthetic fallback mirrors
wmt14's toy translation with distinct vocab sizes per side.
"""
from __future__ import annotations

import os
import tarfile

import numpy as np

from .common import DATA_HOME

__all__ = ["train", "test", "validation", "get_dict"]

UNK_IDX = 2


def _archive():
    p = os.path.join(DATA_HOME, "wmt16", "wmt16.tar.gz")
    return p if os.path.exists(p) else None


def _synthetic_pairs(n, seed, src_size, trg_size):
    rng = np.random.RandomState(seed)
    s_usable = max(4, min(src_size, 40) - 3)
    t_usable = max(4, min(trg_size, 40) - 3)
    for _ in range(n):
        length = int(rng.randint(3, 9))
        src = [int(rng.randint(3, 3 + s_usable)) for _ in range(length)]
        trg = [3 + ((t - 3 + 2) % t_usable) for t in src]
        yield src, [0] + trg, trg + [1]


def _reader(split, src_dict_size, trg_dict_size, src_lang, n, seed):
    def reader():
        if _archive() is None:
            yield from _synthetic_pairs(n, seed, src_dict_size,
                                        trg_dict_size)
            return
        src_dict = get_dict(src_lang, src_dict_size, reverse=False)
        trg_lang = "de" if src_lang == "en" else "en"
        trg_dict = get_dict(trg_lang, trg_dict_size, reverse=False)
        with tarfile.open(_archive(), mode="r") as f:
            name = next(n2 for n2 in f.getnames() if split in n2)
            for line in f.extractfile(name):
                cols = line.decode("utf-8").strip().split("\t")
                if len(cols) != 2:
                    continue
                src_col = 0 if src_lang == "en" else 1
                src = [src_dict.get(w, UNK_IDX)
                       for w in cols[src_col].split()]
                trg = [trg_dict.get(w, UNK_IDX)
                       for w in cols[1 - src_col].split()]
                yield src, [0] + trg, trg + [1]

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("train", src_dict_size, trg_dict_size, src_lang,
                   2000, seed=70)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("test", src_dict_size, trg_dict_size, src_lang,
                   200, seed=71)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("val", src_dict_size, trg_dict_size, src_lang,
                   200, seed=72)


def get_dict(lang, dict_size, reverse=False):
    if _archive() is not None:
        with tarfile.open(_archive(), mode="r") as f:
            name = next(n for n in f.getnames()
                        if ("vocab_%s" % lang) in n)
            d = {"<s>": 0, "<e>": 1, "<unk>": 2}
            for line in f.extractfile(name):
                if len(d) >= dict_size:
                    break
                d[line.decode("utf-8").strip()] = len(d)
    else:
        usable = max(4, min(dict_size, 40))
        d = {"<s>": 0, "<e>": 1, "<unk>": 2}
        for i in range(3, usable):
            d["%s%d" % (lang, i)] = i
    if reverse:
        d = {v: k for k, v in d.items()}
    return d
