"""Pallas TPU kernels (hand-scheduled hot ops).

XLA fusion covers most of the op corpus; kernels live here only where
hand control of VMEM streaming beats the compiler — attention is the
canonical case (reference counterpart: the hand-fused CUDA kernels
under operators/fused/, e.g. multihead_matmul_op.cu and
math/bert_encoder_functor.cu).
"""
from .flash_attention import flash_attention  # noqa: F401
