"""RNN layer APIs.

Parity: /root/reference/python/paddle/fluid/layers/rnn.py
(dynamic_lstm :1860, lstm :2017, dynamic_gru :2395, gru_unit :2548,
lstm_unit :2921). The LoD variants keep the reference's pre-projected
input contract ([T, 4*size] / [T, 3*size]); the dense ``lstm`` packs
per-(layer, direction) weights into one flat parameter consumed by the
scan-stack op (gate order candidate/input/forget/output, matching
operators/math/detail/lstm_cpu_kernel.h).
"""
from __future__ import annotations

from collections import namedtuple as _namedtuple

from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer

__all__ = ["dynamic_lstm", "dynamic_gru", "lstm", "StaticRNN"]


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """LoD LSTM; ``input`` is the pre-projected [T, 4*size//4] sequence.
    Returns (hidden, cell), both LoD-preserving."""
    helper = LayerHelper("lstm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d = size // 4
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[d, 4 * d], dtype=dtype)
    bias_size = [1, 7 * d] if use_peepholes else [1, 4 * d]
    bias = helper.create_parameter(attr=helper.bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(
        "lstm", inputs=inputs,
        outputs={"Hidden": [hidden], "Cell": [cell]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation},
        infer_shape=False)
    hidden.shape = input.shape[:-1] + (d,)
    cell.shape = input.shape[:-1] + (d,)
    hidden.lod_level = input.lod_level
    cell.lod_level = input.lod_level
    return hidden, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False,
                name=None):
    """LoD GRU; ``input`` is the pre-projected [T, 3*size] sequence."""
    helper = LayerHelper("gru", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = helper.input_dtype()
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(attr=helper.bias_attr,
                                   shape=[1, 3 * size], dtype=dtype,
                                   is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(
        "gru", inputs=inputs, outputs={"Hidden": [hidden]},
        attrs={"activation": candidate_activation,
               "gate_activation": gate_activation,
               "is_reverse": is_reverse, "origin_mode": origin_mode},
        infer_shape=False)
    hidden.shape = input.shape[:-1] + (size,)
    hidden.lod_level = input.lod_level
    return hidden


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Dense multi-layer (bi)LSTM over [T, N, D] (reference layers.lstm,
    cudnn-backed there). Returns (out, last_h, last_c)."""
    helper = LayerHelper("cudnn_lstm", input=input, name=name)
    dtype = helper.input_dtype()
    ndir = 2 if is_bidirec else 1
    in_size = input.shape[-1]
    n_weight = 0
    din = in_size
    for layer in range(num_layers):
        for _ in range(ndir):
            n_weight += din * 4 * hidden_size + hidden_size * 4 * hidden_size
            n_weight += 4 * hidden_size
        din = hidden_size * ndir
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[n_weight], dtype=dtype,
        default_initializer=default_initializer)
    out = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "cudnn_lstm",
        inputs={"Input": [input], "InitH": [init_h], "InitC": [init_c],
                "W": [weight]},
        outputs={"Out": [out], "LastH": [last_h], "LastC": [last_c]},
        attrs={"max_len": max_len, "hidden_size": hidden_size,
               "num_layers": num_layers, "is_bidirec": is_bidirec,
               "dropout_prob": dropout_prob, "is_test": is_test,
               "input_size": in_size, "seed": seed},
        infer_shape=False)
    t, n = input.shape[0], input.shape[1]
    out.shape = (t, n, hidden_size * ndir)
    last_h.shape = (num_layers * ndir, n, hidden_size)
    last_c.shape = (num_layers * ndir, n, hidden_size)
    return out, last_h, last_c


class StaticRNN:
    """Fixed-length RNN builder (reference layers/control_flow.py
    StaticRNN / operators/recurrent_op.cc).

    The user's step body is captured into a sub-block once; on exit it is
    UNROLLED: copied T times into the parent block with per-step variable
    renaming — step inputs become time slices, memories thread from step
    to step, step outputs stack back along time. Every unrolled op is an
    ordinary pure op, so the program still whole-compiles (XLA dedups the
    repeated computation structure).

    Usage (reference contract)::

        rnn = StaticRNN()
        with rnn.step():
            w = rnn.step_input(x_tbd)          # x [T, B, D] -> w [B, D]
            prev = rnn.memory(shape=[-1, H], batch_ref=w)
            h = layers.fc([w, prev], size=H)
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        out = rnn()                             # [T, B, H]
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._step_inputs = []   # (sub_var, source_var)
        self._mems = []          # (sub_var, init_var); _next set later
        self._mem_next = {}      # sub_var.name -> sub-block var
        self._step_outputs = []  # sub-block vars
        self._seq_len = None
        self._sub = None
        self._result = None

    def step(self):
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            main = self.helper.main_program
            self._parent_block = main.current_block()
            self._sub = main._create_block()
            try:
                yield
            finally:
                main._rollback()
                self._unroll()

        return _ctx()

    def _require_step(self):
        if self._sub is None:
            raise RuntimeError("call inside `with rnn.step():`")

    def step_input(self, x):
        self._require_step()
        if self._seq_len is None:
            self._seq_len = int(x.shape[0])
        elif int(x.shape[0]) != self._seq_len:
            raise ValueError("step inputs disagree on seq_len")
        v = self._sub.create_var(
            name=self.helper.unique_var_name("step_in"),
            shape=tuple(x.shape[1:]), dtype=x.dtype)
        self._step_inputs.append((v, x))
        return v

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1,
               value=None, dtype="float32"):
        """Reference signature (control_flow.py StaticRNN.memory):
        ``init_value`` is the canonical kwarg; ``value`` kept as an
        alias. The batch-dim indices are accepted for compatibility
        (batch_ref's dim 0 is used as the batch here)."""
        self._require_step()
        if value is not None:
            init_value = value
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init or (shape, batch_ref)")
            from .tensor import fill_constant

            dims = [int(batch_ref.shape[0])] + [int(s) for s in shape
                                                if int(s) != -1]
            # init belongs to the parent block, before the unroll
            cur = self.helper.main_program.current_block()
            self.helper.main_program._current_block_idx = \
                self._parent_block.idx
            try:
                init = fill_constant(shape=dims, dtype=dtype,
                                     value=init_value)
            finally:
                self.helper.main_program._current_block_idx = cur.idx
        v = self._sub.create_var(
            name=self.helper.unique_var_name("mem"),
            shape=tuple(init.shape), dtype=init.dtype)
        self._mems.append((v, init))
        return v

    def update_memory(self, mem, new_val):
        self._require_step()
        self._mem_next[mem.name] = new_val

    def step_output(self, o):
        self._require_step()
        self._step_outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _unroll(self):
        if self._seq_len is None:
            raise ValueError("StaticRNN needs at least one step_input")
        parent = self._parent_block
        state = {}  # sub mem name -> parent var name (current value)
        for mem, init in self._mems:
            state[mem.name] = init.name
        per_step_outs = {o.name: [] for o in self._step_outputs}

        for t in range(self._seq_len):
            mapping = dict(state)
            for v, src in self._step_inputs:
                mapping[v.name] = self._slice_t(parent, src, t).name
            for op in self._sub.ops:
                new_ins = {
                    slot: [mapping.get(n, n) for n in names]
                    for slot, names in op.inputs.items()}
                new_outs = {}
                for slot, names in op.outputs.items():
                    outs = []
                    for n in names:
                        sv = self._sub.vars.get(n)
                        nn = "%s@t%d" % (n, t)
                        if sv is not None and nn not in parent.vars:
                            parent.create_var(name=nn, shape=sv.shape,
                                              dtype=sv.dtype)
                        mapping[n] = nn
                        outs.append(nn)
                    new_outs[slot] = outs
                parent.append_op(op.type, inputs=new_ins, outputs=new_outs,
                                 attrs=dict(op.attrs), infer_shape=False)
            for mem, _init in self._mems:
                nxt = self._mem_next.get(mem.name)
                if nxt is not None:
                    state[mem.name] = mapping[nxt.name]
            for o in self._step_outputs:
                per_step_outs[o.name].append(parent.vars[mapping[o.name]])

        results = []
        cur = self.helper.main_program._current_block_idx
        self.helper.main_program._current_block_idx = parent.idx
        try:
            from .nn import stack

            for o in self._step_outputs:
                results.append(stack(per_step_outs[o.name], axis=0))
        finally:
            self.helper.main_program._current_block_idx = cur
        self._result = results

    def _slice_t(self, parent, src, t):
        from .nn import slice as nn_slice

        cur = self.helper.main_program._current_block_idx
        self.helper.main_program._current_block_idx = parent.idx
        try:
            s = nn_slice(src, axes=[0], starts=[t], ends=[t + 1])
            from .nn import squeeze

            return squeeze(s, axes=[0])
        finally:
            self.helper.main_program._current_block_idx = cur

    def __call__(self):
        if self._result is None:
            raise RuntimeError("StaticRNN not built — use `with rnn.step()`")
        return self._result[0] if len(self._result) == 1 else self._result


class RNNCell:
    """Base cell (reference layers/rnn.py RNNCell): call(inputs, states)
    -> (outputs, new_states)."""

    def call(self, inputs, states):
        raise NotImplementedError

    def __call__(self, inputs, states):
        return self.call(inputs, states)

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0):
        from .tensor import fill_constant

        b = int(batch_ref.shape[0])
        shapes = shape if isinstance(shape, (list, tuple)) and shape and \
            isinstance(shape[0], (list, tuple)) else [shape]
        outs = [fill_constant([b] + [int(s) for s in sh], dtype,
                              init_value) for sh in shapes]
        return outs if len(outs) > 1 else outs[0]


class LSTMCell(RNNCell):
    """(reference layers/rnn.py LSTMCell): one LSTM step built from fc +
    the lstm_unit op; state = [hidden, cell]. Parameters are NAMED once
    per cell instance so every time step of an unroll shares the same
    recurrent weights (LayerHelper reuses parameters by name)."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 name="LSTMCell"):
        from .. import framework
        from ..param_attr import ParamAttr

        self.hidden_size = hidden_size
        base = framework.unique_name.generate(name)
        self._param_attr = param_attr if param_attr is not None else             ParamAttr(name=base + "_w")
        self._bias_attr = bias_attr if bias_attr is not None else             ParamAttr(name=base + "_b")

    def call(self, inputs, states):
        from .extras import lstm_unit

        h_prev, c_prev = states
        h, c = lstm_unit(inputs, h_prev, c_prev,
                         param_attr=self._param_attr,
                         bias_attr=self._bias_attr)
        return h, [h, c]

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]


class GRUCell(RNNCell):
    """(reference layers/rnn.py GRUCell): fc projection + gru_unit op;
    state = hidden. The projection and recurrent weights get DISTINCT
    per-instance names (shared across steps, never across the two ops —
    they have different shapes)."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 name="GRUCell"):
        from .. import framework
        from ..param_attr import ParamAttr

        self.hidden_size = hidden_size
        base = framework.unique_name.generate(name)
        # a user-supplied NAMED param_attr cannot serve both ops (their
        # shapes differ); derive distinct names from it
        user_name = getattr(param_attr, "name", None) if param_attr else             None
        prefix = user_name or base
        self._proj_attr = ParamAttr(name=prefix + "_proj_w")
        self._rec_attr = ParamAttr(name=prefix + "_rec_w")
        self._bias_attr = bias_attr if bias_attr is not None else             ParamAttr(name=prefix + "_b")

    def call(self, inputs, states):
        from .extras import gru_unit
        from .nn import fc

        h_prev = states[0] if isinstance(states, (list, tuple)) else states
        x = fc(inputs, size=3 * self.hidden_size,
               param_attr=self._proj_attr, bias_attr=False)
        h, _, _ = gru_unit(x, h_prev, 3 * self.hidden_size,
                           param_attr=self._rec_attr,
                           bias_attr=self._bias_attr)
        return h, [h]

    @property
    def state_shape(self):
        return [[self.hidden_size]]


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Run `cell` over the time axis of dense inputs (reference
    layers/rnn.py rnn): unrolled via StaticRNN-style slicing, so the
    whole program still compiles. Returns (outputs, final_states)."""
    from .nn import slice as nn_slice
    from .nn import squeeze, stack
    from .tensor import cast, fill_constant

    time_axis = 0 if time_major else 1
    batch_axis = 1 if time_major else 0
    T = int(inputs.shape[time_axis])
    B = int(inputs.shape[batch_axis])
    states = initial_states
    if states is None:
        shapes = cell.state_shape
        states = [fill_constant([B] + [int(d) for d in sh], "float32",
                                0.0) for sh in shapes]
    if not isinstance(states, (list, tuple)):
        states = [states]
    states = list(states)
    len_mask = None
    if sequence_length is not None:
        # [T, B] step-validity mask; padded steps carry the old state
        from .sequence_lod import sequence_mask

        m = sequence_mask(sequence_length, maxlen=T)  # [B, T]
        len_mask = cast(m, inputs.dtype)
    outs = []
    steps = range(T - 1, -1, -1) if is_reverse else range(T)
    for i in steps:
        x_t = squeeze(nn_slice(inputs, axes=[time_axis], starts=[i],
                               ends=[i + 1]), axes=[time_axis])
        o, new_states = cell.call(x_t, list(states))
        if len_mask is not None:
            from .nn import elementwise_add, elementwise_mul
            from .ops import scale as _scale_op

            m_t = nn_slice(len_mask, axes=[1], starts=[i], ends=[i + 1])
            inv_m = _scale_op(m_t, scale=-1.0, bias=1.0)
            new_states = [
                elementwise_add(elementwise_mul(n, m_t),
                                elementwise_mul(s, inv_m))
                for n, s in zip(new_states, states)]
            o = elementwise_mul(o, m_t)
        states = new_states
        outs.append(o)
    if is_reverse:
        outs = outs[::-1]
    outputs = stack(outs, axis=time_axis)
    return outputs, states


__all__ += ["RNNCell", "LSTMCell", "GRUCell", "rnn"]


# ---------------------------------------------------------------------------
# Decoder family (reference layers/rnn.py:560 Decoder, :604 BeamSearchDecoder,
# :1051 dynamic_decode)
# ---------------------------------------------------------------------------


class Decoder:
    """Abstract step-decoder contract (reference layers/rnn.py Decoder):
    ``initialize`` -> (initial_inputs, initial_states, initial_finished);
    ``step`` -> (outputs, next_states, next_inputs, finished);
    ``finalize`` -> (final_outputs, final_states)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError


class BeamSearchDecoder(Decoder):
    """Beam-search decoding over a wrapped cell (reference
    layers/rnn.py:604). TPU-native layout: everything is DENSE
    [batch, beam] / [batch*beam, ...] with static shapes — no LoD — so the
    unrolled decode compiles to one XLA program; the backtrace is the
    gather_tree op, exactly as the reference's finalize (:1030).

    States and inputs handed to ``cell.call`` are shaped
    [batch*beam, ...]; use ``tile_beam_merge_with_batch`` for any extra
    tensor the cell closes over (e.g. attention memory)."""

    OutputWrapper = _namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = _namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        # per-decode constants hoisted out of the unrolled step loop
        # (built once in initialize; the reference caches the same mask
        # as self.noend_mask_tensor)
        self._consts = None

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] -> [batch*beam, ...] with each row repeated
        beam_size times (reference :680)."""
        from .nn import expand, reshape, unsqueeze

        x = unsqueeze(x, [1])
        times = [1] * len(x.shape)
        times[1] = beam_size
        x = expand(x, times)
        shp = [int(s) for s in x.shape]
        lead = -1 if shp[0] < 0 else shp[0] * shp[1]
        return reshape(x, [lead] + shp[2:])

    def _merge(self, x):
        from .nn import reshape

        shp = [int(s) for s in x.shape]
        return reshape(x, [shp[0] * shp[1]] + shp[2:])

    def initialize(self, initial_cell_states):
        """Start tokens everywhere; beam 0 carries log-prob 0, the rest
        -inf so step 1 expands only beam 0 (reference :824 kInf init)."""
        import numpy as np

        from .tensor import assign, fill_constant

        states = initial_cell_states
        if not isinstance(states, (list, tuple)):
            states = [states]
        B = int(states[0].shape[0])
        if B < 0:
            raise ValueError(
                "BeamSearchDecoder needs a static batch size; declare the "
                "initial state with fluid.data(..., shape=[batch, ...]) "
                "instead of a -1 batch dim (static shapes are what let "
                "the decode compile to one XLA program)")
        K = self.beam_size
        cell_states = [self.tile_beam_merge_with_batch(s, K)
                       for s in states]
        init_lp = assign(np.array(
            [[0.0] + [-1e9] * (K - 1)] * B, dtype="float32"))
        finished = fill_constant([B, K], "bool", False)
        lengths = fill_constant([B, K], "int64", 0)
        start = fill_constant([B, K], "int64", self.start_token)
        self._consts = None  # rebuilt lazily on the first step (needs V)
        init_inputs = start
        if self.embedding_fn is not None:
            # [B, K, E] -> [B*K, E]: the wrapped cell always sees the
            # beam dim merged into batch (reference _merge_batch_beams)
            init_inputs = self._merge(self.embedding_fn(start))
        return init_inputs, self.StateWrapper(
            cell_states, init_lp, finished, lengths), finished

    def _step_consts(self, B, K, V):
        """Build the step-invariant constant tensors ONCE per decode —
        the unrolled loop would otherwise re-materialize a [V] literal
        and ~8 fill_constants every step (the reference caches the same
        thing as self.noend_mask_tensor)."""
        if self._consts is not None:
            return self._consts
        import numpy as np

        from .nn import expand, reshape
        from .tensor import assign, cast, fill_constant, range as t_range

        noend = np.full((V,), -1e9, dtype="float32")
        noend[self.end_token] = 0.0
        self._consts = {
            "noend_bkv": expand(reshape(assign(noend), [1, 1, V]),
                                [B, K, 1]),
            "vconst": fill_constant([B, K], "int64", V),
            "kconst": fill_constant([B, K], "int64", K),
            "endconst": fill_constant([B, K], "int64", self.end_token),
            "one_i": fill_constant([B, K], "int64", 1),
            "neg_one_i": fill_constant([B, K], "int64", -1),
            "offs": expand(reshape(cast(t_range(0, B, 1, "int32"),
                                        "int64"), [B, 1]), [1, K]),
            "eps": fill_constant([1], "float32", 1e-20),
            "one_f": fill_constant([1], "float32", 1.0),
            "neg_one_f": fill_constant([1], "float32", -1.0),
        }
        return self._consts

    def _beam_search_step(self, logits, beam_state):
        """One topk-over-(beam x vocab) selection (reference :862)."""
        from .nn import (elementwise_add, elementwise_floordiv,
                         elementwise_mod, elementwise_mul, expand, gather,
                         reshape, softmax, topk, unsqueeze)
        from .ops import log
        from .tensor import cast
        from .control_flow import equal, logical_or

        K, B = self.beam_size, int(beam_state.log_probs.shape[0])
        V = int(logits.shape[-1])
        c = self._step_consts(B, K, V)
        probs = softmax(reshape(logits, [B, K, V]))
        step_lp = log(elementwise_add(probs, c["eps"]))
        # finished beams may only extend with end_token at no cost
        fin_f = expand(unsqueeze(cast(beam_state.finished, "float32"), [2]),
                       [1, 1, V])
        keep_f = elementwise_add(
            elementwise_mul(fin_f, c["noend_bkv"]),
            elementwise_mul(
                step_lp,
                elementwise_add(elementwise_mul(fin_f, c["neg_one_f"]),
                                c["one_f"])))
        total = elementwise_add(
            keep_f, expand(unsqueeze(beam_state.log_probs, [2]), [1, 1, V]))
        scores, idx = topk(reshape(total, [B, K * V]), K)  # [B, K]
        beam_idx = elementwise_floordiv(idx, c["vconst"])
        token_idx = elementwise_mod(idx, c["vconst"])
        # flat parent rows into [B*K, ...] cell states
        flat_parent = reshape(
            elementwise_add(elementwise_mul(c["offs"], c["kconst"]),
                            beam_idx),
            [B * K])
        next_cell = [gather(s, flat_parent) for s in beam_state.cell_states]
        parent_finished = reshape(
            gather(reshape(beam_state.finished, [B * K]), flat_parent),
            [B, K])
        parent_lengths = reshape(
            gather(reshape(beam_state.lengths, [B * K]), flat_parent),
            [B, K])
        next_finished = logical_or(parent_finished,
                                   equal(token_idx, c["endconst"]))
        grow = elementwise_add(
            elementwise_mul(cast(parent_finished, "int64"),
                            c["neg_one_i"]),
            c["one_i"])  # 1 - finished
        next_lengths = elementwise_add(parent_lengths, grow)
        next_state = self.StateWrapper(next_cell, scores, next_finished,
                                       next_lengths)
        output = self.OutputWrapper(scores, token_idx, beam_idx)
        return output, next_state

    def step(self, time, inputs, states, **kwargs):
        cell_out, next_cell = self.cell.call(inputs, states.cell_states)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        probing = self.StateWrapper(next_cell, states.log_probs,
                                    states.finished, states.lengths)
        output, next_state = self._beam_search_step(cell_out, probing)
        next_inputs = output.predicted_ids
        if self.embedding_fn is not None:
            next_inputs = self._merge(self.embedding_fn(next_inputs))
        return output, next_state, next_inputs, next_state.finished

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrace beams through parent pointers (reference :1030 uses
        the same gather_tree op)."""
        from .extras import gather_tree

        predicted = gather_tree(outputs.predicted_ids, outputs.parent_ids)
        return predicted, final_states


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, **kwargs):
    """Drive ``decoder`` until max_step_num (reference layers/rnn.py:1051).

    TPU-native: the loop is unrolled at program-build time with dense
    static shapes (the reference grows LoD arrays inside a While op —
    a dynamic shape per step that XLA cannot tile); finished beams keep
    emitting end tokens, so the fixed trip count changes results only in
    costing compute after convergence, never correctness."""
    from .nn import stack, transpose

    if max_step_num is None:
        raise ValueError("dynamic_decode requires max_step_num (the "
                         "unrolled trip count)")
    inputs, states, finished = decoder.initialize(inits)
    step_outputs = []
    for t in range(int(max_step_num)):
        output, states, inputs, finished = decoder.step(t, inputs, states,
                                                        **kwargs)
        step_outputs.append(output)
    stacked = type(step_outputs[0])(*[
        stack([getattr(o, f) for o in step_outputs], axis=0)
        for f in step_outputs[0]._fields])
    final_outputs, final_states = decoder.finalize(
        stacked, states, getattr(states, "lengths", None))
    if not output_time_major:
        import paddle_tpu.framework as _fw

        def _batch_major(x):
            if isinstance(x, _fw.Variable):
                return transpose(x, [1, 0] + list(range(2, len(x.shape))))
            return x

        if isinstance(final_outputs, tuple) and hasattr(final_outputs,
                                                        "_fields"):
            final_outputs = type(final_outputs)(
                *[_batch_major(f) for f in final_outputs])
        else:
            final_outputs = _batch_major(final_outputs)
    return final_outputs, final_states


__all__ += ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """One LoD beam-search step (reference layers/rnn.py:2698 over
    beam_search_op.cc). Selects the top ``beam_size`` candidates per
    source sentence from per-prefix topk candidates; see
    ops/beam_search_ops.py for the host-side kernel and the TPU-native
    alternative (BeamSearchDecoder)."""
    from ..layer_helper import LayerHelper

    helper = LayerHelper("beam_search", input=pre_ids, name=name)
    selected_ids = helper.create_variable_for_type_inference("int64")
    selected_scores = helper.create_variable_for_type_inference("float32")
    inputs = {"pre_ids": [pre_ids], "pre_scores": [pre_scores],
              "scores": [scores]}
    if ids is not None:
        inputs["ids"] = [ids]
    outputs = {"selected_ids": [selected_ids],
               "selected_scores": [selected_scores]}
    if return_parent_idx:
        parent_idx = helper.create_variable_for_type_inference("int32")
        outputs["parent_idx"] = [parent_idx]
    helper.append_op("beam_search", inputs=inputs, outputs=outputs,
                     attrs={"level": level, "beam_size": beam_size,
                            "end_id": end_id,
                            "is_accumulated": is_accumulated},
                     infer_shape=False)
    if return_parent_idx:
        return selected_ids, selected_scores, parent_idx
    return selected_ids, selected_scores


def beam_search_decode(ids, scores, beam_size, end_id, name=None):
    """Backtrace full hypotheses from per-step beam_search outputs stored
    in LoDTensorArrays (reference layers/rnn.py:2848 over
    beam_search_decode_op.h)."""
    from ..layer_helper import LayerHelper

    helper = LayerHelper("beam_search_decode", input=ids, name=name)
    sentence_ids = helper.create_variable_for_type_inference("int64")
    sentence_scores = helper.create_variable_for_type_inference("float32")
    helper.append_op("beam_search_decode",
                     inputs={"Ids": [ids], "Scores": [scores]},
                     outputs={"SentenceIds": [sentence_ids],
                              "SentenceScores": [sentence_scores]},
                     attrs={"beam_size": beam_size, "end_id": end_id},
                     infer_shape=False)
    return sentence_ids, sentence_scores


__all__ += ["beam_search", "beam_search_decode"]
