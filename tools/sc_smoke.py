#!/usr/bin/env python
"""Single-chip fusion smoke (CI gate, ~30s): the ISSUE-14 acceptance
drill for the fused-optimizer / fused-epilogue / async-feed fast path.

For an MLP and a small conv model, runs the SAME seeded training twice
— baseline (knobs off) and fused (PADDLE_TPU_FUSED_OPTIMIZER +
PADDLE_TPU_FUSED_EPILOGUE) — and gates on:

- the fused program STRICTLY cuts per-step op count, with a
  ``fused_optimizer`` op present (and epilogue ops where the model has
  the chains);
- params after ONE update identical to the per-param baseline —
  bitwise where XLA compiles both programs with the same FMA
  contraction (the mlp/adam config pins that), and within 4 float32
  ULP otherwise: the fused op evaluates the IDENTICAL expression
  sequence, but XLA is free to contract ``a*b+c`` into an fma
  differently in two different programs (measured: the per-param
  momentum/conv baseline itself differs from exact numpy float32 by
  ~2 ULP for the same reason). After N further steps the loss
  trajectories must agree to 1e-3 relative — an iterated nonlinear
  system amplifies a 1-ULP seed, so bitwise-after-N is only required
  where step 1 was bitwise;
- both runs stay on the whole-compile path (zero compile fallbacks);
- the async feeder's steady-state critical-path feed cost does not
  exceed the sync H2D cost it replaces (double-buffering can only
  help).

``--out FILE`` writes a bench_diff-compatible artifact: per-config
step_ms / optimizer_ms / feed_ms (measured by the step profiler) plus
``counters_total["sc.program_ops"]`` — the fused op count, which is
DETERMINISTIC, so ci/check.sh gate 7c diffs it run-over-run at 1%
(growth = the fusion pass silently regressed) while timings gate
loose.

Usage:  python tools/sc_smoke.py [--out FILE] [--steps N]
"""
from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

STEPS = 4
SEED = 1234

KNOBS = ("PADDLE_TPU_FUSED_OPTIMIZER", "PADDLE_TPU_FUSED_EPILOGUE",
         "PADDLE_TPU_ASYNC_FEED")


def _build_mlp():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = SEED
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[32, 64], dtype="float32")
        lbl = fluid.data(name="lbl", shape=[32, 1], dtype="int64")
        h = fluid.layers.fc(x, size=128, act="gelu")
        h2 = fluid.layers.fc(h, size=128)
        h = fluid.layers.elementwise_add(h2, h)
        h = fluid.layers.layer_norm(h)
        pred = fluid.layers.fc(h, size=10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, lbl))
        fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(32, 64).astype("float32"),
            "lbl": rng.randint(0, 10, (32, 1)).astype("int64")}
    return main, startup, loss, feed


def _build_conv():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = SEED
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        img = fluid.data(name="img", shape=[8, 3, 16, 16],
                         dtype="float32")
        lbl = fluid.data(name="lbl", shape=[8, 1], dtype="int64")
        c = fluid.layers.conv2d(img, num_filters=8, filter_size=3,
                                padding=1, act="relu")
        c = fluid.layers.conv2d(c, num_filters=8, filter_size=3,
                                padding=1, act="relu")
        p = fluid.layers.pool2d(c, pool_size=4, pool_type="avg")
        pred = fluid.layers.fc(p, size=10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, lbl))
        fluid.optimizer.MomentumOptimizer(0.1, 0.9).minimize(loss)
    rng = np.random.RandomState(1)
    feed = {"img": rng.rand(8, 3, 16, 16).astype("float32"),
            "lbl": rng.randint(0, 10, (8, 1)).astype("int64")}
    return main, startup, loss, feed


def _set_knobs(on):
    for k in KNOBS:
        os.environ.pop(k, None)
    if on:
        os.environ["PADDLE_TPU_FUSED_OPTIMIZER"] = "1"
        os.environ["PADDLE_TPU_FUSED_EPILOGUE"] = "1"


def _train(build, steps):
    import paddle_tpu as fluid
    from paddle_tpu import observability as obs

    obs.enable()
    fb0 = obs.counter_value("executor.compile_fallbacks") or 0
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        main, startup, loss, feed = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)

        def _snap():
            got = {}
            for v in main.global_block().vars.values():
                if not v.persistable:
                    continue
                var = scope.find_var(v.name)
                if var is not None and var.is_initialized():
                    got[v.name] = np.asarray(var.raw().array)
            return got

        t0 = None
        losses = []
        params1 = None
        for i in range(steps):
            if i == 1:
                params1 = _snap()   # after exactly one update
                t0 = time.perf_counter()
            out = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(out[0]))
        dt = (time.perf_counter() - t0) / max(1, steps - 1)
        params = _snap()
        prof = None
        try:
            from paddle_tpu.observability import profiler as _prof

            prof = _prof.profile_step(main, scope, feed)
        except Exception as e:
            print("profile_step failed (non-fatal): %r" % e)
    fb = (obs.counter_value("executor.compile_fallbacks") or 0) - fb0
    ops = [op.type for op in main.global_block().ops]
    return {"loss": float(out[0]), "losses": losses,
            "step_ms": dt * 1e3, "ops": ops, "params": params,
            "params_step1": params1 or params, "fallbacks": fb,
            "profile": prof}


def _within_ulp(a, b, ulp=4):
    """True when every element of b is within ``ulp`` float32 ULP of
    a — the bound for cross-program FMA-contraction differences."""
    if a.shape != b.shape or a.dtype != b.dtype:
        return False
    fi = np.finfo(np.float32)
    tol = ulp * (fi.eps * np.maximum(np.abs(a), np.abs(b)) + fi.tiny)
    return bool(np.all(np.abs(a.astype("f8") - b.astype("f8")) <= tol))


def run_config(name, build, steps, exact):
    _set_knobs(False)
    base = _train(build, steps)
    _set_knobs(True)
    fused = _train(build, steps)
    _set_knobs(False)

    n_base, n_fused = len(base["ops"]), len(fused["ops"])
    assert n_fused < n_base, (
        "%s: fused program must STRICTLY cut op count (%d -> %d)"
        % (name, n_base, n_fused))
    assert "fused_optimizer" in fused["ops"], (
        "%s: no fused_optimizer op in the rewritten program" % name)
    assert base["fallbacks"] == 0 and fused["fallbacks"] == 0, (
        "%s: compile fallback during the smoke" % name)
    b1, f1 = base["params_step1"], fused["params_step1"]
    common = [k for k in b1 if k in f1]
    assert common, "%s: no comparable params" % name
    exact_ok = all(np.array_equal(b1[k], f1[k]) for k in common)
    if exact:
        assert exact_ok, (
            "%s: step-1 params diverged bitwise: %s"
            % (name, [k for k in common
                      if not np.array_equal(b1[k], f1[k])][:5]))
        assert all(np.array_equal(base["params"][k], fused["params"][k])
                   for k in base["params"] if k in fused["params"]), (
            "%s: params diverged after %d steps despite bitwise step 1"
            % (name, steps))
    else:
        bad = [k for k in common if not _within_ulp(b1[k], f1[k])]
        assert not bad, (
            "%s: step-1 params diverged past the 4-ULP FMA bound: %s"
            % (name, bad[:5]))
    # trajectory agreement over the full run (a 1-ULP seed grows
    # through an iterated nonlinear system — gate on training
    # equivalence, not bitwise, beyond step 1)
    for lb, lf in zip(base["losses"], fused["losses"]):
        assert abs(lb - lf) <= 1e-3 * max(abs(lb), 1e-6), (
            "%s: loss trajectories diverged: %r vs %r"
            % (name, base["losses"], fused["losses"]))
    fused_ops = [t for t in fused["ops"] if t.startswith("fused")]
    print("%-8s ops %d -> %d (fused ops: %s), step-1 %s, %d-step "
          "trajectory ok, step %.1f -> %.1fms"
          % (name, n_base, n_fused, ",".join(sorted(set(fused_ops))),
             "bit-identical" if exact_ok else "within 4 ULP", steps,
             base["step_ms"], fused["step_ms"]))

    rec = {"step_ms": fused["step_ms"],
           "step_ms_baseline": base["step_ms"],
           "ops_baseline": n_base, "ops_fused": n_fused,
           "diag": {"collective_bytes": 0}}
    prof = fused.get("profile")
    if prof:
        rec["profile"] = {
            "feed_ms": prof.get("feed_ms"),
            "optimizer_ms": prof.get("optimizer_ms"),
            "phase_ms": prof.get("phase_ms"),
        }
        bprof = base.get("profile") or {}
        if bprof.get("optimizer_ms") is not None:
            rec["optimizer_ms_baseline"] = bprof["optimizer_ms"]
    return rec, n_fused


def check_async_feed():
    """Steady-state critical-path feed cost with the double buffer must
    not exceed the sync H2D it replaces (plus scheduler noise)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench as _bench

    rng = np.random.RandomState(2)
    feed = {"x": rng.rand(256, 1024).astype("float32")}
    feed_async, feed_sync = _bench._measure_feed(feed, reps=6)
    print("async feed: critical-path %.3fms vs sync H2D %.3fms"
          % (feed_async, feed_sync))
    assert feed_async <= feed_sync + 2.0, (
        "async feeder costs MORE than sync staging (%.3f vs %.3f ms)"
        % (feed_async, feed_sync))
    return feed_async, feed_sync


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    out_path = None
    steps = STEPS
    for a in argv:
        if a.startswith("--out"):
            out_path = a.split("=", 1)[1] if "=" in a else None
        elif a.startswith("--steps="):
            steps = int(a.split("=", 1)[1])
    if out_path is None and "--out" in argv:
        out_path = argv[argv.index("--out") + 1]

    configs = {}
    total_ops = 0
    for name, build, exact in (("sc_mlp", _build_mlp, True),
                               ("sc_conv", _build_conv, False)):
        rec, n_fused = run_config(name, build, steps, exact)
        configs[name] = rec
        total_ops += n_fused
    feed_async, feed_sync = check_async_feed()

    doc = {
        "schema": "sc_smoke.v1",
        "configs": configs,
        "feed_ms": feed_async,
        "feed_ms_sync": feed_sync,
        # deterministic: total op count of the FUSED programs — growth
        # run-over-run means the fusion passes silently regressed
        # (bench_diff watches sc.program_ops as a grows-bad counter)
        "counters_total": {"sc.program_ops": total_ops,
                           "executor.compile_fallbacks": 0},
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print("artifact -> %s" % out_path)
    print("sc_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
