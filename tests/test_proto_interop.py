"""Reference model-format interop (VERDICT r2 #7).

Validates the hand-written proto2 codec three ways: hand-computed wire
bytes, cross-validation against the official ``protoc`` using a schema
generated FROM OUR FIELD TABLES (proving wire-format agreement without
depending on the reference tree), and a full save→load→predict round
trip through the binary ``__model__`` + tensor-stream params path.
"""
import os
import shutil
import struct
import subprocess
import tempfile

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core import proto_format as pf


def test_wire_primitives_hand_computed():
    # Version{version=1} -> field 1 varint: key 0x08, value 0x01
    assert pf.encode_message({"version": 1}, pf.VERSION) == b"\x08\x01"
    assert pf.decode_message(b"\x08\x01", pf.VERSION) == {"version": 1}
    # TensorDesc{data_type=5, dims=[2,-1]} — negative int64 is a
    # 10-byte varint in proto2
    enc = pf.encode_message({"data_type": 5, "dims": [2, -1]},
                            pf.TENSOR_DESC)
    assert enc.startswith(b"\x08\x05\x10\x02\x10")
    dec = pf.decode_message(enc, pf.TENSOR_DESC)
    assert dec == {"data_type": 5, "dims": [2, -1]}
    # packed repeated ints (proto3-style writers) also decode
    packed = b"\x08\x05\x12\x02\x02\x03"  # dims as packed [2,3]
    assert pf.decode_message(packed, pf.TENSOR_DESC)["dims"] == [2, 3]


def _table_to_proto_src():
    """Emit a .proto source from our field tables (schema generated from
    code, for protoc cross-validation only)."""
    lines = ['syntax = "proto2";', "package pt_check;"]

    def msg(name, table, done=set()):
        if name in done:
            return
        done.add(name)
        body = []
        for fno, spec in sorted(table.items()):
            fname, kind = spec[0], spec[1]
            rep = "repeated" if kind.endswith("*") else "optional"
            base = kind.rstrip("*")
            if base == "msg":
                sub = "M%d_%s" % (id(spec[2]) % 997, fname)
                msg(sub, spec[2], done)
                typ = sub
            else:
                typ = {"int": "int64", "enum": "int32", "bool": "bool",
                       "float": "float", "str": "string"}[base]
            body.append("  %s %s %s = %d;" % (rep, typ, fname, fno))
        lines.append("message %s {\n%s\n}" % (name, "\n".join(body)))

    msg("OpDesc", pf.OP_DESC)
    return "\n".join(lines)


@pytest.mark.skipif(shutil.which("protoc") is None,
                    reason="protoc not available")
def test_codec_matches_protoc():
    src = _table_to_proto_src()
    with tempfile.TemporaryDirectory() as d:
        proto_path = os.path.join(d, "check.proto")
        with open(proto_path, "w") as f:
            f.write(src)
        textpb = (
            'type: "mul"\n'
            'inputs { parameter: "X" arguments: "a" arguments: "b" }\n'
            'outputs { parameter: "Out" arguments: "o" }\n'
            'attrs { name: "x_num_col_dims" type: 0 i: 1 }\n'
            'attrs { name: "alpha" type: 1 f: 1.5 }\n'
        )
        official = subprocess.run(
            ["protoc", "--proto_path", d, "--encode=pt_check.OpDesc",
             proto_path],
            input=textpb.encode(), capture_output=True, check=True).stdout
        ours = pf.encode_message(
            {"type": "mul",
             "inputs": [{"parameter": "X", "arguments": ["a", "b"]}],
             "outputs": [{"parameter": "Out", "arguments": ["o"]}],
             "attrs": [
                 {"name": "x_num_col_dims", "type": 0, "i": 1},
                 {"name": "alpha", "type": 1, "f": 1.5},
             ]}, pf.OP_DESC)
        # decode both ways: our decoder reads protoc's bytes and
        # vice versa (byte equality can differ by field order, so
        # compare the decoded structures)
        assert pf.decode_message(official, pf.OP_DESC) == \
            pf.decode_message(ours, pf.OP_DESC)
        back = subprocess.run(
            ["protoc", "--proto_path", d, "--decode=pt_check.OpDesc",
             proto_path],
            input=ours, capture_output=True, check=True).stdout
        assert b'type: "mul"' in back and b"alpha" in back


def test_lod_tensor_stream_roundtrip():
    arr = np.arange(12, dtype="float32").reshape(3, 4)
    data = pf.serialize_lod_tensor(arr, lod=[[0, 2, 3]])
    # framing: u32 version 0, u64 lod_level 1
    assert struct.unpack_from("<I", data, 0)[0] == 0
    assert struct.unpack_from("<Q", data, 4)[0] == 1
    out, lod, pos = pf.parse_lod_tensor(data)
    assert pos == len(data)
    np.testing.assert_array_equal(out, arr)
    assert lod == [[0, 2, 3]]

    combined_path = tempfile.mktemp()
    try:
        b = np.arange(6, dtype="int64").reshape(2, 3)
        pf.save_combine([("a", arr), ("b", b)], combined_path)
        loaded = pf.load_combine(combined_path, ["a", "b"])
        np.testing.assert_array_equal(loaded["a"], arr)
        np.testing.assert_array_equal(loaded["b"], b)
    finally:
        os.unlink(combined_path)


def test_packed_floats_and_bools_decode():
    # proto3-style packed floats: field 7 (floats), wire type LEN
    payload = struct.pack("<2f", 1.5, -2.0)
    data = bytes([7 << 3 | 2, len(payload)]) + payload
    assert pf.decode_message(data, pf.OP_DESC_ATTR)["floats"] == [1.5, -2.0]
    # packed bools: field 11, two varints
    data = bytes([11 << 3 | 2, 2, 1, 0])
    assert pf.decode_message(data, pf.OP_DESC_ATTR)["bools"] == [True, False]


def test_multi_block_program_roundtrip():
    """Sub-block programs (cond/while) must survive the proto round
    trip with parent links and block-attr references intact."""
    desc = {
        "blocks": [
            {"idx": 0, "parent_idx": -1,
             "vars": [{"name": "x",
                       "type": {"type": 7,
                                "lod_tensor": {"tensor": {
                                    "data_type": 5, "dims": [2]}}},
                       "persistable": False}],
             "ops": [{"type": "conditional_block",
                      "inputs": [{"parameter": "Cond",
                                  "arguments": ["x"]}],
                      "outputs": [],
                      "attrs": [{"name": "sub_block", "type": 8,
                                 "block_idx": 1}]}]},
            {"idx": 1, "parent_idx": 0, "vars": [], "ops": []},
        ],
        "version": {"version": 1007000},
    }
    raw = pf.encode_message(desc, pf.PROGRAM_DESC)
    prog, feeds, fetches = pf.proto_bytes_to_program(raw)
    assert len(prog.blocks) == 2
    assert prog.blocks[1].parent_block is prog.blocks[0]
    op = prog.global_block().ops[0]
    assert op.attrs["sub_block"] is prog.blocks[1]


def test_rejects_2x_format_version():
    raw = pf.encode_message(
        {"blocks": [{"idx": 0, "parent_idx": -1}],
         "version": {"version": 2000000}}, pf.PROGRAM_DESC)
    with pytest.raises(RuntimeError, match="2.x"):
        pf.proto_bytes_to_program(raw)


def _build_model():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[4, 6], dtype="float32")
        h = fluid.layers.fc(x, size=8, act="relu")
        pred = fluid.layers.fc(h, size=3, act="softmax")
    return main, startup, pred


def test_save_load_reference_format_roundtrip(tmp_path):
    main, startup, pred = _build_model()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    x = rng.rand(4, 6).astype("float32")
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (ref_out,) = exe.run(main, feed={"x": x}, fetch_list=[pred])
        # save in the reference binary format (separate param files AND
        # a combined-file variant)
        fluid.io.save_inference_model(
            str(tmp_path / "sep"), ["x"], [pred], exe,
            main_program=main, model_filename="__model__")
        fluid.io.save_inference_model(
            str(tmp_path / "comb"), ["x"], [pred], exe,
            main_program=main, model_filename="__model__",
            params_filename="__params__")

    assert (tmp_path / "sep" / "__model__").exists()
    # binary, not JSON
    head = (tmp_path / "sep" / "__model__").read_bytes()[:1]
    assert head != b"{"

    for sub, params in (("sep", None), ("comb", "__params__")):
        scope2 = fluid.Scope()
        with fluid.scope_guard(scope2):
            exe2 = fluid.Executor(fluid.CPUPlace())
            prog, feeds, fetches = fluid.io.load_inference_model(
                str(tmp_path / sub), exe2, params_filename=params)
            assert feeds == ["x"]
            (out,) = exe2.run(prog, feed={"x": x}, fetch_list=fetches)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg="proto round-trip (%s)" % sub)
