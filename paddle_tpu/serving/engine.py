"""ServingEngine: worker threads + admission control over one predictor.

The runtime half of the serving subsystem (batcher.py is the policy
half). N worker threads pull micro-batches off the shared
DynamicBatcher and push them through ONE shared ``PaddlePredictor`` —
the predictor's run lock serializes the actual device dispatch (one
accelerator, one dispatch stream), but extra workers still pay off:
while one dispatch is in flight the next batch is being
assembled/padded/unpadded on another thread.

Production behaviors the bare predictor lacks, in one place:

- **admission control** — a bounded queue; a full queue rejects at
  submit time with ``ServerOverloaded`` instead of letting latency grow
  without bound (the caller can shed load / retry elsewhere NOW);
- **deadlines** — a request that has already blown its budget is
  dropped at batch-formation time, *before* a device dispatch is wasted
  on rows nobody is waiting for;
- **warmup** — every ladder bucket is compiled at ``start()``, so the
  first real request never eats a multi-ms XLA compile;
- **graceful drain** — ``stop()`` refuses new work, finishes what's
  queued, then joins the workers;
- **lifecycle** — ``health()`` reports a machine-readable state
  (``starting | warming | serving | draining | stopped``) so a fleet
  router can stop routing at ``draining`` instead of waiting for a
  connection refusal;
- **idempotent request ids** — ``submit(request_id=...)`` joins a
  duplicate of an already-seen request to the ORIGINAL's future (a
  bounded LRU remembers recently-completed ids too), so a hedged or
  retried delivery never runs the predictor twice on this replica and
  never double-counts ``serving.requests``.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observability import distributed as _dtrace
from . import metrics as _m
from .batcher import BatchPolicy, DynamicBatcher, PendingRequest

__all__ = ["ServingConfig", "ServingEngine", "ServingError",
           "ServerOverloaded", "DeadlineExpired", "EngineStopped",
           "RequestTooLarge", "BatchExecutionError"]


class ServingError(RuntimeError):
    """Base of all typed serving failures."""


class ServerOverloaded(ServingError):
    """Admission control: the pending queue is full. Retry later or
    against another replica — queuing more here only grows latency."""


class DeadlineExpired(ServingError):
    """The request's deadline passed while it waited in the queue."""


class EngineStopped(ServingError):
    """submit() after stop() (or before start())."""


class RequestTooLarge(ServingError):
    """A single request's rows exceed max_batch_size; the batcher never
    splits a request, so it could never be scheduled."""


class BatchExecutionError(ServingError):
    """The predictor (or output unpadding) blew up inside a batch
    dispatch. Exactly the co-batched requests fail — with this typed
    error (HTTP 500) — and the engine stays healthy: worker threads
    survive, the next batch dispatches normally. The original
    exception rides along as ``__cause__``."""


class ServingConfig:
    """Engine knobs. ``ladder=None`` -> powers of two up to
    ``max_batch_size``; ``max_queue`` bounds PENDING requests (in-flight
    batches don't count); ``default_deadline_ms=None`` -> requests
    without an explicit deadline never expire."""

    def __init__(self, max_batch_size: int = 8,
                 batch_timeout_ms: float = 2.0,
                 ladder: Optional[Sequence[int]] = None,
                 max_queue: int = 64,
                 num_workers: int = 2,
                 default_deadline_ms: Optional[float] = None,
                 warmup: bool = True,
                 request_id_cache: int = 1024):
        self.policy = BatchPolicy(max_batch_size, batch_timeout_ms, ladder)
        self.max_queue = int(max_queue)
        self.num_workers = int(num_workers)
        if self.num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.default_deadline_ms = default_deadline_ms
        self.warmup = bool(warmup)
        # idempotent-resubmit window: how many request ids (pending AND
        # recently completed) the engine remembers; 0 disables dedup
        self.request_id_cache = int(request_id_cache)


class ServingEngine:
    """Dynamic-batching front of a shared predictor.

    ``predictor`` needs the PaddlePredictor surface: ``run(dict) ->
    [PaddleTensor]`` (thread-safe — inference/__init__ guards it) and
    ``get_input_names()``. ``sample_feed`` (dict name -> single-row
    array) is the warmup template; when omitted it is derived from the
    predictor program's feed-var shapes/dtypes (batch dim and unknown
    dims become 1/zeros).
    """

    _POLL_S = 0.05

    def __init__(self, predictor, config: Optional[ServingConfig] = None,
                 sample_feed: Optional[Dict[str, np.ndarray]] = None):
        self.config = config or ServingConfig()
        self._predictor = predictor
        self._input_names = list(predictor.get_input_names())
        self._batcher = DynamicBatcher(self.config.policy,
                                       self.config.max_queue)
        # per-input template (single row, model dtype): warmup tiles it,
        # and _validate checks/coerces requests against it so one
        # malformed request is rejected at submit with ITS OWN error
        # instead of poisoning every co-batched request at concatenate,
        # and off-dtype JSON payloads (int64 from integer literals)
        # cannot mint novel jit signatures past the bucket ladder
        self._spec = (
            {n: np.asarray(v) for n, v in sample_feed.items()}
            if sample_feed else self._derive_sample_feed())
        self._workers: List[threading.Thread] = []
        self._state_lock = threading.Lock()
        self._started = False
        self._warming = False
        self._stopping = False
        self._abort = False
        self._stopped = False
        self.warmed_buckets: tuple = ()
        # request-id -> Future, insertion-ordered LRU; entries stay
        # after completion (bounded by request_id_cache) so a late
        # duplicate delivery of a FINISHED request still joins its
        # original result instead of re-running the predictor
        self._ids: "OrderedDict[str, Future]" = OrderedDict()
        self._ids_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ServingEngine":
        with self._state_lock:
            if self._stopping or self._stopped:
                # checked BEFORE _started: stop() leaves _started True,
                # so the old order silently returned a dead engine
                raise EngineStopped("engine cannot be restarted")
            if self._started:
                return self
            # static verification of the model program this engine is
            # about to serve (PADDLE_TPU_VERIFY_IR, default off): a
            # malformed loaded program fails at start(), before any
            # worker thread exists, with the op/invariant named
            prog = getattr(self._predictor, "_program", None)
            if prog is not None:
                from ..analysis import maybe_verify_program

                fetch = [v.name for v in getattr(
                    self._predictor, "_fetch_vars", None) or []]
                maybe_verify_program(prog, where="serving.engine",
                                     fetch_names=fetch or None)
            if self.config.warmup:
                self._warming = True
                try:
                    self._warmup()
                finally:
                    self._warming = False
            for i in range(self.config.num_workers):
                t = threading.Thread(target=self._worker_loop,
                                     name="serving-worker-%d" % i,
                                     daemon=True)
                t.start()
                self._workers.append(t)
            self._started = True
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Refuse new submits; with ``drain`` finish queued work first,
        else fail queued requests with EngineStopped; join workers."""
        with self._state_lock:
            if self._stopped or not self._started:
                self._stopped = True
                self._stopping = True
                self._batcher.close()
                return
            self._stopping = True
        end = time.monotonic() + timeout  # ONE deadline for the whole
        # stop: drain wait + every join share it, so stop(timeout=30)
        # cannot block 30s per phase per worker
        if not drain:
            # abort BEFORE touching the queue: workers that win the
            # race for a queued batch fail it instead of dispatching
            # work the caller just abandoned
            self._abort = True
        else:
            while not self._batcher.empty() and time.monotonic() < end:
                time.sleep(self._POLL_S / 5)
        self._batcher.close()
        for t in self._workers:
            t.join(max(0.0, end - time.monotonic()))
        # whatever is STILL queued (no-drain mode, drain timeout, or a
        # submit that raced past close) must be failed, never stranded
        # — a stranded future hangs its caller forever
        while True:
            batch = self._batcher.next_batch(poll_timeout=0)
            if not batch:
                break
            for p in batch:
                self._fail(p, EngineStopped("engine stopped"))
        with self._state_lock:
            self._stopped = True

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def running(self) -> bool:
        return self._started and not self._stopping

    def health(self) -> str:
        """Machine-readable lifecycle for an external supervisor or
        fleet router: ``starting`` (constructed, ``start()`` not done)
        -> ``warming`` (pre-compiling ladder buckets) -> ``serving``
        (accepting work) -> ``draining`` (from the moment ``stop()``
        flips readiness until the workers have joined — stop routing
        NOW, but in-flight requests are still finishing) ->
        ``stopped``. A router must route ONLY at ``serving``."""
        if self._stopping or self._stopped:
            return "stopped" if self._stopped else "draining"
        if self.running:
            return "serving"
        if self._warming:
            return "warming"
        return "starting"

    def health_doc(self) -> Dict:
        """The /healthz body. ``engine_kind`` lets a prober (fleet
        router, steering daemon) tell a one-shot replica from a decode
        replica without schema-sniffing the rest of the payload; the
        decode engine's doc adds its KV-occupancy fields under the
        same contract."""
        return {"status": self.health(), "engine_kind": "oneshot",
                "queue_depth": self._batcher.depth()}

    # -- request path ------------------------------------------------------

    def submit(self, feed: Dict[str, np.ndarray],
               deadline_ms: Optional[float] = None,
               request_id: Optional[str] = None,
               cost_class: Optional[str] = None) -> Future:
        """Queue one request (arrays WITH leading batch axis; every
        input must agree on rows). Returns a Future resolving to a dict
        name -> ndarray of that request's rows.

        ``request_id`` makes the submit IDEMPOTENT: a duplicate of a
        pending or recently-completed id returns the ORIGINAL future —
        the predictor never runs twice for one id and the request is
        counted once (how a fleet's hedge/retry duplicates stay
        exactly-once on the replica). ``cost_class`` is accepted for
        interface parity with the fleet router; a single engine has no
        priority lanes and ignores it."""
        del cost_class  # single-replica engine: no shed lanes
        if not self._started or self._stopping:
            raise EngineStopped("engine is not accepting requests")
        if request_id is not None and self.config.request_id_cache > 0:
            with self._ids_lock:
                f = self._ids.get(str(request_id))
                if f is not None:
                    # LRU, not FIFO: a hot id (slow client re-sending,
                    # repeated hedges) must not be evicted by age
                    self._ids.move_to_end(str(request_id))
            if f is not None:
                _m.inc(_m.DEDUP_HITS)
                return f
        feed, rows = self._validate(feed)
        if rows > self.config.policy.max_batch_size:
            raise RequestTooLarge(
                "request has %d rows > max_batch_size %d"
                % (rows, self.config.policy.max_batch_size))
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        # capture the submitter's trace context (set per-request by the
        # HTTP front, or by any caller): the dispatch worker parents
        # this request's span to it, so one serving request is one
        # trace from HTTP arrival through batch dispatch
        pending = PendingRequest(feed, rows, deadline,
                                 trace_ctx=_dtrace.current())
        if request_id is not None and self.config.request_id_cache > 0:
            # register BEFORE the enqueue under the ids lock: two
            # concurrent duplicates race here, and the loser must find
            # the winner's future rather than enqueue a second copy
            with self._ids_lock:
                f = self._ids.get(str(request_id))
                if f is not None:
                    self._ids.move_to_end(str(request_id))
                    _m.inc(_m.DEDUP_HITS)
                    return f
                self._ids[str(request_id)] = pending.future
                while len(self._ids) > self.config.request_id_cache:
                    self._ids.popitem(last=False)
        if not self._batcher.try_put(pending):
            if request_id is not None:
                # a concurrent duplicate may ALREADY hold this future
                # from the dedup lookup above — resolving it with the
                # same rejection (before raising ours) is what keeps
                # that holder from blocking forever on a future whose
                # producer was never admitted
                with self._ids_lock:
                    self._ids.pop(str(request_id), None)
                exc = (EngineStopped("engine is not accepting requests")
                       if self._stopping else ServerOverloaded(
                           "pending queue full (%d requests); retry "
                           "later" % self.config.max_queue))
                self._fail(pending, exc)
            if self._stopping:
                # refusal came from close(), not capacity: a submit
                # that raced past the _stopping check above must not
                # report (and count) shutdown as backpressure
                raise EngineStopped("engine is not accepting requests")
            _m.inc(_m.REJECTED)
            raise ServerOverloaded(
                "pending queue full (%d requests); retry later"
                % self.config.max_queue)
        _m.inc(_m.REQUESTS)
        return pending.future

    def predict(self, feed: Dict[str, np.ndarray],
                deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None,
                request_id: Optional[str] = None,
                cost_class: Optional[str] = None) -> Dict[str, np.ndarray]:
        """Blocking submit().result() convenience."""
        return self.submit(feed, deadline_ms, request_id=request_id,
                           cost_class=cost_class).result(timeout)

    def stats(self) -> Dict:
        out = _m.snapshot()
        out["queue_depth"] = self._batcher.depth()
        out["warmed_buckets"] = list(self.warmed_buckets)
        out["running"] = self.running
        out["state"] = self.health()
        return out

    # -- internals ---------------------------------------------------------

    def _validate(self, feed):
        if not isinstance(feed, dict):
            raise ValueError("feed must be a dict name -> ndarray")
        missing = [n for n in self._input_names if n not in feed]
        extra = [n for n in feed if n not in self._input_names]
        if missing or extra:
            raise ValueError(
                "feed names mismatch: missing=%s unexpected=%s (inputs: %s)"
                % (missing, extra, self._input_names))
        arrs = {n: np.asarray(feed[n]) for n in self._input_names}
        rows = {n: (a.shape[0] if a.ndim else -1) for n, a in arrs.items()}
        distinct = set(rows.values())
        if len(distinct) != 1 or -1 in distinct:
            raise ValueError(
                "every input needs the same leading batch axis, got %s"
                % rows)
        n_rows = distinct.pop()
        if n_rows < 1:
            # a zero-row request would spend a whole padded dispatch
            # returning empty arrays — a client error, not work
            raise ValueError("request has no rows (leading axis is 0)")
        if self._spec:
            for n, a in arrs.items():
                tmpl = self._spec.get(n)
                if tmpl is None:
                    continue
                if tuple(a.shape[1:]) != tuple(tmpl.shape[1:]):
                    raise ValueError(
                        "input %r rows have shape %s, model expects %s"
                        % (n, tuple(a.shape[1:]), tuple(tmpl.shape[1:])))
                if a.dtype != tmpl.dtype:
                    arrs[n] = a.astype(tmpl.dtype)
        return arrs, n_rows

    def _warmup(self) -> None:
        """Run one dispatch per ladder bucket so every shape the
        batcher can emit is compiled before traffic arrives."""
        sample = self._spec
        if sample is None:
            return
        warmed = []
        for bucket in self.config.policy.ladder:
            feed = {n: np.broadcast_to(
                        v, (bucket,) + tuple(v.shape[1:])).copy()
                    for n, v in sample.items()}
            self._predictor.run(feed)
            warmed.append(bucket)
        self.warmed_buckets = tuple(warmed)

    def _derive_sample_feed(self) -> Optional[Dict[str, np.ndarray]]:
        """Zero single-row feeds from the predictor program's feed-var
        metadata; None when the predictor has no program surface (a
        stub) or a shape is unknown past the batch dim."""
        program = getattr(self._predictor, "_program", None)
        if program is None:
            return None
        block = program.global_block()
        sample = {}
        for name in self._input_names:
            v = block._find_var_recursive(name)
            if v is None or v.shape is None:
                return None
            tail = list(v.shape)[1:]
            if any(s is None or int(s) < 0 for s in tail):
                return None
            try:
                dtype = np.dtype(v.dtype)
            except TypeError:
                dtype = np.float32
            sample[name] = np.zeros([1] + [int(s) for s in tail], dtype)
        return sample

    def _worker_loop(self) -> None:
        while True:
            batch = self._batcher.next_batch(self._POLL_S)
            if not batch:
                if self._stopping and self._batcher.empty():
                    return
                continue
            if self._abort:
                for p in batch:
                    self._fail(p, EngineStopped("engine stopped"))
                continue
            self._dispatch(batch)

    def _dispatch(self, batch: List[PendingRequest]) -> None:
        now = time.monotonic()
        t0_perf = time.perf_counter()
        live = []
        for p in batch:
            if p.deadline is not None and now > p.deadline:
                _m.inc(_m.DEADLINE_EXPIRED)
                self._fail(p, DeadlineExpired(
                    "deadline passed %.1f ms ago while queued"
                    % ((now - p.deadline) * 1e3)))
            else:
                live.append(p)
        if not live:
            return
        try:
            feed, slices, bucket, pad = self._batcher.assemble(live)
        except Exception as e:  # noqa: BLE001 — e.g. trailing-shape
            # mismatch between requests surfacing at concatenate
            _m.inc(_m.ERRORS, len(live))
            for p in live:
                self._fail(p, e)
            return
        _m.inc(_m.BATCHES)
        _m.observe(_m.BATCH_SIZE, bucket - pad)
        if pad:
            _m.inc(_m.PADDING_WASTE, pad)
        for p in live:
            _m.observe(_m.QUEUE_MS, (now - p.t_enqueue) * 1e3)
        try:
            outs = self._predictor.run(feed)
            outputs = {t.name: np.asarray(t.data) for t in outs}
        except Exception as e:  # noqa: BLE001 — the MODEL failed: the
            # batch fails as a unit with the TYPED wrapper (HTTP 500),
            # serving.batch_errors counts the event once, and the
            # worker thread survives for the next batch
            _m.inc(_m.ERRORS, len(live))
            _m.inc(_m.BATCH_ERRORS)
            err = BatchExecutionError(
                "batch dispatch failed (%d request(s), bucket %d): "
                "%s: %s" % (len(live), bucket, type(e).__name__, e))
            err.__cause__ = e
            for p in live:
                self._fail(p, err)
            return
        try:
            results = self._batcher.split_outputs(outputs, slices, bucket)
        except Exception as e:  # noqa: BLE001 — unpadding failed (an
            # output-contract violation, not a model crash): resolve
            # the futures with the original error — a stranded future
            # would hang its caller forever
            _m.inc(_m.ERRORS, len(live))
            for p in live:
                self._fail(p, e)
            return
        done = time.monotonic()
        for p in live:
            # one span per co-batched request, parented into the
            # request's own propagated trace (an HTTP request with an
            # X-Trace-Id arrives, queues, and dispatches as ONE trace)
            if p.trace_ctx is not None:
                _dtrace.record_span("serving.dispatch", t0_perf,
                                    cat="serving", ctx=p.trace_ctx,
                                    bucket=bucket, rows=p.rows)
        for p, result in zip(live, results):
            _m.observe(_m.TOTAL_MS, (done - p.t_enqueue) * 1e3)
            try:
                p.future.set_result(result)
            except Exception:
                pass  # caller cancelled; result has nowhere to go

    @staticmethod
    def _fail(p: PendingRequest, exc: Exception) -> None:
        try:
            p.future.set_exception(exc)
        except Exception:
            pass
