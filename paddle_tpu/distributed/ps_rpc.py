"""Minimal socket RPC for the parameter-server runtime.

The reference's PS dataplane is gRPC/BRPC (operators/distributed/grpc/
grpc_client.cc, grpc_server.cc) with a sync round protocol
(listen_and_serv_op.cc:110 RunSyncLoop: wait for every trainer's grads,
run the optimize blocks, serve param reads until all trainers fetched)
and liveness tracking (heart_beat_monitor.h:54). This module provides
the same contract over plain TCP sockets — enough transport for real
multi-process PS training and its tests, without a gRPC dependency.

Wire format (no pickle — frames from the network must not be able to
execute code): 8-byte LE json-header length, json header, 8-byte LE raw
length, raw array bytes. The header carries only json-safe scalars;
arrays travel as dtype/shape in the header plus the raw section.

Round protocol (sync mode): send_grad buffers; the fanin-th
send_barrier sums each grad, runs its optimize block, and opens the
params; get_param waits for the open round; the fanin-th fetch_barrier
closes it. A send_barrier for round N+1 blocks until round N is fully
fetched — without that gate, a fast trainer's next round would flip
the round incomplete while a slow trainer is still mid-fetch and both
would deadlock.

Fault tolerance (reference grpc_client.cc deadline/retry +
heart_beat_monitor.h semantics):

- every frame passes through ``distributed/fault.py`` — the
  env-configured injector (``PADDLE_TPU_FAULTS``) that makes each
  recovery path below testable on one host;
- the client retries EVERY rpc with bounded exponential backoff +
  jitter after a timeout, EOF, or connection loss. Requests carry a
  ``(cid, round, seq)`` dedup token (``cid`` is a per-incarnation
  random nonce standing in for the trainer id, so a restarted
  trainer's fresh ``seq`` can never match its previous life's cache);
  the server executes each token exactly once — a retried
  ``send_grad``/barrier is summed/counted once no matter how many
  copies of the frame arrive. Responses echo ``seq`` so the client
  discards stale replies left in the stream by duplicated frames;
- the server evicts trainers whose heartbeats go silent past
  ``PADDLE_PS_EVICT_AFTER`` seconds: the effective fanin shrinks so
  surviving trainers' barriers complete instead of deadlocking, and
  the heartbeat response names the evicted so survivors
  log-and-continue. A relaunched trainer that sends again is
  re-admitted and the fanin grows back;
- ``rpc.retries`` / ``rpc.timeouts`` (labeled by rpc ``method``) /
  ``ps.evictions`` / ``ps.readmissions`` are recorded unconditionally
  in the observability registry (rare events, and CI asserts on them).

Replication + failover (ISSUE 4 — the reference's brpc failover /
checkpoint_notify availability tier, made survivable end to end):

- ``PADDLE_PSERVER_ENDPOINTS`` names an ordered primary + N backups.
  In sync mode the primary streams every applied round — round number,
  post-round scope blobs, and the per-client ``(cid -> seq)`` dedup
  watermark — to each live backup and waits for the acks BEFORE
  marking the round complete, so no trainer can observe (get_param) an
  update a promoted backup would not have;
- ``PSClient`` accepts a comma-separated endpoint list. When the
  bounded retry budget on the current endpoint is exhausted by
  transport failures (conn loss / timeout — never app errors), it
  advances to the next endpoint, replays its per-round log of
  non-idempotent rpcs (send_grad / send_barrier / push_sparse, with
  their ORIGINAL dedup tokens), and reissues the in-flight rpc. The
  replicated watermark makes replays of already-folded rpcs no-ops,
  so the replay is exactly-once on the new primary;
- promotion is deterministic: the lowest-index live endpoint. A backup
  only accepts the dataplane from a client that actually failed over
  (its rpcs carry a failover epoch ``fo >= 1``); fresh clients are
  redirected (``not_primary``) so a relaunched server can never steal
  traffic from the live primary (no split brain);
- a relaunched server (``PADDLE_PS_REJOIN=1``, set by the launch
  supervisor) rejoins as a backup: it refuses the dataplane until it
  has caught up from the active server's manifest-verified snapshot
  (``join_backup`` rpc -> ``snapshot_scope_to_dir`` ->
  ``checkpoint.load_scope_snapshot``), then receives the stream;
- counters: ``ps.failovers{cause=}``, ``ps.promotions``,
  ``ps.catchup_ms``, and the per-backup gauge
  ``ps.replication_lag_rounds{backup=}`` (0 after every ack; a backup
  that stops acking is dropped from the stream and the gauge freezes
  at its lag).

GB-scale replication + failover (ISSUE 8 — the reference's sparse /
geo-SGD PS heritage: key-range-sliced tables, delta shipping):

- **delta replication**: the primary no longer ships the full
  post-round parameter blob every round. It tracks a content digest
  per scope var; a round ships only the vars (or, for sparse tables
  updated by ``push_sparse``, only the touched ROWS) whose digest
  changed, with a periodic full-snapshot ANCHOR every
  ``PADDLE_PS_ANCHOR_EVERY`` rounds (default 8) so a rejoining backup
  bounds its replay. A backup that cannot apply a delta (freshly
  rejoined, behind) answers ``repl_gap`` and is re-anchored with a
  full blob instead of silently diverging. Both paths are gated
  bit-for-bit against each other by the ft suite.
  ``ps.replication_bytes{mode=full|delta}`` / ``ps.delta_rounds`` /
  ``ps.anchor_rounds`` make a regression back to full-blob shipping
  visible (and ``tools/bench_diff.py`` watches the bytes counter).
- **lease-based promotion with quorum**: the lowest-live-index
  promotion rule is replaced by a lease. The active primary renews a
  lease with every group peer each ``PADDLE_PS_LEASE_MS``/3 (renewal
  also rides every replication rpc); a backup may promote only after
  its lease view EXPIRED **and** a majority of the endpoint group
  grants its epoch bump (``vote`` rpc; each voter grants once per
  epoch, only while its own lease view is expired, and only to a
  candidate at least as caught up as itself). A connection REFUSAL is
  counted as a tombstone grant — on the drill topology a closed port
  is positive evidence no server owns that endpoint — while a TIMEOUT
  (what a real partition produces, and what the ``partition`` fault
  primitive injects) is no evidence and denies quorum. Net effect: a
  SIGKILLed primary is replaced within ~one lease, while a network
  partition yields AT MOST ONE writable primary (the isolated side
  fails loudly instead of splitting the brain). Epochs fence stale
  primaries: a lower-epoch primary that reaches a peer which has seen
  a newer epoch is told ``fenced`` and demotes itself, and a primary
  in a group of >= 3 that cannot renew with a majority for a full
  lease steps down (a majority might have elected a rival behind the
  partition; with 2 endpoints no rival quorum can form, so the
  primary soldiers on). ``PADDLE_PS_LEASE_MS=0`` restores the legacy
  instant fo>=1 promotion. Counters: ``ps.lease_renewals``,
  ``ps.lease_expiries{shard=}``.
- **async-mode round-gating**: an async (RunAsyncLoop) primary with
  backups replicates every ``PADDLE_PS_ASYNC_REPL_EVERY`` applied ops
  (default 32) as a synthetic round, and every async ack tells the
  client which replication round will carry that op
  (``pending_round`` / ``durable_round``). The client's failover
  replay log is round-gated on those tags — entries are dropped only
  once their round is replicated — so a failover mid-async-push is
  exactly-once like the sync path (closing the durability gap carried
  since ISSUE 4).

Sharding note: key-range partitioning of the parameter space across
multiple primary+backup groups lives in ``distributed/ps_shard.py``
(``ShardedPSClient`` routes by key and runs the two-phase round
barrier); each ``PSServer`` group is oblivious — it sees only its own
endpoint chain.

Elastic PS (ISSUE 13 — live migration, chunk digests, witnesses):

- **live key-range migration**: ``migrate_begin`` records an intent
  on the donor group's primary; the transfer executes INSIDE the next
  round apply, while every trainer is barrier-blocked — install the
  frozen range (+ the folded-seq watermark) on the recipient's
  primary (staged, not servable), soft-commit (shard-map version
  bump; the var stays in the donor's stream), replicate the round
  WITH the migration state to the donor's backups, then drive the
  recipient's commit (staged -> scope + block_factory-rebuilt
  optimize block + immediate push to the recipient's own backups)
  and hard-commit (drop the var from the donor's stream). Trainers
  adopt the bumped map atomically at the barrier ack or lazily via
  ``wrong_shard`` redirects whose tokens are un-recorded
  (exactly-once across the version bump; replays of pre-migration
  rpcs answer ``replayed`` at the recipient via the shipped
  watermark). Every kill window rolls back or completes through the
  epoch fence: an intent/override that reached the donor's stream is
  finished by the promoted backup; one that did not leaves the map
  unbumped everywhere a trainer can see (the recipient's staged
  orphan is superseded by any retry). Drilled by ``chaos_drill
  --migrate`` (donor primary SIGKILLed between install and commit).
- **chunk-level + incremental digests**: see the helpers around
  ``_chunk_digests`` — ``PADDLE_PS_DIGEST_CHUNK_MB`` (default 1),
  ``PADDLE_PS_INCR_DIGEST`` (default on). Counters ``ps.digest_ms``,
  ``ps.digest_vars{mode=hashed|rows|skipped}``;
  ``tools/ps_scale_bench.py`` records the cost/savings curves.
- **external quorum witnesses**: ``PADDLE_PS_WITNESSES`` names
  ``PSWitness`` endpoints outside every group; renewals include them,
  and an election needs a live witness GRANT on top of its GROUP-only
  quorum (witnesses gate, never provide margin — closing the
  forged-tombstone corner without letting candidate+witness depose a
  busy live primary). Voters keep Raft votedFor semantics (same
  candidate re-collects a lost grant) and a reachable active
  primary's denial vetoes the election.
  ``ps.witness_votes{shard=}``.
- **stale-round guard**: workers stamp the TRAINING round (``tr``) on
  send_grad/send_barrier; a round this server already applied
  (eviction shrank the fanin past a dead trainer) answers
  ``stale_round`` instead of contaminating the next round —
  ``ps.stale_rounds``, drilled by ``chaos_drill --evict``.
- **clock-jitter chaos**: every lease deadline and election timer is
  read through ``fault.clock_skew()`` (the ``clock_jitter:prob:ms``
  rule), so drills prove promotion stays quorum-gated under skewed
  clocks.

Distributed observability (ISSUE 5 — Dapper-style context riding the
existing frame):

- the client stamps ``trace_id`` / ``parent_span`` onto every rpc
  header (one trace per sync round, or the ambient context when one is
  installed — e.g. a serving request). The server opens a child span
  per rpc under the propagated context, and because ``child_span``
  installs itself thread-locally, the optimize apply and the
  replication rpcs it issues join the SAME trace — one round is one
  timeline across client, primary, and backups, retries/failovers/
  injected faults included. Old-frame peers ignore the extra fields;
- ``rpc.latency_ms{method=}`` observes every attempt's reply latency
  (retries observe separately) — the axis retry-policy tuning needs
  next to ``rpc.retries`` counts;
- every rpc token, retry, failover, replay, promotion, eviction, and
  round apply/applied pair is recorded in the crash flight recorder
  (``observability.flight``; heartbeat/status polls excluded so the
  bounded ring holds decisions, not noise) — dumped per-process into
  ``$PADDLE_TPU_METRICS_DIR`` and merged by ``tools/ft_timeline.py``
  into the cross-process postmortem.
"""
from __future__ import annotations

import base64
import hashlib
import json
import os
import random
import signal
import socket
import struct
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..observability import distributed as _dtrace
from ..observability import flight as _flight
from . import fault as _fault

_ROUND_TIMEOUT = float(os.environ.get("PADDLE_PS_ROUND_TIMEOUT", "120"))

# kinds whose per-frame flight events would flood the bounded ring
# (a heartbeater ticks every few hundred ms for the whole job, lease
# renewals every lease/3) — they still get latency histograms and
# trace spans, just no black-box line
_FLIGHT_QUIET = ("heartbeat", "repl_status", "lease_renew")


def _counter(name: str, **labels):
    from .. import observability as _obs

    return _obs.counter(name, **labels)


def _gauge(name: str, **labels):
    from .. import observability as _obs

    return _obs.gauge(name, **labels)


def _histogram(name: str, **labels):
    from .. import observability as _obs

    return _obs.histogram(name, **labels)


def _endpoints_from_env() -> List[str]:
    raw = os.environ.get("PADDLE_PSERVER_ENDPOINTS", "")
    return [e.strip() for e in raw.split(",") if e.strip()]


def _send_msg(sock: socket.socket, msg: dict,
              raw: bytes = b"") -> None:
    header = json.dumps(msg).encode("utf-8")
    frame = (struct.pack("<Q", len(header)) + header
             + struct.pack("<Q", len(raw)) + raw)
    inj = _fault.get_injector()
    if inj is not None:
        inj.on_send(sock, frame)  # may drop/dup/sever per the plan
    else:
        sock.sendall(frame)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    """Returns (msg_dict, raw_bytes) or None on EOF."""
    while True:
        inj = _fault.get_injector()
        action = inj.on_recv(sock) if inj is not None else "pass"
        h = _recv_exact(sock, 8)
        if h is None:
            return None
        (hlen,) = struct.unpack("<Q", h)
        header = _recv_exact(sock, hlen)
        if header is None:
            return None
        r = _recv_exact(sock, 8)
        if r is None:
            return None
        (rlen,) = struct.unpack("<Q", r)
        raw = _recv_exact(sock, rlen) if rlen else b""
        if raw is None:
            return None
        if action == "drop":
            continue  # injected: the frame evaporates in flight
        return json.loads(header.decode("utf-8")), raw


def _array_header(arr: np.ndarray) -> dict:
    return {"dtype": str(arr.dtype), "shape": list(arr.shape)}


def _array_from(header: dict, raw: bytes) -> np.ndarray:
    return np.frombuffer(raw, dtype=header["dtype"]).reshape(
        header["shape"]).copy()


def _var_digest(arr: np.ndarray) -> str:
    """Content digest the delta-replication planner diffs rounds by.
    Hashing GB-scale state every round is the price of shipping only
    what changed — blake2b streams at memory bandwidth, orders of
    magnitude under the network cost of the full blob it avoids."""
    return hashlib.blake2b(arr.tobytes(), digest_size=16).hexdigest()


# -- chunk-level digests (ISSUE 13: elasticity affordable at GB scale) ------
#
# A whole-var digest makes a GB embedding touched on ONE row ship the
# whole var whenever the touched-row set is unknown (promotion cleared
# it, a dense block updated it). Chunk digests bound that cost: every
# dense var is hashed as fixed-size chunks of its FLAT element stream
# (PADDLE_PS_DIGEST_CHUNK_MB, default 1 MiB; a var smaller than one
# chunk degenerates to the whole-var digest), a delta round ships only
# the chunks whose digest moved, and — with PADDLE_PS_INCR_DIGEST=1,
# the default — only the rows/chunks DIRTIED since the last ship are
# re-hashed at all. The soundness contract for the skip is family
# locality: the optimize block for ``w@GRAD`` touches only ``w`` and
# its ``@``-suffixed companions (true for every transpiled sgd/
# momentum/adam block and the pslib row-local sparse blocks); every
# ANCHOR re-hashes everything from scratch, so a contract violation is
# bounded to at most anchor_every rounds and caught by the bit-for-bit
# drills. PADDLE_PS_INCR_DIGEST=0 restores hash-everything-every-round.


def _digest_chunk_bytes() -> int:
    return max(1, int(float(os.environ.get(
        "PADDLE_PS_DIGEST_CHUNK_MB", "1")) * (1 << 20)))


def _incr_digest_enabled() -> bool:
    return os.environ.get("PADDLE_PS_INCR_DIGEST", "1") != "0"


def _chunk_elems_for(arr: np.ndarray) -> int:
    itemsize = max(1, int(arr.dtype.itemsize))
    return max(1, _digest_chunk_bytes() // itemsize)


def _chunk_hash(flat: np.ndarray, ci: int, ce: int) -> str:
    return hashlib.blake2b(flat[ci * ce:(ci + 1) * ce].tobytes(),
                           digest_size=16).hexdigest()


def _chunk_digests(flat: np.ndarray, ce: int) -> List[str]:
    n = max(1, -(-int(flat.size) // ce))  # >= 1 chunk even for empty
    return [_chunk_hash(flat, i, ce) for i in range(n)]


def _chunks_for_rows(rows, arr: np.ndarray, ce: int) -> set:
    """Chunk indices of the FLAT stream touched by the given row ids —
    a row whose byte range straddles a chunk boundary dirties BOTH
    chunks (the straddle edge case the tests pin)."""
    rowsize = int(np.prod(arr.shape[1:])) if arr.ndim > 1 else 1
    nchunks = max(1, -(-int(arr.size) // ce))
    out = set()
    for r in rows:
        lo = int(r) * rowsize
        hi = lo + rowsize - 1
        for ci in range(lo // ce, min(hi // ce, nchunks - 1) + 1):
            out.add(ci)
    return out


def _bare_rpc(endpoint: str, msg: dict, timeout: float = 1.0) -> dict:
    """One connect + frame exchange with none of PSClient's retry /
    dedup / failover machinery — the lease-and-vote control plane,
    where a failure IS the signal. ``ConnectionRefusedError``
    propagates distinctly: a refused connect means no listener owns
    the endpoint (positive evidence of process death on the drill
    topology, counted as a tombstone by elections), while a timeout —
    what a partition produces — is no evidence at all. Frames still
    route through the fault injector, so partitions drill this path
    too. Patchable by tests to simulate link states in-process."""
    host, port = endpoint.rsplit(":", 1)
    sock = socket.create_connection((host or "127.0.0.1", int(port)),
                                    timeout=timeout)
    try:
        sock.settimeout(timeout)
        _send_msg(sock, msg)
        got = _recv_msg(sock)
        if got is None:
            raise OSError("EOF from %s during %s"
                          % (endpoint, msg.get("kind")))
        return got[0]
    finally:
        try:
            sock.close()
        except OSError:
            pass


def snapshot_scope_to_dir(executor, scope, dirname: str,
                          names_map: bool = False) -> None:
    """Serialize every tensor var in ``scope`` into ``dirname`` in the
    reference tensor-stream format (shared by the server-side
    'checkpoint' RPC kind and the emulated checkpoint_notify path).

    checkpoint_notify fans out over SEVERAL pservers that share one
    dir — each contributes its shard's vars concurrently — so the
    write is a MERGE: every file lands via tmp+fsync+rename (never a
    torn file) and the sha256 manifest is rewritten over the whole dir
    after this server's files. A whole-dir rename would let racing
    shards clobber each other. Scope of the guarantee: the manifest
    certifies integrity of the files PRESENT (no torn/corrupt file
    loads as garbage); whether every EXPECTED server contributed is
    the notifier's concern — it fans out the RPCs and sees each
    server's ack or error.

    ``names_map=True`` additionally writes ``__vars__.json``
    (file name -> original var name) so a DEDICATED snapshot — the
    ``join_backup`` catch-up path — can restore vars whose names were
    munged for the filesystem. Never set it for SHARED multi-server
    dirs: concurrent shards would clobber each other's map."""
    import os

    from ..checkpoint import SCOPE_VARS_NAME, atomic_write_bytes, \
        makedirs_durable, write_manifest
    from ..core import proto_format

    # durable mkdir (ISSUE 19): a fresh snapshot dir's dirent must
    # survive a host crash, not just process death
    makedirs_durable(dirname)
    names: Dict[str, str] = {}
    for name in list(scope.local_var_names()):
        val = executor._read_var(scope, name)
        if val is None or not hasattr(val, "shape"):
            continue
        fn = name.replace("/", "_")
        names[fn] = name
        atomic_write_bytes(
            os.path.join(dirname, fn),
            proto_format.serialize_lod_tensor(np.asarray(val)))
    if names_map:
        atomic_write_bytes(
            os.path.join(dirname, SCOPE_VARS_NAME),
            json.dumps(names, indent=1, sort_keys=True).encode())
    write_manifest(dirname)


class HeartBeatMonitor:
    """Per-trainer last-ping tracking (heart_beat_monitor.h:54)."""

    def __init__(self, stale_seconds: float = 60.0):
        self._last: Dict[int, float] = {}
        self._stale = stale_seconds
        self._lock = threading.Lock()

    def ping(self, trainer_id: int) -> None:
        with self._lock:
            self._last[int(trainer_id)] = time.time()

    def register(self, trainer_ids) -> None:
        """Start the staleness clock for expected trainers that have
        not pinged yet — a rank that dies BEFORE its first rpc must
        still become evictable, or survivors would wait out the full
        round timeout on a trainer the monitor never heard of."""
        now = time.time()
        with self._lock:
            for t in trainer_ids:
                self._last.setdefault(int(t), now)

    def forget(self, trainer_id: int) -> None:
        """Drop a trainer's entry (post-eviction: a stale entry would
        re-report the same trainer forever; re-admission re-pings)."""
        with self._lock:
            self._last.pop(int(trainer_id), None)

    def status(self) -> Dict[int, float]:
        """trainer_id -> seconds since last ping."""
        now = time.time()
        with self._lock:
            return {t: now - ts for t, ts in self._last.items()}

    def stale_trainers(self) -> List[int]:
        return [t for t, age in self.status().items()
                if age > self._stale]


class PSServer:
    """Sync-mode PS endpoint implementing the RunSyncLoop round
    protocol; async mode applies each grad immediately (RunAsyncLoop).

    ``evict_after`` (seconds; env ``PADDLE_PS_EVICT_AFTER``, 0 =
    disabled) arms the heartbeat monitor: a trainer silent that long is
    evicted — its slot leaves the effective fanin so the surviving
    trainers' barriers complete, and the heartbeat response carries the
    eviction so survivors can log-and-continue.

    ``endpoints`` (env ``PADDLE_PSERVER_ENDPOINTS``) is the ordered
    primary + backups list this server belongs to; index 0 starts as
    the active primary, the rest as replication backups that refuse
    the trainer dataplane until a genuinely failed-over client
    promotes them. ``rejoin=True`` (env ``PADDLE_PS_REJOIN``, set by
    the launch supervisor on a server relaunch) starts the server as
    an un-caught-up backup that first pulls a manifest-verified
    snapshot from the active server."""

    _DEDUPE_CAP = 512  # distinct live client nonces remembered

    # rpcs that belong to trainers (gated on primary role); everything
    # else — heartbeat, replication, catch-up, shutdown — any role
    # answers
    _DATAPLANE = ("send_grad", "send_barrier", "get_param",
                  "fetch_barrier", "pull_sparse", "push_sparse")

    def __init__(self, endpoint: str, executor, scope, grad_to_block,
                 fanin: int = 1, sync_mode: bool = True,
                 evict_after: Optional[float] = None,
                 endpoints: Optional[List[str]] = None,
                 rejoin: Optional[bool] = None,
                 anchor_every: Optional[int] = None,
                 lease_ms: Optional[float] = None,
                 shard: Optional[int] = None,
                 witnesses: Optional[List[str]] = None,
                 block_factory=None,
                 durable_dir: Optional[str] = None):
        host, port = endpoint.rsplit(":", 1)
        # endpoint-pair partition rules address server processes by
        # their advertised endpoint; first server in wins (one server
        # per process everywhere but in-process unit tests)
        if _fault.get_identity() is None:
            _fault.set_identity(endpoint)
        self._executor = executor
        self._scope = scope
        self._grad_to_block = grad_to_block
        self._fanin = max(int(fanin), 1)
        self._sync = bool(sync_mode)
        # -- replication topology -----------------------------------------
        if endpoints is None:
            endpoints = _endpoints_from_env()
        self._endpoints = [e.strip() for e in (endpoints or [])
                           if e.strip()]
        self._own_endpoint = endpoint
        try:
            self._index = self._endpoints.index(endpoint)
        except ValueError:
            self._index = 0
            self._endpoints = [endpoint]
        if rejoin is None:
            rejoin = os.environ.get("PADDLE_PS_REJOIN") == "1"
        self._rejoin = bool(rejoin)
        self._active = (self._index == 0 and not self._rejoin)
        self._promoted = False
        self._caught_up = not self._rejoin
        self._applied_round = 0
        # cid -> highest seq whose effect is folded into the replicated
        # state this server holds: a failover replay at-or-below it is
        # acknowledged without re-executing (exactly-once across the
        # promotion)
        self._repl_watermark: Dict[str, int] = {}
        # the watermark AS OF THE LAST APPLIED ROUND — the only thing
        # ever shipped to backups. The live ``_last_seq`` also covers
        # rpcs buffered in the CURRENT unapplied round (a join_backup
        # can land mid-round); shipping those would make a promoted
        # backup falsely skip their replay and lose the round.
        self._applied_watermark: Dict[str, int] = {}
        self._repl_clients: Dict[str, "PSClient"] = {}
        self._repl_dead: set = set()
        self._repl_deadline = float(
            os.environ.get("PADDLE_PS_REPL_DEADLINE", "10"))
        self._repl_connect = float(
            os.environ.get("PADDLE_PS_REPL_CONNECT_TIMEOUT", "3"))
        # -- delta replication (ISSUE 8 / 13) -----------------------------
        # per-var digest STATE of what was last shipped to the stream
        # ({"chunks": [...], "chunk_elems":, "nelems":, "dtype":} —
        # chunk-level, ISSUE 13); empty => next ship is a full anchor
        # (fresh primary, fresh promotion)
        self._shipped_digests: Dict[str, dict] = {}
        # param var -> set of rows touched by push_sparse since the
        # last ship: lets a delta round ship row SLICES of a sparse
        # table (sound because pslib sparse optimize blocks are
        # row-local); a dense round touching the var's FAMILY
        # escalates it to _dirty_dense — full-var diff wins there
        self._dirty_rows: Dict[str, set] = {}
        # vars whose family a dense round touched since the last ship:
        # re-hashed fully at the next plan. Vars in NEITHER dirty set
        # skip hashing entirely under PADDLE_PS_INCR_DIGEST=1 (their
        # shipped digests carry over — the incremental-digest win)
        self._dirty_dense: set = set()
        self._incr_digest = _incr_digest_enabled()
        if anchor_every is None:
            anchor_every = int(os.environ.get("PADDLE_PS_ANCHOR_EVERY",
                                              "8"))
        self._anchor_every = int(anchor_every)
        self._async_ops = 0
        self._async_repl_every = int(
            os.environ.get("PADDLE_PS_ASYNC_REPL_EVERY", "32"))
        # highest round at least one backup has ACKED — what async
        # clients may prune their replay logs up to
        self._durable_round = 0
        # -- lease + quorum promotion (ISSUE 8) ---------------------------
        if shard is None:
            shard = int(os.environ.get("PADDLE_PSERVER_SHARD", "0"))
        self._shard = str(int(shard))
        self._shard_index = int(shard)
        # -- external quorum witnesses (ISSUE 13) -------------------------
        # extra vote/renewal endpoints OUTSIDE the replication group.
        # Witnesses are a pure SAFETY gate: they never join the quorum
        # arithmetic (a candidate + a witness must not be able to
        # out-vote a busy-but-alive primary whose handlers are briefly
        # starved — quorum stays group-only), but with witnesses
        # configured an election ADDITIONALLY needs at least one live
        # witness GRANT (positive evidence the primary stopped
        # renewing), closing the corner where N-1 forged
        # connection-REFUSALs alone could elect a backup under a live
        # primary. A REFUSED witness is itself a tombstone (a dead
        # witness must not freeze promotion forever); a TIMED-OUT one
        # keeps the requirement (a partition must not relax it).
        if witnesses is None:
            witnesses = [e.strip() for e in os.environ.get(
                "PADDLE_PS_WITNESSES", "").split(",") if e.strip()]
        self._witnesses = list(witnesses or [])
        # -- live shard migration (ISSUE 13) ------------------------------
        # shard-map overrides this group knows about: var base name ->
        # {"shard": owner index, "version": map version, "committed":
        # bool, "to_endpoints": donor-side recipient chain}; version 0
        # = the pure hash map. Replicated to backups with every round.
        self._shard_map_version = 0
        self._map_overrides: Dict[str, dict] = {}
        # donor side: the migration requested but not yet executed
        # (runs at the next round apply, inside the barrier)
        self._pending_migration: Optional[dict] = None
        # recipient side: installed-but-uncommitted var blobs
        self._staged_in: Dict[str, dict] = {}
        # vars hard-committed AWAY from this group: masked from
        # replication/anchors (the scope copy may linger — routing
        # answers wrong_shard before scope is ever consulted)
        self._dropped: set = set()
        self._mig_clients: Dict[str, "PSClient"] = {}
        # -- row-range live migration (ISSUE 18) --------------------------
        # per-table ROW-RANGE overrides: table base name -> list of
        # {"lo","hi" (GLOBAL row ids), "shard" (new owner),
        # "local_base" (recipient-LOCAL id of global lo), "version",
        # "committed"; donor side additionally "src_lo"/"src_hi" (the
        # donor-LOCAL window that moved) + "to_endpoints"}. Rides the
        # replication stream like _map_overrides.
        self._range_overrides: Dict[str, List[dict]] = {}
        # donor side: the row-range migration requested but not yet
        # executed (runs at the next round apply, inside the barrier)
        self._pending_range_migration: Optional[dict] = None
        # recipient side: installed-but-uncommitted row-range stages,
        # keyed by table name (a re-install replaces the orphan)
        self._staged_ranges: Dict[str, dict] = {}
        # grad name -> optimize block builder for vars migrating IN
        # (a migration ships state, never code; the factory rebuilds
        # the block from the shared program definition)
        self._block_factory = block_factory
        if lease_ms is None:
            lease_ms = float(os.environ.get("PADDLE_PS_LEASE_MS",
                                            "1500"))
        self._lease_s = float(lease_ms) / 1e3
        self._epoch = 0           # the epoch this server serves at
        self._seen_epoch = 0      # highest epoch heard from any primary
        self._promised_epoch = 0  # highest epoch this voter granted
        self._promised_to = None  # who holds that promise (votedFor)
        # boot grace: a backup must never elect before the primary had
        # one full lease to introduce itself (clock-jitter chaos skews
        # this view too, like every other lease read)
        self._lease_deadline = (time.monotonic() + self._lease_s
                                + _fault.clock_skew())
        self._lease_expired_counted = False
        self._last_majority_ack = time.monotonic()
        self._election_lock = threading.Lock()
        if evict_after is None:
            evict_after = float(os.environ.get("PADDLE_PS_EVICT_AFTER",
                                               "0"))
        self._evict_after = float(evict_after)
        self.monitor = HeartBeatMonitor(
            stale_seconds=self._evict_after if self._evict_after > 0
            else 60.0)
        self._evicted: set = set()
        self._clock_started = False
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # var name -> {trainer_id: grad}: keyed (not appended) so a
        # relaunched trainer RE-SENDING the round it died in REPLACES
        # its dead incarnation's contribution instead of double
        # counting it, and summed in sorted-tid order so the applied
        # total is bit-deterministic regardless of arrival order
        self._pending: Dict[str, Dict[int, np.ndarray]] = {}
        self._send_barriers = 0
        self._fetch_barriers = 0
        self._round_complete = True   # params servable before round 1
        self._fetches_pending = False  # True between apply and last fetch
        # per-client (token, response) cache: the client resends after a
        # reconnect; without dedupe a response lost AFTER server-side
        # processing would double-apply a grad/barrier in the round.
        # Keyed by the client's random nonce (NOT trainer_id: the
        # background heartbeater is a second connection with the same
        # trainer_id, and sharing one slot would let its traffic evict
        # the main client's in-flight entry mid-retry).
        self._dedupe: Dict[str, list] = {}   # cid -> [key, ev, resp, raw, ts]
        self._last_seq: Dict[str, int] = {}  # cid -> highest seq admitted
        self._dedupe_lock = threading.Lock()
        self._shutdown = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host or "127.0.0.1", int(port)))
        self._sock.listen(16)
        self._threads: List[threading.Thread] = []
        self._conns: set = set()
        self._conn_lock = threading.Lock()
        # -- whole-job durable rounds (ISSUE 19) --------------------------
        # With a durable dir armed, the primary TEES every applied
        # round's replication frame to disk (same delta/anchor blobs
        # the backups get) so a CORRELATED loss — every member of the
        # group, or the whole job — can cold-restart from a
        # round-consistent cut. Restore runs here, before any serving
        # thread exists, when the launcher exported
        # PADDLE_PS_RESTORE=1 (never on a live-failover rejoin: the
        # replication stream outranks disk for a process that has a
        # living group to catch up from).
        if durable_dir is None:
            durable_dir = os.environ.get("PADDLE_PS_DURABLE_DIR") or None
        self._durable_store = None
        if durable_dir:
            from ..checkpoint import RoundStore

            self._durable_store = RoundStore(durable_dir,
                                             self._shard_index)
        self._restored_round = 0
        if (self._durable_store is not None and not self._rejoin
                and os.environ.get("PADDLE_PS_RESTORE") == "1"):
            self._restore_from_disk()
        if self._evict_after > 0:
            t = threading.Thread(target=self._evict_loop,
                                 name="ps-evict-monitor", daemon=True)
            t.start()
            self._threads.append(t)
        if self._rejoin:
            t = threading.Thread(target=self._catchup_loop,
                                 name="ps-catchup", daemon=True)
            t.start()
            self._threads.append(t)
        if len(self._endpoints) > 1 and self._lease_s > 0:
            t = threading.Thread(target=self._lease_loop,
                                 name="ps-lease", daemon=True)
            t.start()
            self._threads.append(t)

    # -- round protocol ---------------------------------------------------

    def _effective_fanin(self) -> int:
        return max(1, self._fanin - len(self._evicted))

    def _stale_train_round_locked(self, msg: dict) -> bool:
        """True when the rpc names a TRAINING round (``tr``, stamped
        by workers that track one) this server already applied — the
        re-send of a relaunched trainer re-running a round that
        sailed without it (eviction shrank the fanin, or its dead
        incarnation's barrier already closed it). Distinct from the
        ``(cid, round, seq)`` dedup token, which a fresh incarnation
        cannot reproduce."""
        tr = msg.get("tr")
        stale = tr is not None and int(tr) <= self._applied_round
        if stale:
            _counter("ps.stale_rounds").inc()
            _flight.record("ps.stale_round", kind=msg.get("kind"),
                           tr=int(tr), applied=self._applied_round,
                           trainer=msg.get("trainer_id"))
        return stale

    def _apply_round(self):
        """All trainers' grads in (locked by caller): sum per var, run
        its optimize block, replicate the applied round to every live
        backup (acks REQUIRED before the round reads as complete — a
        promoted backup must never be behind a state any trainer has
        observed), then open params for reading."""
        nxt = self._applied_round + 1
        # begin/applied flight pair: a primary SIGKILLed mid-apply
        # leaves "ps.round_apply" with no matching "ps.round_applied"
        # in its last periodic dump — the postmortem's smoking gun
        _flight.record("ps.round_apply", round=nxt,
                       vars=len(self._pending))
        t_apply = time.monotonic()
        with _dtrace.child_span("ps.apply_round", cat="ps", round=nxt):
            # a dense round touches, by the family-locality contract,
            # its grad's base var and every @-companion of it: mark
            # those FAMILIES dense-dirty (full re-hash + full-var /
            # chunk diff at the next ship) and escalate any row-slice
            # tracking they had — row tracking is only sound between
            # dense touches of that family
            self._mark_families_dirty_locked(list(self._pending))
            for name in sorted(self._pending):
                by_tid = self._pending[name]
                tids = sorted(by_tid)
                total = by_tid[tids[0]]
                for t in tids[1:]:
                    total = total + by_tid[t]
                self._executor._write_var(self._scope, name, total)
                sub = self._grad_to_block.get(name)
                if sub is not None:
                    t_blk = time.monotonic()
                    self._executor.run_block(sub, self._scope)
                    # per-TABLE apply timing: the hot-shard steerer
                    # needs to name the hot table, not just the group
                    _histogram("ps.apply_ms", shard=self._shard,
                               table=name.split("@", 1)[0]).observe(
                        (time.monotonic() - t_blk) * 1e3)
            self._pending.clear()
            self._send_barriers = 0
            self._applied_round += 1
            # safe point for a watermark snapshot: every processed
            # send-kind seq is now folded into the scope (trainers
            # cannot have sent next-round traffic — their barriers
            # haven't returned yet)
            self._applied_watermark = self._watermark_locked()
            # live migration rides the same barrier: the range is
            # frozen HERE (no trainer can observe the round until the
            # install + the replication below both finished)
            self._step_migration_locked()
            self._step_range_migration_locked()
            self._replicate_locked()
            self._commit_migrations_locked()
        # per-shard apply timing (ROADMAP hot-shard detector input):
        # always-on like every ps.* family, labeled by shard so the
        # merged dump shows which shard's optimize blocks run hot —
        # the steering daemon's migration signal lands here first.
        # table="_round" is the whole-round series; real tables get
        # their own series at the block run / sparse push.
        _histogram("ps.apply_ms", shard=self._shard,
                   table="_round").observe(
            (time.monotonic() - t_apply) * 1e3)
        _flight.record("ps.round_applied", round=self._applied_round)
        self._round_complete = True
        self._fetches_pending = True
        self._cond.notify_all()

    def _family_index(self):
        """base name -> [scope vars in that family], one O(V) pass —
        the apply marks G families against it instead of scanning the
        scope per grad (O(V+G), not O(V*G), under the server lock)."""
        fams: Dict[str, list] = {}
        for vn in list(self._scope.local_var_names()):
            fams.setdefault(vn.split("@", 1)[0], []).append(vn)
        return fams

    def _mark_families_dirty_locked(self, names) -> None:
        """A dense update touched these grads' families: each base var
        and every ``@``-companion must be re-hashed at the next ship
        (and any row-slice tracking for them is no longer sound)."""
        fams = self._family_index()
        for name in names:
            for vn in fams.get(name.split("@", 1)[0], ()):
                self._dirty_dense.add(vn)
                self._dirty_rows.pop(vn, None)

    # -- replication (primary -> backups) ---------------------------------

    def _repl_targets(self) -> List[str]:
        return [ep for ep in self._endpoints
                if ep != self._own_endpoint and ep not in self._repl_dead]

    def _repl_client(self, ep: str) -> "PSClient":
        c = self._repl_clients.get(ep)
        if c is None:
            c = PSClient(ep, trainer_id=None, auto_heartbeat=False,
                         timeout=self._repl_connect,
                         rpc_deadline=self._repl_deadline,
                         max_retries=int(os.environ.get(
                             "PADDLE_PS_REPL_RETRIES", "3")))
            self._repl_clients[ep] = c
        return c

    def _scope_arrays(self) -> List[tuple]:
        """[(name, contiguous array)] for every tensor var in scope —
        minus vars hard-committed away by a migration (their scope
        copy may linger; the stream must stop carrying them)."""
        out = []
        for name in list(self._scope.local_var_names()):
            if name in self._dropped:
                continue
            val = self._executor._read_var(self._scope, name)
            if val is None or not hasattr(val, "shape"):
                continue
            out.append((name, np.ascontiguousarray(np.asarray(val))))
        return out

    @staticmethod
    def _blobs_for(items) -> tuple:
        """(headers, raw) for [(name, array, extra-or-None)] — an
        ``extra`` of ``{"rows": [...]}`` is a row SLICE of the named
        table (local row ids), ``{"chunk": [start, stop]}`` a FLAT
        element range of it (chunk-digest delta); without either the
        array replaces the whole var."""
        headers, chunks = [], []
        for name, arr, extra in items:
            h = _array_header(arr)
            h["name"] = name
            if extra:
                h.update(extra)
            headers.append(h)
            chunks.append(arr.tobytes())
        return headers, b"".join(chunks)

    def _scope_blobs(self):
        """Full-blob (headers, raw) for every tensor var — the anchor
        payload and the ``repl_gap`` re-anchor fallback."""
        return self._blobs_for(
            [(n, a, None) for n, a in self._scope_arrays()])

    def _watermark_locked(self) -> Dict[str, int]:
        """Per-cid seq watermark covering every rpc folded into the
        state being replicated (own processed seqs plus any watermark
        this server itself inherited through a promotion)."""
        with self._dedupe_lock:
            wm = dict(self._last_seq)
        for cid, s in self._repl_watermark.items():
            if int(wm.get(cid, 0)) < int(s):
                wm[cid] = int(s)
        return wm

    def _replication_plan(self, arrays) -> tuple:
        """(mode, items, digests) for the round about to ship: a FULL
        anchor when nothing was ever shipped or the anchor interval
        divides the round (every var fully re-hashed — the digest
        state RESETS at anchors, bounding any incremental-skip drift);
        otherwise a DELTA of only the vars whose chunk digests moved —
        as row slices where push_sparse recorded which rows changed
        and the slice beats the var, as flat CHUNK slices where only
        some chunks of a big dense var moved, else whole vars. Under
        ``PADDLE_PS_INCR_DIGEST=1`` vars in neither dirty set skip
        hashing entirely and row-dirty tables re-hash only the touched
        chunks (``ps.digest_vars{mode=}`` counts both paths;
        ``ps.digest_ms`` accumulates the hashing bill)."""
        t0 = time.perf_counter()
        prev = self._shipped_digests
        anchor = (not prev
                  or (self._anchor_every > 0 and self._applied_round
                      % self._anchor_every == 0))
        incr = self._incr_digest and not anchor
        digests: Dict[str, dict] = {}
        items = []
        for n, a in arrays:
            flat = a.reshape(-1)
            ps = prev.get(n)
            ce = _chunk_elems_for(a)
            compat = (ps is not None
                      and ps.get("chunk_elems") == ce
                      and ps.get("nelems") == int(flat.size)
                      and ps.get("dtype") == str(a.dtype))
            touched = n in self._dirty_dense or n in self._dirty_rows
            if incr and compat and not touched:
                # untouched since the last ship: the shipped digests
                # carry over UNHASHED — the incremental-digest win
                digests[n] = ps
                _counter("ps.digest_vars", mode="skipped").inc()
                continue
            rows = self._dirty_rows.get(n)
            if (incr and compat and rows is not None
                    and n not in self._dirty_dense):
                # row-dirty only: re-hash just the chunks those rows
                # touch, carry the rest over
                chunks = list(ps["chunks"])
                for ci in sorted(_chunks_for_rows(rows, a, ce)):
                    chunks[ci] = _chunk_hash(flat, ci, ce)
                state = dict(ps)
                state["chunks"] = chunks
                _counter("ps.digest_vars", mode="rows").inc()
            else:
                state = {"chunks": _chunk_digests(flat, ce),
                         "chunk_elems": ce, "nelems": int(flat.size),
                         "dtype": str(a.dtype)}
                _counter("ps.digest_vars", mode="hashed").inc()
            digests[n] = state
            if anchor:
                continue  # the anchor ships every var below anyway
            if compat and ps["chunks"] == state["chunks"]:
                continue  # digest says unchanged
            if (rows and n not in self._dirty_dense
                    and getattr(a, "ndim", 0) >= 1
                    and len(rows) < int(a.shape[0])):
                # rows re-dirtied AFTER a dense touch in the same
                # window (e.g. a push right after a range-move zeroed
                # its slice) must not shrink the ship to the slice —
                # the dense change would silently never reach backups
                rs = np.asarray(sorted(rows), dtype=np.int64)
                items.append((n, np.ascontiguousarray(a[rs]),
                              {"rows": rs.tolist()}))
            elif compat and len(state["chunks"]) > 1:
                changed = [i for i, (x, y) in
                           enumerate(zip(ps["chunks"],
                                         state["chunks"])) if x != y]
                if not changed:
                    continue
                # contiguous runs of changed chunks -> flat slices
                runs = [[changed[0], changed[0]]]
                for ci in changed[1:]:
                    if ci == runs[-1][1] + 1:
                        runs[-1][1] = ci
                    else:
                        runs.append([ci, ci])
                for lo, hi in runs:
                    s, e = lo * ce, min((hi + 1) * ce, int(flat.size))
                    items.append((n, np.ascontiguousarray(flat[s:e]),
                                  {"chunk": [s, e]}))
            else:
                items.append((n, a, None))
        if anchor:
            items = [(n, a, None) for n, a in arrays]
        _counter("ps.digest_ms").inc(
            (time.perf_counter() - t0) * 1e3)
        return ("full" if anchor else "delta"), items, digests

    def _replicate_locked(self) -> None:
        """Stream the just-applied round to every live backup and wait
        for each ack (locked by caller — the round stays incomplete,
        and unfetchable, until the backups hold it). Ships a DELTA of
        what changed (full anchor every ``_anchor_every`` rounds); a
        backup answering ``repl_gap`` (freshly rejoined / behind the
        delta's base) is re-anchored with a full blob on the spot. A
        backup that fails the short replication deadline is dropped
        from the stream (its lag gauge freezes; a relaunch re-enters
        via join_backup); one that answers ``fenced`` outranks us — a
        higher-epoch primary exists — and this server demotes."""
        if not self._active_role():
            return
        targets = self._repl_targets()
        if not targets and self._durable_store is None:
            # no stream to diff against: keep dirty tracking bounded
            # and digests empty so a first backup gets a clean anchor
            self._dirty_rows.clear()
            self._dirty_dense.clear()
            return
        arrays = self._scope_arrays()
        mode, items, digests = self._replication_plan(arrays)
        headers, raw = self._blobs_for(items)
        full_cache = (headers, raw) if mode == "full" else None
        wm = self._applied_watermark
        base = self._applied_round - 1
        extra = self._repl_extra_locked()
        # durable tee BEFORE shipping (ISSUE 19): the frame must be on
        # disk before any barrier reply can make trainers observe the
        # round, so a whole-job kill always finds every shard's disk
        # at-or-past any round a trainer checkpointed. Same blobs as
        # the wire — per-round durable bytes ride the delta path.
        if self._durable_store is not None:
            self._persist_round_locked(mode, headers, raw, wm, base,
                                       extra)
        acked = 0
        for ep in targets:
            _gauge("ps.replication_lag_rounds", backup=ep).set(1)
            try:
                resp = self._repl_client(ep).replicate(
                    self._applied_round, headers, raw, wm, mode=mode,
                    base_round=base, epoch=self._epoch, extra=extra)
                if resp.get("fenced"):
                    self._demote_locked(int(resp.get("epoch", 0)),
                                        "fenced by %s during "
                                        "replication" % ep)
                    return
                if resp.get("repl_gap"):
                    if full_cache is None:
                        full_cache = self._blobs_for(
                            [(n, a, None) for n, a in arrays])
                    fh, fraw = full_cache
                    self._repl_client(ep).replicate(
                        self._applied_round, fh, fraw, wm,
                        mode="full", base_round=base,
                        epoch=self._epoch, extra=extra)
                    _counter("ps.replication_bytes",
                             mode="full").inc(len(fraw))
                    _flight.record("ps.reanchor", backup=ep,
                                   round=self._applied_round)
                else:
                    _counter("ps.replication_bytes",
                             mode=mode).inc(len(raw))
                _gauge("ps.replication_lag_rounds", backup=ep).set(0)
                acked += 1
            except (RuntimeError, OSError) as e:
                self._repl_dead.add(ep)
                _flight.record("ps.backup_dropped", backup=ep,
                               round=self._applied_round)
                try:
                    self._repl_clients.pop(ep).close()
                except (KeyError, OSError):
                    pass
                print("[ps_rpc] dropping backup %s from the replication"
                      " stream at round %d: %s"
                      % (ep, self._applied_round, e),
                      file=sys.stderr, flush=True)
        _counter("ps.anchor_rounds" if mode == "full"
                 else "ps.delta_rounds").inc()
        if acked:
            self._durable_round = self._applied_round
        self._shipped_digests = digests
        self._dirty_rows.clear()
        self._dirty_dense.clear()

    # -- whole-job durable rounds (ISSUE 19) ------------------------------
    #
    # Live replication survives PARTIAL failures; these methods make
    # the group survive a CORRELATED one. Every applied round's
    # replication frame (headers + raw blob + watermark + shard-map /
    # migration extras + fencing epoch) is persisted atomically under
    # ``<durable_dir>/shard-<k>/round-<n>/`` by the active primary,
    # and a cold-booting server replays the newest anchor chain with
    # the SAME splice semantics a backup applies — so a restored shard
    # is bit-for-bit the state any trainer could have observed at that
    # round. The launcher computes the job-wide cut (the newest round
    # present on EVERY shard) and pins it via PADDLE_PS_RESTORE_ROUND;
    # a shard never restores past it, so a mixed cut cannot happen.

    def _persist_round_locked(self, mode, headers, raw, wm, base,
                              extra) -> None:
        """Tee the just-applied round's frame to disk (locked by
        caller, BEFORE the barrier reply). A persist failure is loud
        but non-fatal: the job keeps training on live replication and
        the operator sees ``ps.durable_errors`` grow."""
        try:
            self._durable_store.put_round(
                self._applied_round, headers, raw, wm, mode=mode,
                base_round=(base if mode == "delta" else None),
                epoch=self._epoch, extra=extra)
            # ops folded into this frame are covered by it now
            self._durable_store.clear_ops_through(self._applied_round)
        except OSError as e:
            _counter("ps.durable_errors").inc()
            print("[ps_rpc] durable persist of round %d failed: %s"
                  % (self._applied_round, e), file=sys.stderr,
                  flush=True)
            return
        # disk is at least as durable as a backup ack: async clients
        # may prune replay-log entries folded into this frame
        self._durable_round = self._applied_round
        _flight.record("ps.round_durable", round=self._applied_round,
                       mode=mode, shard=self._shard)

    def _restore_from_disk(self) -> None:
        """Cold-restart resume (boot-time, before any serving thread):
        load the target round's anchor chain, re-arm the fencing epoch
        PAST the dead incarnation so its stragglers are refused, and
        replay the async op tail exactly-once against the restored
        watermark. Every group member restores (a backup that booted
        at the cut applies the primary's next delta without a
        re-anchor); only the active primary bumps its serving epoch."""
        from ..checkpoint import CheckpointCorrupt

        store = self._durable_store
        rounds = store.restorable_rounds()
        if not rounds:
            return
        tgt_env = os.environ.get("PADDLE_PS_RESTORE_ROUND", "")
        target = int(tgt_env) if tgt_env else rounds[-1]
        if target not in set(rounds):
            eligible = [r for r in rounds if r <= target]
            if not eligible:
                raise CheckpointCorrupt(
                    "shard %s cannot reach the job restore cut %d: "
                    "restorable rounds are %s"
                    % (self._shard, target, rounds))
            target = eligible[-1]
        t0 = time.monotonic()
        with self._lock:
            store.load_round(target, self._apply_restore_frame)
            meta = store.meta(target) or {}
            stored_epoch = int(meta.get("epoch", 0))
            # fence out the DEAD incarnation: any straggler still
            # speaking its epoch is refused by every restored member
            self._seen_epoch = max(self._seen_epoch, stored_epoch + 1)
            if self._active:
                self._epoch = max(self._epoch, stored_epoch + 1)
            self._applied_round = target
            self._durable_round = target
            self._restored_round = target
            self._applied_watermark = dict(self._repl_watermark)
            self._caught_up = True
            self._round_complete = True
            replayed = 0
            for e in store.pending_ops(after_round=target):
                replayed += self._replay_logged_op_locked(e)
        ms = (time.monotonic() - t0) * 1e3
        _histogram("checkpoint.restore_ms").observe(ms)
        _flight.record("ps.restore", round=target,
                       epoch=stored_epoch + 1,
                       shard=self._shard_index,
                       ops_replayed=replayed, ms=ms)
        print("[ps_rpc] %s restored shard %s at round %d "
              "(epoch fence %d, %d async ops replayed, %.0fms)"
              % (self._own_endpoint, self._shard, target,
                 stored_epoch + 1, replayed, ms),
              file=sys.stderr, flush=True)

    def _apply_restore_frame(self, meta: dict, raw: bytes) -> None:
        """Apply one durable frame — the disk twin of the 'replicate'
        handler: splice row/chunk deltas (or whole vars) into scope
        and adopt the shard-map / migration state the frame carried."""
        off = 0
        for h in meta.get("vars", []):
            n = int(np.dtype(h["dtype"]).itemsize
                    * int(np.prod(h["shape"]) if h["shape"] else 1))
            arr = _array_from(h, raw[off:off + n])
            off += n
            rows = h.get("rows")
            chunk = h.get("chunk")
            if rows is not None:
                tbl = np.array(np.asarray(
                    self._executor._read_var(self._scope, h["name"])),
                    copy=True)
                tbl[np.asarray(rows, dtype=np.int64)] = arr
                self._executor._write_var(self._scope, h["name"], tbl)
            elif chunk is not None:
                tbl = np.array(np.asarray(
                    self._executor._read_var(self._scope, h["name"])),
                    copy=True)
                tbl.reshape(-1)[int(chunk[0]):int(chunk[1])] \
                    = arr.reshape(-1)
                self._executor._write_var(self._scope, h["name"], tbl)
            else:
                self._executor._write_var(self._scope, h["name"], arr)
        ex = meta.get("repl_extra") or {}
        sm = ex.get("shard_map")
        if sm and int(sm.get("version", 0)) >= self._shard_map_version:
            self._shard_map_version = int(sm["version"])
        for n2, ov in (ex.get("map_overrides") or {}).items():
            cur = self._map_overrides.get(n2)
            if cur is None or int(cur.get("version", 0)) \
                    <= int(ov.get("version", 0)):
                self._map_overrides[n2] = dict(ov)
        for n2 in ex.get("dropped", []) or []:
            if n2 not in self._dropped:
                self._dropped.add(n2)
                try:
                    if hasattr(self._scope, "__delitem__") \
                            and n2 in self._scope.local_var_names():
                        del self._scope[n2]
                except (KeyError, TypeError):
                    pass
        pm = ex.get("pending_migration")
        # like the stream: the newest frame is the truth — an intent
        # that stopped riding it was executed or rolled back upstream
        self._pending_migration = dict(pm) if pm else None
        ro = ex.get("range_overrides")
        if ro:
            self._range_overrides = {
                t: [dict(r) for r in rs] for t, rs in ro.items()}
        prm = ex.get("pending_range_migration")
        self._pending_range_migration = dict(prm) if prm else None
        for cid, s in (meta.get("watermark") or {}).items():
            if int(self._repl_watermark.get(cid, 0)) < int(s):
                self._repl_watermark[cid] = int(s)

    def _log_async_op_locked(self, msg: dict, raw: bytes,
                             kind: str = "push_sparse") -> None:
        """Durably log one acked async op (geo/async mode): between
        synthetic-round frames the op exists ONLY in this process, so
        the ack must not outlive the bytes. The entry carries the op's
        dedup token and the round that will fold it; the tail is
        truncated when that frame lands and replayed — exactly-once
        against the frame watermark — on cold restart."""
        entry = {"round": self._applied_round + 1,
                 "kind": kind,
                 "cid": msg.get("cid"),
                 "seq": int(msg.get("seq") or 0),
                 "name": msg.get("name"),
                 "param": msg.get("param", ""),
                 "array": msg["array"],
                 "gh": msg.get("gh"),
                 "raw": base64.b64encode(raw).decode("ascii")}
        if kind == "push_sparse":
            entry["rows"] = msg["rows"]
        try:
            self._durable_store.append_op(entry)
        except OSError as e:
            _counter("ps.durable_errors").inc()
            print("[ps_rpc] async op-log append failed: %s" % e,
                  file=sys.stderr, flush=True)

    def _replay_logged_op_locked(self, e: dict) -> int:
        """Re-apply one logged async op at restore; returns 1 when
        applied, 0 when the restored frame watermark already covers
        its (cid, seq) — the op was folded into the frame (or a newer
        log entry superseded it) and re-applying would double-count."""
        cid = str(e.get("cid") or "")
        seq = int(e.get("seq") or 0)
        if cid and seq \
                and seq <= int(self._repl_watermark.get(cid, 0)):
            return 0
        raw = base64.b64decode(e.get("raw", ""))
        if e.get("kind") == "send_grad":
            # dense async grad: whole-var write + its optimize block
            arr = _array_from(e["array"], raw)
            self._executor._write_var(self._scope, e["name"], arr)
            sub = self._grad_to_block.get(e["name"])
            if sub is not None:
                self._executor.run_block(sub, self._scope)
            self._mark_families_dirty_locked([e["name"]])
        else:
            rh, vh = e["rows"], e["array"]
            nrows_bytes = int(np.dtype(rh["dtype"]).itemsize
                              * int(np.prod(rh["shape"])))
            rows = np.frombuffer(raw[:nrows_bytes],
                                 dtype=rh["dtype"]).reshape(-1)
            vals = _array_from(vh, raw[nrows_bytes:])
            from ..core.tensor import LoDTensor, SelectedRows

            pname = e.get("param", "")
            tbl = (self._executor._read_var(self._scope, pname)
                   if pname else None)
            height = (int(np.asarray(tbl).shape[0]) if tbl is not None
                      else int(rows.max()) + 1)
            sr = SelectedRows(rows=rows.tolist(), height=height)
            sr._value = LoDTensor(vals)
            self._executor._write_var(self._scope, e["name"], sr)
            sub = self._grad_to_block.get(e["name"])
            if sub is not None:
                self._executor.run_block(sub, self._scope)
            if pname:
                self._dirty_rows.setdefault(pname, set()).update(
                    int(r) for r in rows)
        if cid and seq:
            if seq > int(self._repl_watermark.get(cid, 0)):
                self._repl_watermark[cid] = seq
            with self._dedupe_lock:
                if seq > int(self._last_seq.get(cid, 0)):
                    self._last_seq[cid] = seq
        self._async_ops += 1
        return 1

    # -- live shard migration (ISSUE 13) ----------------------------------
    #
    # A key range (a dense var; its @-companions follow) moves from
    # this group (the DONOR) to another (the RECIPIENT) under the
    # two-phase round barrier, with zero lost or double-applied
    # rounds. The whole protocol runs inside ONE round apply, while
    # every trainer is still blocked in its round-N barrier rpc:
    #
    #   1. INSTALL — the donor freezes the var at the just-applied
    #      round and ships it (with its dedup watermark) to the
    #      recipient's active primary, which STAGES it (not servable).
    #   2. SOFT COMMIT — the donor bumps its shard-map version and
    #      records the override {var -> recipient shard}; the var
    #      STAYS in the donor's scope and replication stream until the
    #      recipient durably owns it.
    #   3. REPLICATE — the round ships to the donor's backups WITH the
    #      override (committed=False) + any pending intent, so a
    #      promoted donor backup either never heard of the migration
    #      (-> clean ROLLBACK: the map never bumped anywhere a trainer
    #      can see) or inherits the obligation to finish it.
    #   4. COMMIT — the recipient moves the staged var into its scope,
    #      rebuilds its optimize block via the block_factory, ships it
    #      to ITS backups, and acks; the donor then HARD-commits
    #      (drops the var from its stream, ships `dropped` next
    #      round). Re-sent every round until acked — idempotent.
    #
    # The epoch fence closes every kill window: a donor killed before
    # step 3 rolls back (its promoted backup holds the var, version
    # unbumped, the recipient's staged orphan is superseded by any
    # retry); a donor killed after step 3 completes via its promoted
    # backup re-driving step 4; a recipient killed mid-install fails
    # the install (donor retries next round, bounded, else rollback).
    # Trainers adopt the new map atomically at the round barrier
    # (responses carry it) or lazily via `wrong_shard` redirects whose
    # tokens are NEVER recorded as executed — replays with ORIGINAL
    # tokens stay exactly-once across the version bump because the
    # install carries the donor's folded-seq watermark.

    def _repl_extra_locked(self) -> dict:
        """Shard-map / migration fields riding every replicate rpc."""
        ex = {}
        if self._shard_map_version:
            ex["shard_map"] = self._shard_map_payload_locked()
            ex["map_overrides"] = {
                n: dict(ov) for n, ov in self._map_overrides.items()}
        if self._dropped:
            ex["dropped"] = sorted(self._dropped)
        if self._pending_migration is not None:
            pm = self._pending_migration
            ex["pending_migration"] = {
                "name": pm["name"], "to_shard": pm["to_shard"],
                "to_endpoints": pm["to_endpoints"]}
        if self._range_overrides:
            # full server-side dicts (src window + recipient chain
            # included): a promoted backup must be able to re-drive
            # an uncommitted range commit, or zero the right slice
            ex["range_overrides"] = {
                t: [dict(r) for r in rs]
                for t, rs in self._range_overrides.items()}
        if self._pending_range_migration is not None:
            pm = self._pending_range_migration
            ex["pending_range_migration"] = {
                "name": pm["name"], "lo": pm["lo"], "hi": pm["hi"],
                "src_lo": pm["src_lo"], "src_hi": pm["src_hi"],
                "to_shard": pm["to_shard"],
                "to_endpoints": pm["to_endpoints"]}
        return ex

    def _shard_map_payload_locked(self) -> dict:
        """The client-facing shard map: version + var -> shard ints,
        plus per-table row-range ownership (ISSUE 18) as
        ``{table: [[global_lo, global_hi, shard, local_base], ...]}``."""
        payload = {"version": self._shard_map_version,
                   "overrides": {n: int(ov["shard"])
                                 for n, ov in self._map_overrides.items()}}
        if self._range_overrides:
            payload["ranges"] = {
                t: [[int(r["lo"]), int(r["hi"]), int(r["shard"]),
                     int(r["local_base"])] for r in rs]
                for t, rs in self._range_overrides.items()}
        return payload

    def _mig_client(self, chain: str) -> "PSClient":
        c = self._mig_clients.get(chain)
        if c is None:
            c = PSClient(chain, trainer_id=None, auto_heartbeat=False,
                         timeout=self._repl_connect,
                         rpc_deadline=self._repl_deadline,
                         max_retries=int(os.environ.get(
                             "PADDLE_PS_REPL_RETRIES", "3")))
            self._mig_clients[chain] = c
        return c

    def _step_migration_locked(self) -> None:
        """Donor side, called inside the round apply: execute the
        pending migration (install + soft commit). Transport failures
        retry at the next round's barrier, bounded — then roll back."""
        pm = self._pending_migration
        if pm is None or not self._active_role():
            return
        name = pm["name"]
        val = self._executor._read_var(self._scope, name)
        if val is None or name in self._dropped:
            self._pending_migration = None
            return
        ver = self._shard_map_version + 1
        _flight.record("ps.migration_begin", var=name,
                       to_shard=pm["to_shard"], version=ver,
                       round=self._applied_round)
        try:
            self._install_migration_locked(name, int(pm["to_shard"]),
                                           pm["to_endpoints"], ver)
        except (RuntimeError, OSError) as e:
            pm["attempts"] = int(pm.get("attempts", 0)) + 1
            _counter("ps.migrations", outcome="install_retry").inc()
            if pm["attempts"] >= 3:
                self._pending_migration = None
                _counter("ps.migrations", outcome="rollback").inc()
                _flight.record("ps.migration_rollback", var=name,
                               why="install failed: %s" % e)
                print("[ps_rpc] migration of %r to shard %s ROLLED "
                      "BACK after %d install failures (%s)"
                      % (name, pm["to_shard"], pm["attempts"], e),
                      file=sys.stderr, flush=True)
            return
        if os.environ.get("PADDLE_PS_CHAOS_DIE_AFTER_INSTALL") \
                == self._own_endpoint:
            # chaos-drill hook: the donor primary dies in the WORST
            # spot — range installed on the recipient, nothing
            # committed or replicated. The drill proves this rolls
            # back (or completes via a retriggered migration) with
            # params bit-for-bit.
            print("[ps_rpc] CHAOS: donor %s dying after migrate "
                  "install" % self._own_endpoint, file=sys.stderr,
                  flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
        # soft commit: route the var away; keep its state in our
        # stream until the recipient durably owns it (hard commit)
        self._pending_migration = None
        self._shard_map_version = ver
        self._map_overrides[name] = {
            "shard": int(pm["to_shard"]), "version": ver,
            "committed": False, "to_endpoints": pm["to_endpoints"]}
        _counter("ps.migrations", outcome="installed").inc()
        _flight.record("ps.migration_installed", var=name,
                       version=ver, round=self._applied_round)

    def _install_migration_locked(self, name: str, to_shard: int,
                                  to_endpoints: str, ver: int) -> None:
        """Ship ``name``'s WHOLE FAMILY (base var + every @-companion
        in scope — momentum/adam state moves with its param, grads are
        transient but harmless) to the recipient's active primary for
        staging. Raises on transport/app failure — the caller owns the
        retry/rollback policy."""
        items = []
        for vn in self._family_index().get(name, [name]):
            v = self._executor._read_var(self._scope, vn)
            if v is None or not hasattr(v, "shape"):
                continue
            items.append((vn, np.ascontiguousarray(np.asarray(v)),
                          None))
        if not items:
            raise RuntimeError("no tensor state for %r" % name)
        headers, raw = self._blobs_for(items)
        # kind=var vs kind=range: a regression back to whole-var
        # moves of a sparse table shows up as var bytes where range
        # bytes should be (bench_diff watches this family)
        _counter("ps.migration_bytes", kind="var").inc(len(raw))
        self._mig_client(to_endpoints)._call({
            "kind": "migrate_install", "name": name,
            "mig_version": ver, "mig_round": self._applied_round,
            "to_shard": int(to_shard),
            "watermark": dict(self._applied_watermark),
            "has_block": (name + "@GRAD") in self._grad_to_block,
            "vars": headers}, raw)

    def _step_range_migration_locked(self) -> None:
        """Donor side of a ROW-RANGE migration (ISSUE 18), called
        inside the round apply: ship the dirty-row-tracked slice
        ``[src_lo, src_hi)`` of one sparse table to the recipient and
        soft-commit the per-range ownership split. Rides the PR-13
        protocol verbatim: install (staged, not servable) -> soft
        commit (map version bump; the rows stay in the donor's stream)
        -> the caller's replication ships the override to the donor's
        backups -> _commit_migrations_locked drives the replicated
        commit home. Transport failures retry at the next round's
        barrier, bounded — then roll back with no override anywhere a
        trainer can see."""
        pm = self._pending_range_migration
        if pm is None or not self._active_role():
            return
        name = pm["name"]
        tbl = self._executor._read_var(self._scope, name)
        if tbl is None:
            self._pending_range_migration = None
            return
        ver = self._shard_map_version + 1
        _flight.record("ps.range_migration_begin", var=name,
                       lo=int(pm["lo"]), hi=int(pm["hi"]),
                       to_shard=pm["to_shard"], version=ver,
                       round=self._applied_round)
        try:
            local_base = self._install_range_locked(pm, ver)
        except (RuntimeError, OSError) as e:
            pm["attempts"] = int(pm.get("attempts", 0)) + 1
            _counter("ps.migrations", outcome="install_retry").inc()
            if pm["attempts"] >= 3:
                self._pending_range_migration = None
                _counter("ps.migrations", outcome="rollback").inc()
                _flight.record("ps.range_migration_rollback", var=name,
                               why="install failed: %s" % e)
                print("[ps_rpc] range migration of %r[%s,%s) to shard "
                      "%s ROLLED BACK after %d install failures (%s)"
                      % (name, pm["lo"], pm["hi"], pm["to_shard"],
                         pm["attempts"], e),
                      file=sys.stderr, flush=True)
            return
        if os.environ.get("PADDLE_PS_CHAOS_DIE_AFTER_INSTALL") \
                == self._own_endpoint:
            # chaos-drill hook (shared with the whole-var path): the
            # donor primary dies in the WORST spot — rows staged on
            # the recipient, nothing committed or replicated
            print("[ps_rpc] CHAOS: donor %s dying after range "
                  "install" % self._own_endpoint, file=sys.stderr,
                  flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
        # soft commit: route the window away; keep its rows in our
        # stream (unzeroed) until the recipient durably owns them
        self._pending_range_migration = None
        self._shard_map_version = ver
        rs = self._range_overrides.setdefault(name, [])
        rs[:] = [r for r in rs
                 if not (int(r["lo"]) == int(pm["lo"])
                         and int(r["hi"]) == int(pm["hi"]))]
        rs.append({"lo": int(pm["lo"]), "hi": int(pm["hi"]),
                   "shard": int(pm["to_shard"]),
                   "local_base": int(local_base),
                   "src_lo": int(pm["src_lo"]),
                   "src_hi": int(pm["src_hi"]),
                   "version": ver, "committed": False,
                   "to_endpoints": pm["to_endpoints"]})
        _counter("ps.migrations", outcome="installed").inc()
        _flight.record("ps.range_migration_installed", var=name,
                       lo=int(pm["lo"]), hi=int(pm["hi"]),
                       version=ver, round=self._applied_round)

    def _install_range_locked(self, pm: dict, ver: int) -> int:
        """Ship rows ``[src_lo, src_hi)`` of the table — and the same
        window of every @-companion sharing its row axis — to the
        recipient's active primary for staging. Returns the
        recipient-LOCAL base id the staged rows will land at (its
        table height at stage time). Raises on transport/app failure —
        the caller owns the retry/rollback policy."""
        name = pm["name"]
        s, e = int(pm["src_lo"]), int(pm["src_hi"])
        items = []
        found_base = False
        for vn in self._family_index().get(name, [name]):
            v = self._executor._read_var(self._scope, vn)
            if v is None or not hasattr(v, "shape"):
                continue
            a = np.asarray(v)
            if a.ndim < 1 or int(a.shape[0]) < e:
                continue  # companions off the row axis stay put
            if vn == name:
                found_base = True
            items.append((vn, np.ascontiguousarray(a[s:e]), None))
        if not found_base:
            raise RuntimeError("no sliceable rows [%d,%d) of %r"
                               % (s, e, name))
        headers, raw = self._blobs_for(items)
        _counter("ps.migration_bytes", kind="range").inc(len(raw))
        resp, _ = self._mig_client(pm["to_endpoints"])._call({
            "kind": "migrate_range_install", "name": name,
            "mig_version": ver, "mig_round": self._applied_round,
            "lo": int(pm["lo"]), "hi": int(pm["hi"]),
            "to_shard": int(pm["to_shard"]),
            "watermark": dict(self._applied_watermark),
            "has_block": (name + "@GRAD") in self._grad_to_block,
            "vars": headers}, raw)
        return int(resp.get("local_base", 0))

    def _zero_range_locked(self, name: str, s: int, e: int) -> None:
        """Hard commit of a row-range move: ZERO the moved donor-local
        slice (a deterministic tombstone — shrinking the array would
        re-base every other local id this shard's clients hold) on the
        base table and every companion sharing its row axis, and mark
        them dense-dirty so the tombstone replicates."""
        for vn in self._family_index().get(name, [name]):
            v = self._executor._read_var(self._scope, vn)
            if v is None or not hasattr(v, "shape"):
                continue
            a = np.asarray(v)
            if a.ndim < 1 or int(a.shape[0]) < e:
                continue
            a = np.array(a, copy=True)
            a[s:e] = 0
            self._executor._write_var(self._scope, vn, a)
            self._dirty_dense.add(vn)
            self._dirty_rows.pop(vn, None)

    def _commit_migrations_locked(self) -> None:
        """Donor side (original or promoted): drive every uncommitted
        outbound migration to its commit — re-sent each round until
        the recipient acks (idempotent), then hard-commit locally. A
        recipient that LOST its stage (its primary died between
        install and commit; staging is memory-only) is re-installed
        first — the donor still holds the var, which is exactly why
        the hard commit waits for the ack."""
        if not self._active_role():
            return
        for name, rs in list(self._range_overrides.items()):
            for ov in rs:
                if ov.get("committed") or "to_endpoints" not in ov:
                    continue
                try:
                    self._mig_client(ov["to_endpoints"])._call({
                        "kind": "migrate_range_commit", "name": name,
                        "mig_version": int(ov["version"]),
                        "lo": int(ov["lo"]), "hi": int(ov["hi"]),
                        "to_shard": int(ov["shard"])})
                except (RuntimeError, OSError) as e:
                    _counter("ps.migrations",
                             outcome="commit_retry").inc()
                    print("[ps_rpc] migrate_range_commit of %r[%s,%s) "
                          "failed (%s) — re-installing and retrying "
                          "next round" % (name, ov["lo"], ov["hi"], e),
                          file=sys.stderr, flush=True)
                    try:
                        # stage gone (recipient primary died) or its
                        # local base drifted: re-stage with a fresh
                        # base — the rows are still here, unzeroed
                        ov["local_base"] = self._install_range_locked(
                            {"name": name, "lo": ov["lo"],
                             "hi": ov["hi"], "src_lo": ov["src_lo"],
                             "src_hi": ov["src_hi"],
                             "to_shard": ov["shard"],
                             "to_endpoints": ov["to_endpoints"]},
                            int(ov["version"]))
                    except (RuntimeError, OSError):
                        pass  # next round retries the whole sequence
                    continue
                ov["committed"] = True
                self._zero_range_locked(name, int(ov["src_lo"]),
                                        int(ov["src_hi"]))
                _counter("ps.migrations", outcome="committed").inc()
                _flight.record("ps.range_migration_committed",
                               var=name, lo=int(ov["lo"]),
                               hi=int(ov["hi"]),
                               version=int(ov["version"]),
                               round=self._applied_round)
        for name, ov in list(self._map_overrides.items()):
            if ov.get("committed") or "to_endpoints" not in ov:
                continue
            try:
                self._mig_client(ov["to_endpoints"])._call({
                    "kind": "migrate_commit", "name": name,
                    "mig_version": int(ov["version"]),
                    "to_shard": int(ov["shard"])})
            except (RuntimeError, OSError) as e:
                _counter("ps.migrations", outcome="commit_retry").inc()
                print("[ps_rpc] migrate_commit of %r failed (%s) — "
                      "re-installing and retrying next round"
                      % (name, e), file=sys.stderr, flush=True)
                try:
                    # the stage may be GONE (the recipient primary
                    # that held it died; a promoted backup has no
                    # memory of it): put it back — this primary still
                    # holds the state, which is exactly why the hard
                    # commit waits for the ack
                    self._install_migration_locked(
                        name, int(ov["shard"]), ov["to_endpoints"],
                        int(ov["version"]))
                except (RuntimeError, OSError):
                    pass  # next round retries the whole sequence
                continue
            ov["committed"] = True
            self._drop_var_locked(name)
            _counter("ps.migrations", outcome="committed").inc()
            _flight.record("ps.migration_committed", var=name,
                           version=int(ov["version"]),
                           round=self._applied_round)

    def _drop_var_locked(self, name: str) -> None:
        """Hard commit: mask the migrated-out var (and its whole
        family — grads/optimizer state moved with it conceptually)
        from this group's stream; delete where the scope allows."""
        for vn in list(self._scope.local_var_names()):
            if vn.split("@", 1)[0] != name:
                continue
            self._dropped.add(vn)
            self._shipped_digests.pop(vn, None)
            self._dirty_rows.pop(vn, None)
            self._dirty_dense.discard(vn)
            try:
                if hasattr(self._scope, "__delitem__"):
                    del self._scope[vn]
            except (KeyError, TypeError):
                pass

    def _commit_staged_locked(self, name: str) -> None:
        """Recipient side: a staged var becomes servable — into the
        scope, optimize block rebuilt, watermark merged (replays of
        rpcs already folded into the shipped state answer `replayed`
        here too — exactly-once across the shard-map bump), map
        bumped, and the var pushed to THIS group's backups before the
        donor ever gets the ack."""
        st = self._staged_in.pop(name)
        for vn, arr in st["arrays"].items():
            self._executor._write_var(self._scope, vn, arr)
            self._dropped.discard(vn)
            self._shipped_digests.pop(vn, None)
        gname = name + "@GRAD"
        if gname not in self._grad_to_block \
                and self._block_factory is not None:
            blk = self._block_factory(gname)
            if blk is not None:
                self._grad_to_block[gname] = blk
        for cid, s in (st.get("watermark") or {}).items():
            if int(self._repl_watermark.get(cid, 0)) < int(s):
                self._repl_watermark[cid] = int(s)
        ver = int(st["version"])
        self._shard_map_version = max(self._shard_map_version, ver)
        self._map_overrides[name] = {"shard": int(st["to_shard"]),
                                     "version": ver, "committed": True}
        self._replicate_vars_locked(sorted(st["arrays"]))
        _counter("ps.migrations", outcome="adopted").inc()
        _flight.record("ps.migration_commit", var=name, version=ver,
                       round=self._applied_round)

    def _commit_staged_range_locked(self, name: str) -> None:
        """Recipient side of a row-range move: the staged rows become
        servable — APPENDED to the resident table (at the local base
        promised in the install ack) and to every companion that
        shipped with them, optimize block rebuilt, watermark merged,
        map bumped with the committed range ownership, and the grown
        family pushed to THIS group's backups before the donor ever
        gets the ack."""
        st = self._staged_ranges.pop(name)
        for vn, arr in st["arrays"].items():
            cur = self._executor._read_var(self._scope, vn)
            if cur is not None and hasattr(cur, "shape") \
                    and np.asarray(cur).ndim == arr.ndim:
                grown = np.concatenate([np.asarray(cur), arr], axis=0)
            else:
                grown = arr
            self._executor._write_var(self._scope, vn,
                                      np.ascontiguousarray(grown))
            self._dropped.discard(vn)
            self._shipped_digests.pop(vn, None)
            self._dirty_dense.add(vn)
        gname = name + "@GRAD"
        if gname not in self._grad_to_block \
                and self._block_factory is not None:
            blk = self._block_factory(gname)
            if blk is not None:
                self._grad_to_block[gname] = blk
        for cid, s in (st.get("watermark") or {}).items():
            if int(self._repl_watermark.get(cid, 0)) < int(s):
                self._repl_watermark[cid] = int(s)
        ver = int(st["version"])
        self._shard_map_version = max(self._shard_map_version, ver)
        rs = self._range_overrides.setdefault(name, [])
        rs[:] = [r for r in rs
                 if not (int(r["lo"]) == int(st["lo"])
                         and int(r["hi"]) == int(st["hi"]))]
        rs.append({"lo": int(st["lo"]), "hi": int(st["hi"]),
                   "shard": int(st["to_shard"]),
                   "local_base": int(st["local_base"]),
                   "version": ver, "committed": True})
        _gauge("ps.table_rows", shard=self._shard, table=name).set(
            int(st["local_base"]) + int(st["hi"]) - int(st["lo"]))
        self._replicate_vars_locked(sorted(st["arrays"]))
        _counter("ps.migrations", outcome="adopted").inc()
        _flight.record("ps.range_migration_adopted", var=name,
                       lo=int(st["lo"]), hi=int(st["hi"]),
                       version=ver, round=self._applied_round)

    def _range_redirect_locked(self, table: str, rows, mv: int):
        """Sparse-dataplane routing for row-range migrations: commit a
        staged range whose appended region a map-proving client is
        addressing (backstop for a donor that died between its bump
        and the commit), then answer ``wrong_shard`` when ANY
        requested local row falls in a window migrated away — all or
        nothing, so the client re-splits the whole request against the
        adopted map and every row executes exactly once. Returns the
        redirect response dict, or None to proceed."""
        st = self._staged_ranges.get(table)
        if st is not None and mv >= int(st["version"]):
            tbl = self._executor._read_var(self._scope, table)
            height = (int(np.asarray(tbl).shape[0])
                      if tbl is not None and hasattr(tbl, "shape")
                      else 0)
            if height == int(st["local_base"]) \
                    and any(int(r) >= height for r in rows):
                # the client PROVED the donor's map bump (its adopted
                # version rides the rpc) and is addressing the staged
                # rows' landing zone: commit
                self._commit_staged_range_locked(table)
        for r in self._range_overrides.get(table, ()):
            if int(r["shard"]) == self._shard_index:
                continue
            s, e = int(r.get("src_lo", -1)), int(r.get("src_hi", -1))
            if s < 0:
                continue
            if any(s <= int(x) < e for x in rows):
                return {"ok": False, "wrong_shard": True,
                        "name": table,
                        "shard_map": self._shard_map_payload_locked(),
                        "error": "rows [%d,%d) of %r migrated to "
                        "shard %s (map v%d)"
                        % (s, e, table, r["shard"],
                           self._shard_map_version)}
        return None

    def _replicate_vars_locked(self, names) -> None:
        """Push the named vars (plus the shard-map state) to this
        group's backups right now — the recipient's primary must not
        be the only holder of a freshly adopted family for even a
        round. Any failure schedules a full re-anchor at the next
        round instead of risking divergence."""
        items = []
        for name in names:
            val = self._executor._read_var(self._scope, name)
            if val is None or not hasattr(val, "shape"):
                continue
            items.append((name,
                          np.ascontiguousarray(np.asarray(val)),
                          None))
        if not items:
            return
        headers, raw = self._blobs_for(items)
        extra = self._repl_extra_locked()
        for ep in self._repl_targets():
            try:
                resp = self._repl_client(ep).replicate(
                    self._applied_round, headers, raw,
                    dict(self._applied_watermark), mode="delta",
                    base_round=self._applied_round,
                    epoch=self._epoch, extra=extra)
                if resp.get("repl_gap") or resp.get("fenced"):
                    self._shipped_digests = {}
            except (RuntimeError, OSError):
                self._shipped_digests = {}  # anchor next round

    def _async_tick_locked(self) -> dict:
        """Async-mode (RunAsyncLoop) durability bookkeeping, locked by
        caller: count the applied op, ship a synthetic replication
        round every ``PADDLE_PS_ASYNC_REPL_EVERY`` ops, and tell the
        client which round will carry this op — ``pending_round`` tags
        its replay-log entry, ``durable_round`` prunes every entry
        whose round is now replicated. That round-gating makes a
        failover mid-async-push exactly-once like the sync path
        (ISSUE 8 satellite; the gap carried since ISSUE 4)."""
        # a lone server normally has nobody to make rounds durable
        # WITH — but an armed durable dir IS a durability target
        # (ISSUE 19): synthetic rounds tick so the disk frames (and
        # the op-log truncation riding them) keep advancing
        if self._sync or not self._active_role() \
                or (len(self._endpoints) <= 1
                    and self._durable_store is None):
            return {}
        self._async_ops += 1
        pending = self._applied_round + 1
        if (self._async_repl_every > 0
                and self._async_ops % self._async_repl_every == 0):
            self._applied_round += 1
            self._applied_watermark = self._watermark_locked()
            self._replicate_locked()
            pending = self._applied_round
        # durable = the last round at least one backup ACKED (not the
        # last round we merely tried to ship): a ship that reached
        # nobody must not let the client prune ops that exist only on
        # this primary. Replication is state-based, so a LATER
        # successful ship retroactively makes every earlier round
        # durable — the monotonic _durable_round encodes exactly that.
        return {"durable_round": self._durable_round,
                "pending_round": pending}

    def _active_role(self) -> bool:
        return self._active or self._promoted

    def _promote_locked(self, kind: str) -> None:
        """A genuinely failed-over client reached this backup: become
        the primary (deterministic — clients walk the endpoint list in
        order, so the lowest-index live endpoint wins) and start
        streaming to the remaining backups."""
        self._promoted = True
        self._repl_dead.discard(self._own_endpoint)
        # the state this server holds = the replicated rounds; its
        # folded-seq watermark is exactly the inherited one, and its
        # first ship as a primary must be a full ANCHOR (it never
        # shipped anything, and the other backups' bases are unknown)
        self._applied_watermark = dict(self._repl_watermark)
        self._shipped_digests = {}
        # nothing is replicated BEYOND this server yet: async clients
        # must hold their replay logs until its first acked ship
        self._durable_round = 0
        _counter("ps.promotions").inc()
        _flight.record("ps.promotion", round=self._applied_round,
                       index=self._index, endpoint=self._own_endpoint,
                       rpc=kind, epoch=self._epoch)
        print("[ps_rpc] endpoint %s (index %d) promoted to primary at "
              "round %d epoch %d (trigger: %s)"
              % (self._own_endpoint, self._index, self._applied_round,
                 self._epoch, kind), file=sys.stderr, flush=True)

    # -- lease + quorum (ISSUE 8: at most one writable primary) -----------

    def _lease_mode(self) -> bool:
        return self._lease_s > 0 and len(self._endpoints) > 1

    def _lease_expired_locked(self) -> bool:
        return time.monotonic() > self._lease_deadline

    def _refresh_lease_locked(self, epoch: int) -> None:
        """A renewal / replication / snapshot from an equal-or-newer
        primary: its lease holds for another period. The deadline is
        read through the ``clock_jitter`` chaos hook — a drilled
        process's lease view wanders like a real skewed clock would,
        and the quorum math must still never split the brain."""
        self._seen_epoch = max(self._seen_epoch, int(epoch))
        self._lease_deadline = (time.monotonic() + self._lease_s
                                + _fault.clock_skew())
        self._lease_expired_counted = False

    def _demote_locked(self, new_epoch: int, why: str) -> None:
        """Step down: a higher-epoch primary exists (fencing) or this
        primary lost its renewal majority long enough that one could.
        Better a loud redirect than a second writable primary."""
        if not self._active_role():
            return
        self._active = False
        self._promoted = False
        self._seen_epoch = max(self._seen_epoch, int(new_epoch))
        self._lease_deadline = time.monotonic() + self._lease_s
        self._cond.notify_all()
        _flight.record("ps.demotion", endpoint=self._own_endpoint,
                       epoch=self._epoch, seen_epoch=self._seen_epoch,
                       why=why)
        print("[ps_rpc] endpoint %s DEMOTED at round %d (epoch %d): %s"
              % (self._own_endpoint, self._applied_round, self._epoch,
                 why), file=sys.stderr, flush=True)

    def _lease_loop(self) -> None:
        """One background loop per multi-endpoint server: the active
        primary renews its lease with every group peer; a caught-up
        backup whose lease view expired stands for election. Control-
        plane failures are signals, never fatal. The tick period is
        perturbed by the ``clock_jitter`` chaos hook — a skewed
        process renews early/late like a real drifting clock."""
        base_period = max(self._lease_s / 3.0, 0.05)
        while not self._shutdown.wait(
                max(0.02, base_period + _fault.clock_skew() / 3.0)):
            try:
                if self._active_role():
                    self._renew_lease()
                elif self._caught_up:
                    self._maybe_elect("lease-expiry")
            except Exception as e:  # noqa: BLE001 — the lease loop
                # must survive anything the drills throw at the wire
                print("[ps_rpc] lease loop error on %s: %s: %s"
                      % (self._own_endpoint, type(e).__name__, e),
                      file=sys.stderr, flush=True)

    def _renew_lease(self) -> None:
        """Primary side: one renewal sweep over the group. A refused
        peer is dead (tombstone — it cannot grant a rival's quorum
        either); a fenced reply means a newer epoch rules and this
        server demotes; in groups of >= 3, a full lease without a
        renewal MAJORITY demotes too — behind that partition a rival
        quorum may exist. With 2 endpoints no rival quorum can form
        without this server's own vote, so it serves on."""
        with self._lock:
            epoch, rnd = self._epoch, self._applied_round
        # witnesses receive renewals too (their per-shard lease views
        # must stay fresh, or they would rubber-stamp elections under
        # a live primary) but the renewal MAJORITY is group-only,
        # mirroring the election quorum
        n = len(self._endpoints)
        grants = 1  # self
        for ep in list(self._endpoints) + list(self._witnesses):
            if ep == self._own_endpoint or self._shutdown.is_set():
                continue
            witness = ep in self._witnesses
            try:
                resp = _bare_rpc(
                    ep, {"kind": "lease_renew", "epoch": epoch,
                         "round": rnd, "frm": self._own_endpoint,
                         "shard": self._shard,
                         "lease_ms": self._lease_s * 1e3},
                    timeout=max(self._lease_s / 3.0, 0.2))
            except ConnectionRefusedError:
                if not witness:
                    grants += 1  # dead listener: tombstone
                continue
            except (OSError, ValueError):
                continue  # partition/timeout: no evidence either way
            if resp.get("fenced"):
                with self._lock:
                    self._demote_locked(int(resp.get("epoch", 0)),
                                        "fenced by %s during lease "
                                        "renewal" % ep)
                return
            if resp.get("ok"):
                if not witness:
                    grants += 1
                    _counter("ps.lease_renewals").inc()
                # witness acks count their own ps.witness_renewals
        now = time.monotonic()
        if grants * 2 > n:
            self._last_majority_ack = now
        elif n >= 3 and now - self._last_majority_ack > self._lease_s:
            with self._lock:
                self._demote_locked(
                    self._epoch, "no renewal majority for %.1fs "
                    "(%d/%d reachable)" % (now - self._last_majority_ack,
                                           grants, n))

    def _maybe_elect(self, trigger: str) -> bool:
        """Quorum election (backup side). Returns True when this
        server is (or just became) the active primary. Prerequisites:
        caught up, lease view expired (+ an index-staggered grace so
        the lowest surviving index wins clean races). The epoch bump
        needs strictly more than half the endpoint GROUP: self +
        granted votes + refused-connect tombstones. Any voter holding
        a newer round than this candidate vetoes — better no primary
        than a stale one."""
        if not self._lease_mode():
            return self._active_role()
        with self._lock:
            if self._active_role():
                return True
            if not self._caught_up:
                return False
            stagger = max(0, self._index - 1) * self._lease_s / 4.0
            if time.monotonic() <= self._lease_deadline + stagger:
                return False
            if not self._lease_expired_counted:
                self._lease_expired_counted = True
                _counter("ps.lease_expiries", shard=self._shard).inc()
                _flight.record("ps.lease_expired",
                               endpoint=self._own_endpoint,
                               shard=self._shard,
                               round=self._applied_round)
        with self._election_lock:
            with self._lock:
                if self._active_role():
                    return True
                if time.monotonic() <= self._lease_deadline:
                    return False  # a renewal landed while we queued
                target = max(self._epoch, self._seen_epoch,
                             self._promised_epoch) + 1
                my_round = self._applied_round
            grants, tombstones, denials = 1, 0, 0
            w_grants, w_tombstones = 0, 0
            stale = vetoed = False
            for ep in list(self._endpoints) + list(self._witnesses):
                if ep == self._own_endpoint or self._shutdown.is_set():
                    continue
                witness = ep in self._witnesses
                try:
                    resp = _bare_rpc(
                        ep, {"kind": "vote", "epoch": target,
                             "cand_round": my_round,
                             "shard": self._shard,
                             "lease_ms": self._lease_s * 1e3,
                             "candidate": self._own_endpoint},
                        timeout=max(self._lease_s / 3.0, 0.3))
                except ConnectionRefusedError:
                    if witness:
                        w_tombstones += 1  # dead witness: its veto
                        # power dies with it (positive evidence)
                    else:
                        tombstones += 1
                    continue
                except (OSError, ValueError):
                    continue  # unreachable: silence is not assent
                if int(resp.get("round", -1)) > my_round:
                    stale = True
                if resp.get("granted"):
                    if witness:
                        w_grants += 1
                    else:
                        grants += 1
                else:
                    denials += 1
                    if resp.get("active"):
                        # a REACHABLE, still-active primary denied:
                        # it is demonstrably alive — this candidate's
                        # lease view is merely late (a delayed
                        # renewal sweep, a jittered clock). Deposing
                        # it would be pure churn: VETO. Promotion
                        # needs the primary unreachable (timeout),
                        # dead (refused), or demoted — never outvoted
                        # while it answers.
                        vetoed = True
            # quorum is GROUP-ONLY: witnesses gate below but never
            # provide margin (a busy primary whose vote handler is
            # starved for a moment must not be out-votable by
            # candidate+witness — the PR-8 invariant that a 2-group
            # backup can never promote without the primary's death
            # evidence stays intact)
            n = len(self._endpoints)
            quorum = (grants + tombstones) * 2 > n
            # witnesses configured => the election ALSO needs positive
            # evidence: at least one live witness granting (its lease
            # view of this shard expired — the primary really stopped
            # renewing), unless every witness is itself a tombstone.
            # Forged connection-REFUSALs alone can no longer elect a
            # backup under a live primary (the ISSUE-13 corner).
            w_ok = (not self._witnesses or w_grants >= 1
                    or w_tombstones >= len(self._witnesses))
            won = quorum and not stale and w_ok and not vetoed
            _flight.record("ps.election", endpoint=self._own_endpoint,
                           epoch=target, grants=grants,
                           tombstones=tombstones, denials=denials,
                           witness_grants=w_grants,
                           witness_tombstones=w_tombstones,
                           stale=stale, vetoed=vetoed, won=won,
                           trigger=trigger)
            if not won:
                if vetoed:
                    with self._lock:
                        # the primary lives: stop standing until its
                        # next renewal actually fails to arrive
                        self._refresh_lease_locked(self._seen_epoch)
                print("[ps_rpc] endpoint %s lost election for epoch %d"
                      " (%d grants + %d tombstones of %d, denials=%d, "
                      "witness grants=%d/%d, stale=%s, vetoed=%s; "
                      "trigger=%s) — staying a backup"
                      % (self._own_endpoint, target, grants, tombstones,
                         n, denials, w_grants, len(self._witnesses),
                         stale, vetoed, trigger),
                      file=sys.stderr, flush=True)
                return False
            with self._lock:
                if not self._active_role():
                    self._epoch = target
                    self._seen_epoch = max(self._seen_epoch, target)
                    self._promote_locked(trigger)
                return True

    # -- rejoin catch-up (relaunched server -> backup) --------------------

    def _catchup_loop(self) -> None:
        """Probe the endpoint list for the active server, pull a
        manifest-verified snapshot (join_backup also splices this
        server back into the replication stream, atomically with the
        snapshot), load it, and open for replication traffic."""
        import shutil
        import tempfile

        t0 = time.monotonic()
        while not self._shutdown.is_set():
            for ep in self._endpoints:
                if ep == self._own_endpoint or self._shutdown.is_set():
                    continue
                probe = None
                d = None
                try:
                    probe = PSClient(ep, trainer_id=None,
                                     auto_heartbeat=False, timeout=2.0,
                                     rpc_deadline=30.0, max_retries=0)
                    st, _ = probe._call({"kind": "repl_status"})
                    if not st.get("active"):
                        continue
                    d = tempfile.mkdtemp(prefix="ps_catchup_")
                    resp, _ = probe._call({
                        "kind": "join_backup", "dir": d,
                        "endpoint": self._own_endpoint})
                    from ..checkpoint import load_scope_snapshot

                    with self._lock:
                        # replication may already have raced past the
                        # snapshot (we were spliced into the stream the
                        # instant it was taken) — newer full blobs win
                        if self._applied_round <= int(resp["round"]):
                            load_scope_snapshot(self._executor,
                                                self._scope, d)
                            self._applied_round = int(resp["round"])
                        for cid, s in (resp.get("watermark")
                                       or {}).items():
                            if int(self._repl_watermark.get(cid, 0)) \
                                    < int(s):
                                self._repl_watermark[cid] = int(s)
                        # shard-map / migration state: a rejoiner must
                        # not re-serve (or re-anchor) vars the group
                        # migrated away while it was down
                        sm = resp.get("shard_map")
                        if sm:
                            self._shard_map_version = max(
                                self._shard_map_version,
                                int(sm.get("version", 0)))
                        for n2, ov in (resp.get("map_overrides")
                                       or {}).items():
                            self._map_overrides[n2] = dict(ov)
                        for n2 in resp.get("dropped", []) or []:
                            self._dropped.add(n2)
                            try:
                                if hasattr(self._scope, "__delitem__") \
                                        and n2 in \
                                        self._scope.local_var_names():
                                    del self._scope[n2]
                            except (KeyError, TypeError):
                                pass
                        # adopt the active primary's epoch + a fresh
                        # lease: a just-rejoined backup must not stand
                        # for election before the primary's first
                        # renewal reaches it
                        self._refresh_lease_locked(
                            int(resp.get("epoch", 0)))
                        self._pending.clear()
                        self._send_barriers = 0
                        self._fetch_barriers = 0
                        self._round_complete = True
                        self._fetches_pending = False
                        self._caught_up = True
                    _histogram("ps.catchup_ms").observe(
                        (time.monotonic() - t0) * 1e3)
                    _flight.record("ps.rejoin",
                                   round=self._applied_round, via=ep)
                    print("[ps_rpc] endpoint %s rejoined as backup at "
                          "round %d (caught up from %s in %.0f ms)"
                          % (self._own_endpoint, self._applied_round,
                             ep, (time.monotonic() - t0) * 1e3),
                          file=sys.stderr, flush=True)
                    return
                except (RuntimeError, OSError, KeyError, ValueError) \
                        as e:
                    print("[ps_rpc] rejoin catch-up attempt via %s "
                          "failed (will retry): %s" % (ep, e),
                          file=sys.stderr, flush=True)
                    continue
                finally:
                    if probe is not None:
                        probe.close()
                    if d is not None:
                        # failed attempts must not leave a snapshot
                        # dir per 0.5s retry during a long outage
                        shutil.rmtree(d, ignore_errors=True)
            self._shutdown.wait(0.5)

    def _wait_for(self, predicate, what: str):
        """Bounded condition wait (locked by caller); surfaces stale
        trainers instead of hanging forever when a rank died."""
        deadline = time.time() + _ROUND_TIMEOUT
        while not predicate():
            if self._shutdown.is_set():
                raise RuntimeError("pserver shut down mid-round")
            if time.time() > deadline:
                raise RuntimeError(
                    "PS round stalled waiting for %s (fanin=%d); stale "
                    "trainers by heartbeat: %s"
                    % (what, self._fanin, self.monitor.stale_trainers()))
            self._cond.wait(timeout=1.0)

    # -- eviction (heart_beat_monitor.h semantics) ------------------------

    def _evict_loop(self):
        period = max(self._evict_after / 4.0, 0.05)
        while not self._shutdown.wait(period):
            stale = self.monitor.stale_trainers()
            if not stale:
                continue
            with self._lock:
                for t in stale:
                    if t not in self._evicted:
                        self._evict_locked(t)

    def _evict_locked(self, trainer_id: int) -> None:
        """Remove a dead trainer from the round math (locked by
        caller): shrink the effective fanin and re-check both barriers
        — the survivors may already have everyone-still-alive's
        contributions in, in which case the round completes NOW."""
        self._evicted.add(trainer_id)
        self.monitor.forget(trainer_id)
        _counter("ps.evictions").inc()
        _flight.record("ps.eviction", trainer=trainer_id,
                       effective_fanin=self._effective_fanin())
        print("[ps_rpc] evicting trainer %d (silent > %.1fs); "
              "effective fanin now %d"
              % (trainer_id, self._evict_after, self._effective_fanin()),
              file=sys.stderr, flush=True)
        eff = self._effective_fanin()
        if not self._round_complete and self._send_barriers >= eff:
            self._apply_round()
        if self._fetches_pending and self._fetch_barriers >= eff:
            self._fetch_barriers = 0
            self._fetches_pending = False
        self._cond.notify_all()

    def _readmit(self, trainer_id: int) -> None:
        with self._lock:
            if trainer_id in self._evicted:
                self._evicted.discard(trainer_id)
                _counter("ps.readmissions").inc()
                _flight.record("ps.readmission", trainer=trainer_id)
                print("[ps_rpc] re-admitting trainer %d; effective "
                      "fanin now %d"
                      % (trainer_id, self._effective_fanin()),
                      file=sys.stderr, flush=True)

    def _handle(self, msg: dict, raw: bytes):
        """Returns (response_dict, response_raw)."""
        kind = msg["kind"]
        if kind in self._DATAPLANE and not self._active_role():
            # backup role. Lease mode (the default): promotion is
            # gated on lease expiry + a quorum election — a client
            # merely REACHING a backup proves nothing (it may be the
            # wrong side of a partition). Legacy mode
            # (PADDLE_PS_LEASE_MS=0): only a client that genuinely
            # failed over (fo >= 1 — it watched the previous endpoint
            # die) may promote. In both: an un-caught-up rejoiner
            # redirects unconditionally, and a backup that fell off
            # the stream is never promoted by a client that OBSERVED a
            # newer round than it holds — better no primary (loud
            # failure) than a stale one (silent param regression).
            with self._lock:
                lease_mode = self._lease_mode()
                reject = (not self._caught_up
                          or int(msg.get("round", 0))
                          > self._applied_round
                          or (not lease_mode
                              and int(msg.get("fo", 0)) < 1))
                expired = lease_mode and self._lease_expired_locked()
            if not reject:
                if lease_mode:
                    # election takes its own locks (it rpcs the group)
                    if not self._maybe_elect("dataplane:" + kind):
                        resp = {
                            "ok": False, "not_primary": True,
                            "error": "endpoint %s is a backup (index "
                            "%d) awaiting lease expiry/quorum"
                            % (self._own_endpoint, self._index)}
                        if expired or int(msg.get("fo", 0)) >= 1:
                            # a failed-over client should WAIT here
                            # (its old primary is dead to it) instead
                            # of burning failover budget on redirects
                            with self._lock:
                                left = (self._lease_deadline
                                        - time.monotonic()) * 1e3
                            resp["lease_wait_ms"] = max(
                                min(left, 1000.0),
                                self._lease_s * 250.0)
                        return resp, b""
                else:
                    with self._lock:
                        if not self._active_role():
                            self._promote_locked(kind)
            else:
                return {"ok": False, "not_primary": True,
                        "error": "endpoint %s is a backup (index "
                        "%d, caught_up=%s, round %d vs client "
                        "round %s), not the primary"
                        % (self._own_endpoint, self._index,
                           self._caught_up, self._applied_round,
                           msg.get("round"))}, b""
        if kind in self._DATAPLANE and self._active_role():
            # even an ACTIVE primary must refuse a client that has
            # OBSERVED a newer round than it holds: a backup that fell
            # off the replication stream and later won a tombstone
            # election (its only voter being the dead primary) would
            # otherwise silently regress params. Better no primary —
            # loud failure — than a stale one.
            with self._lock:
                if int(msg.get("round", 0)) > self._applied_round:
                    return {"ok": False, "not_primary": True,
                            "error": "endpoint %s is at round %d but "
                            "the client observed round %s — refusing "
                            "to serve stale params"
                            % (self._own_endpoint, self._applied_round,
                               msg.get("round"))}, b""
        if kind in ("send_grad", "get_param") and self._active_role():
            # live-migration routing (ISSUE 13): a var migrated AWAY
            # redirects (the token is un-recorded — the rpc executes
            # exactly once, at the real owner); a var staged IN whose
            # dataplane traffic arrives proves the donor's map bump
            # reached a trainer, so the stage self-commits (backstop
            # for a donor that died between its bump and the commit)
            base = str(msg.get("name", "")).split("@", 1)[0]
            if base:
                with self._lock:
                    st = self._staged_in.get(base)
                    if st is not None and int(msg.get("mv", -1)) \
                            >= int(st["version"]):
                        # the client PROVED the donor's map bump (its
                        # adopted map version rides the rpc): commit.
                        # A version-0 hash-routed client proves
                        # nothing — a var migrating BACK toward its
                        # hash-home must not be committed by a client
                        # that never saw the bump.
                        self._commit_staged_locked(base)
                    ov = self._map_overrides.get(base)
                    if ov is not None \
                            and int(ov["shard"]) != self._shard_index:
                        return {"ok": False, "wrong_shard": True,
                                "name": base,
                                "shard_map":
                                    self._shard_map_payload_locked(),
                                "error": "var %r migrated to shard %s "
                                "(map v%d)" % (base, ov["shard"],
                                               self._shard_map_version)
                                }, b""
        if "trainer_id" in msg:
            tid = int(msg["trainer_id"])
            if self._evict_after > 0 and not self._clock_started:
                # first sign of life from ANY trainer arms the clock
                # for every expected rank (0..fanin-1) — not at server
                # construction, or slow worker startup (interpreter +
                # jax import) would read as death before round 1
                self._clock_started = True
                self.monitor.register(range(self._fanin))
            self.monitor.ping(tid)
            # an evicted trainer that TRAINS again (a supervised
            # relaunch) rejoins the round math; a mere heartbeat from a
            # zombie must not grow the fanin back
            if tid in self._evicted and kind in (
                    "send_grad", "send_barrier", "get_param",
                    "fetch_barrier", "pull_sparse", "push_sparse"):
                self._readmit(tid)
        if kind == "send_grad":
            arr = _array_from(msg["array"], raw)
            extra = {}
            with self._lock:
                if self._sync:
                    if self._stale_train_round_locked(msg):
                        # the TRAINING round this grad belongs to was
                        # already applied here (eviction sailed it, or
                        # a relaunched trainer is re-running a round
                        # whose barrier its dead incarnation already
                        # closed): folding it into the NEXT round
                        # would double-apply — drop it, tell the
                        # client, keep exactly-once
                        return {"ok": True, "stale_round": True,
                                "round": self._applied_round}, b""
                    self._pending.setdefault(
                        msg["name"], {})[int(msg.get("trainer_id",
                                                     0))] = arr
                else:  # async: apply immediately (RunAsyncLoop)
                    self._executor._write_var(self._scope, msg["name"],
                                              arr)
                    sub = self._grad_to_block.get(msg["name"])
                    if sub is not None:
                        self._executor.run_block(sub, self._scope)
                    # a dense async update touches its grad's FAMILY
                    # through its block: full diff takes over there
                    self._mark_families_dirty_locked([msg["name"]])
                    if (self._durable_store is not None
                            and self._active_role()):
                        self._log_async_op_locked(msg, raw,
                                                  kind="send_grad")
                    extra = self._async_tick_locked()
            return dict({"ok": True}, **extra), b""
        if kind == "send_barrier":
            with self._lock:
                if self._sync and self._stale_train_round_locked(msg):
                    # this barrier's round already applied: counting
                    # it would pre-pay the NEXT round's fanin and
                    # apply it early with a trainer missing
                    resp = {"ok": True, "stale_round": True,
                            "round": self._applied_round}
                    if self._shard_map_version:
                        resp["shard_map"] = \
                            self._shard_map_payload_locked()
                    return resp, b""
                # gate round N+1 on round N being fully fetched
                self._wait_for(lambda: not self._fetches_pending,
                               "previous round's fetch barriers")
                self._send_barriers += 1
                self._round_complete = False
                if self._send_barriers >= self._effective_fanin():
                    self._apply_round()
                else:
                    self._wait_for(lambda: self._round_complete,
                                   "all trainers' send barriers")
                resp = {"ok": True, "round": self._applied_round}
                if self._shard_map_version:
                    # the barrier IS the atomic map-adoption point:
                    # every trainer's round-N ack carries the map that
                    # round N's apply may just have bumped
                    resp["shard_map"] = self._shard_map_payload_locked()
            return resp, b""
        if kind == "get_param":
            with self._lock:
                if self._sync:
                    self._wait_for(lambda: self._round_complete,
                                   "the optimize round")
                val = self._executor._read_var(self._scope, msg["name"])
            if val is None:
                return {"ok": False,
                        "error": "no var %r" % msg["name"]}, b""
            arr = np.ascontiguousarray(np.asarray(val))
            return {"ok": True, "array": _array_header(arr)}, \
                arr.tobytes()
        if kind == "fetch_barrier":
            with self._lock:
                # only count toward an OPEN fetch window: a failover
                # replay of an already-satisfied barrier (the round it
                # closed arrived here via replication) must not
                # pre-pay the NEXT round's fetch count, or a later
                # round would unlatch with a trainer still mid-fetch
                if self._fetches_pending:
                    self._fetch_barriers += 1
                    if self._fetch_barriers >= self._effective_fanin():
                        self._fetch_barriers = 0
                        self._fetches_pending = False
                        self._cond.notify_all()
            return {"ok": True}, b""
        if kind == "pull_sparse":
            # sparse table pull (pslib PullSparseVarsSync,
            # fleet_wrapper.h:84): LOCAL row ids in, value rows out.
            # Deliberately NOT gated on the dense sync round: a pull
            # happens at FORWARD time, and waiting for _round_complete
            # here would deadlock two sync trainers (A's barrier waits
            # for B while B's pull waits for the round A opened) —
            # sparse tables are round-free in pslib, like the push.
            ids = _array_from(msg["array"], raw).reshape(-1)
            with self._lock:
                base = str(msg["name"]).split("@", 1)[0]
                redir = self._range_redirect_locked(
                    base, ids, int(msg.get("mv", -1)))
                if redir is not None:
                    return redir, b""
                tbl = self._executor._read_var(self._scope, msg["name"])
            if tbl is None:
                return {"ok": False,
                        "error": "no table %r" % msg["name"]}, b""
            vals = np.ascontiguousarray(np.asarray(tbl)[ids])
            return {"ok": True, "array": _array_header(vals)}, \
                vals.tobytes()
        if kind == "push_sparse":
            # sparse grad push applied IMMEDIATELY (pslib
            # PushSparseVarsAsync semantics — downpour workers don't
            # gate sparse updates on the dense sync round). raw =
            # rows bytes + values bytes; rows are LOCAL to this shard.
            rh, vh = msg["rows"], msg["array"]
            nrows_bytes = int(np.dtype(rh["dtype"]).itemsize
                              * int(np.prod(rh["shape"])))
            rows = np.frombuffer(raw[:nrows_bytes],
                                 dtype=rh["dtype"]).reshape(-1)
            vals = _array_from(vh, raw[nrows_bytes:])
            from ..core.tensor import LoDTensor, SelectedRows

            extra = {}
            with self._lock:
                pname = msg.get("param", "")
                if pname:
                    redir = self._range_redirect_locked(
                        pname, rows, int(msg.get("mv", -1)))
                    if redir is not None:
                        return redir, b""
                tbl = self._executor._read_var(self._scope, pname)
                height = (int(np.asarray(tbl).shape[0])
                          if tbl is not None else int(rows.max()) + 1)
                sr = SelectedRows(rows=rows.tolist(), height=height)
                sr._value = LoDTensor(vals)
                self._executor._write_var(self._scope, msg["name"], sr)
                sub = self._grad_to_block.get(msg["name"])
                t_blk = time.monotonic()
                if sub is not None:
                    self._executor.run_block(sub, self._scope)
                if pname:
                    # pslib sparse optimize blocks are row-local: the
                    # touched rows are exactly the pushed rows, so the
                    # next delta round can ship a row SLICE of the
                    # table instead of the whole thing
                    self._dirty_rows.setdefault(pname, set()).update(
                        int(r) for r in rows)
                    # hot-shard steering inputs (ISSUE 18): per-table
                    # apply time, dirty-row census, and a coarse
                    # row-heat histogram (8 buckets over the local
                    # height) the steerer derives split points from
                    _histogram("ps.apply_ms", shard=self._shard,
                               table=pname).observe(
                        (time.monotonic() - t_blk) * 1e3)
                    _gauge("ps.dirty_rows", shard=self._shard,
                           table=pname).set(
                        len(self._dirty_rows[pname]))
                    # GLOBAL height when the router stamped it (a
                    # range-sliced push), else this shard's own: the
                    # steerer sizes migrate_range plans from this
                    _gauge("ps.table_rows", shard=self._shard,
                           table=pname).set(
                        int(msg.get("gh") or height))
                    if height > 0:
                        for r in rows:
                            b = min(7, int(r) * 8 // height)
                            _counter("ps.row_heat", shard=self._shard,
                                     table=pname,
                                     bucket=str(b)).inc()
                if (self._durable_store is not None and not self._sync
                        and self._active_role()):
                    # log BEFORE the tick: if the tick folds this op
                    # into a frame, clear_ops_through truncates the
                    # entry right back — the invariant is that every
                    # acked async op is durable somewhere (frame or
                    # tail) the moment the ack leaves
                    self._log_async_op_locked(msg, raw)
                extra = self._async_tick_locked()
            return dict({"ok": True}, **extra), b""
        if kind == "checkpoint":
            # checkpoint_notify_op.cc: snapshot every servable var into
            # the requested directory (reference tensor-stream format)
            with self._lock:
                snapshot_scope_to_dir(self._executor, self._scope,
                                      msg.get("dir", ""))
            return {"ok": True}, b""
        if kind == "replicate":
            # primary -> backup round stream: post-round blobs (full
            # anchor or changed-vars/rows delta) + the dedup watermark,
            # applied atomically with a round-state reset so a
            # promotion right after is a clean round start. The rpc
            # doubles as a lease renewal (it proves the primary
            # lives); a lower-epoch sender is fenced.
            if self._active_role():
                return {"ok": False, "error":
                        "replicate sent to the active primary %s"
                        % self._own_endpoint}, b""
            mode = msg.get("repl_mode", "full")
            off = 0
            with self._lock:
                epoch = int(msg.get("epoch", 0))
                if epoch < self._seen_epoch:
                    # ok=True: the rpc worked — the VERDICT is fenced,
                    # and the stale primary must read it, not retry.
                    # Loud in the flight ring: after a cold restart
                    # this is the dead incarnation's straggler being
                    # refused by the disk-restored epoch (ISSUE 19)
                    _counter("ps.fence_refused").inc()
                    _flight.record("ps.fence_refused",
                                   kind="replicate", epoch=epoch,
                                   seen=self._seen_epoch,
                                   shard=self._shard)
                    return {"ok": True, "fenced": True,
                            "epoch": self._seen_epoch}, b""
                self._refresh_lease_locked(epoch)
                if mode == "delta" and (
                        not self._caught_up
                        or int(msg.get("repl_base_round", -1))
                        != self._applied_round):
                    # can't apply a delta we don't have the base for
                    # (freshly rejoined / missed rounds): ask for a
                    # full re-anchor instead of silently diverging
                    return {"ok": True, "repl_gap": True,
                            "round": self._applied_round}, b""
                for h in msg.get("vars", []):
                    n = int(np.dtype(h["dtype"]).itemsize
                            * int(np.prod(h["shape"]) if h["shape"]
                                  else 1))
                    arr = _array_from(h, raw[off:off + n])
                    off += n
                    rows = h.get("rows")
                    chunk = h.get("chunk")
                    if rows is not None:
                        # row SLICE of a sparse table: splice into the
                        # resident copy (the anchor shipped the rest)
                        tbl = np.array(np.asarray(
                            self._executor._read_var(self._scope,
                                                     h["name"])),
                            copy=True)
                        tbl[np.asarray(rows, dtype=np.int64)] = arr
                        self._executor._write_var(self._scope,
                                                  h["name"], tbl)
                    elif chunk is not None:
                        # FLAT element range of a dense var (chunk-
                        # digest delta, ISSUE 13): splice into the
                        # flattened resident copy
                        tbl = np.array(np.asarray(
                            self._executor._read_var(self._scope,
                                                     h["name"])),
                            copy=True)
                        tbl.reshape(-1)[int(chunk[0]):int(chunk[1])] \
                            = arr.reshape(-1)
                        self._executor._write_var(self._scope,
                                                  h["name"], tbl)
                    else:
                        self._executor._write_var(self._scope,
                                                  h["name"], arr)
                # shard-map / migration state rides the stream: a
                # promoted backup must know what moved (or is moving)
                # away, or it would serve — or lose — a migrated var
                sm = msg.get("shard_map")
                if sm and int(sm.get("version", 0)) \
                        >= self._shard_map_version:
                    self._shard_map_version = int(sm["version"])
                mo = msg.get("map_overrides")
                if mo:
                    for n2, ov in mo.items():
                        cur = self._map_overrides.get(n2)
                        if cur is None or int(cur.get("version", 0)) \
                                <= int(ov.get("version", 0)):
                            self._map_overrides[n2] = dict(ov)
                for n2 in msg.get("dropped", []) or []:
                    if n2 not in self._dropped:
                        self._dropped.add(n2)
                        self._shipped_digests.pop(n2, None)
                        try:
                            if hasattr(self._scope, "__delitem__") \
                                    and n2 in self._scope.local_var_names():
                                del self._scope[n2]
                        except (KeyError, TypeError):
                            pass
                pm = msg.get("pending_migration")
                if pm:
                    # inherit the intent: a promoted backup re-drives
                    # the migration instead of silently dropping it
                    self._pending_migration = dict(pm)
                elif not self._active_role():
                    # the stream is the truth: an intent that stopped
                    # riding it was executed or rolled back upstream
                    self._pending_migration = None
                ro = msg.get("range_overrides")
                if ro:
                    # row-range ownership (ISSUE 18): adopted wholesale
                    # — the stream is the truth, and the full dicts
                    # (src window + recipient chain) let a promoted
                    # backup re-drive an uncommitted range commit
                    self._range_overrides = {
                        t: [dict(r) for r in rs] for t, rs in ro.items()}
                prm = msg.get("pending_range_migration")
                if prm:
                    self._pending_range_migration = dict(prm)
                elif not self._active_role():
                    self._pending_range_migration = None
                # NB "round" is the dedup-token key _call stamps on
                # every message — the payload round travels separately
                self._applied_round = int(msg["repl_round"])
                for cid, s in (msg.get("watermark") or {}).items():
                    if int(self._repl_watermark.get(cid, 0)) < int(s):
                        self._repl_watermark[cid] = int(s)
                self._pending.clear()
                self._send_barriers = 0
                self._fetch_barriers = 0
                self._round_complete = True
                self._fetches_pending = False
                self._caught_up = True
            _flight.record("ps.replicated", round=self._applied_round,
                           mode=mode)
            return {"ok": True, "round": self._applied_round}, b""
        if kind == "migrate_begin":
            # control plane, donor side: record the intent; the
            # transfer itself runs inside the NEXT round apply (the
            # freeze point every trainer is barrier-blocked behind)
            if not self._active_role():
                return {"ok": False, "not_primary": True,
                        "error": "migrate_begin sent to non-active "
                        "endpoint %s" % self._own_endpoint}, b""
            name = str(msg.get("name", "")).split("@", 1)[0]
            with self._lock:
                ov = self._map_overrides.get(name)
                if ov is not None \
                        and int(ov["shard"]) != self._shard_index:
                    return {"ok": True, "already_migrated": True,
                            "shard_map":
                                self._shard_map_payload_locked()}, b""
                if self._executor._read_var(self._scope, name) is None:
                    return {"ok": False, "error":
                            "no var %r to migrate" % name}, b""
                pm = self._pending_migration
                if pm is not None and pm.get("name") != name:
                    # one in-flight migration per group: silently
                    # replacing an acked intent would strand its
                    # caller — refuse loudly, retry after the barrier
                    return {"ok": False, "error":
                            "migration of %r already pending on %s — "
                            "retry after the next round barrier"
                            % (pm.get("name"),
                               self._own_endpoint)}, b""
                self._pending_migration = {
                    "name": name, "to_shard": int(msg["to_shard"]),
                    "to_endpoints": str(msg["to_endpoints"])}
            _flight.record("ps.migration_requested", var=name,
                           to_shard=int(msg["to_shard"]))
            return {"ok": True, "pending": True}, b""
        if kind == "migrate_install":
            # recipient side: STAGE the inbound range (not servable
            # until the donor's commit — or a dataplane touch that
            # proves the donor's map bump reached a trainer)
            if not self._active_role():
                return {"ok": False, "not_primary": True,
                        "error": "migrate_install sent to non-active "
                        "endpoint %s" % self._own_endpoint}, b""
            if msg.get("has_block") and self._block_factory is None:
                return {"ok": False, "error":
                        "recipient %s has no block_factory to rebuild "
                        "the optimize block for %r"
                        % (self._own_endpoint, msg.get("name"))}, b""
            name = str(msg["name"])
            arrays: Dict[str, np.ndarray] = {}
            off = 0
            for h in msg.get("vars", []):
                n = int(np.dtype(h["dtype"]).itemsize
                        * int(np.prod(h["shape"]) if h["shape"]
                              else 1))
                arrays[h["name"]] = _array_from(h, raw[off:off + n])
                off += n
            if name not in arrays:
                return {"ok": False, "error":
                        "migrate_install payload lacks the base var "
                        "%r" % name}, b""
            ver = int(msg["mig_version"])
            with self._lock:
                cur = self._map_overrides.get(name)
                if cur is not None and cur.get("committed") \
                        and int(cur.get("version", 0)) >= ver:
                    return {"ok": True, "already_committed": True}, b""
                self._staged_in[name] = {
                    "version": ver, "arrays": arrays,
                    "to_shard": int(msg["to_shard"]),
                    "round": int(msg.get("mig_round", 0)),
                    "watermark": dict(msg.get("watermark") or {})}
            _flight.record("ps.migration_install", var=name,
                           version=ver,
                           round=int(msg.get("mig_round", 0)))
            return {"ok": True, "staged": True}, b""
        if kind == "migrate_commit":
            if not self._active_role():
                return {"ok": False, "not_primary": True,
                        "error": "migrate_commit sent to non-active "
                        "endpoint %s" % self._own_endpoint}, b""
            name = str(msg["name"])
            ver = int(msg["mig_version"])
            with self._lock:
                cur = self._map_overrides.get(name)
                if cur is not None \
                        and int(cur.get("version", 0)) >= ver \
                        and cur.get("committed"):
                    return {"ok": True, "already_committed": True}, b""
                st = self._staged_in.get(name)
                if st is None or int(st["version"]) != ver:
                    return {"ok": False, "error":
                            "no staged migration of %r at version %d "
                            "on %s" % (name, ver,
                                       self._own_endpoint)}, b""
                self._commit_staged_locked(name)
            return {"ok": True}, b""
        if kind == "migrate_range_begin":
            # control plane, donor side (ISSUE 18): record the intent
            # to move rows [lo, hi) (global; src_lo/src_hi donor-local)
            # of one sparse table; the transfer itself runs inside the
            # NEXT round apply, behind the barrier every trainer is
            # blocked in
            if not self._active_role():
                return {"ok": False, "not_primary": True,
                        "error": "migrate_range_begin sent to "
                        "non-active endpoint %s"
                        % self._own_endpoint}, b""
            name = str(msg.get("name", "")).split("@", 1)[0]
            lo, hi = int(msg["lo"]), int(msg["hi"])
            src_lo, src_hi = int(msg["src_lo"]), int(msg["src_hi"])
            if hi <= lo or src_hi - src_lo != hi - lo or src_lo < 0:
                return {"ok": False, "error":
                        "bad range [%d,%d) (src [%d,%d)) for %r"
                        % (lo, hi, src_lo, src_hi, name)}, b""
            with self._lock:
                for r in self._range_overrides.get(name, ()):
                    if int(r["shard"]) != self._shard_index \
                            and not (hi <= int(r["lo"])
                                     or int(r["hi"]) <= lo):
                        return {"ok": True, "already_migrated": True,
                                "shard_map":
                                    self._shard_map_payload_locked()
                                }, b""
                tbl = self._executor._read_var(self._scope, name)
                if tbl is None or not hasattr(tbl, "shape") \
                        or int(np.asarray(tbl).shape[0]) < src_hi:
                    return {"ok": False, "error":
                            "no table %r holding local rows [%d,%d)"
                            % (name, src_lo, src_hi)}, b""
                if self._pending_migration is not None \
                        or self._pending_range_migration is not None:
                    # one in-flight migration per group, same refusal
                    # discipline as the whole-var path
                    return {"ok": False, "error":
                            "a migration is already pending on %s — "
                            "retry after the next round barrier"
                            % self._own_endpoint}, b""
                self._pending_range_migration = {
                    "name": name, "lo": lo, "hi": hi,
                    "src_lo": src_lo, "src_hi": src_hi,
                    "to_shard": int(msg["to_shard"]),
                    "to_endpoints": str(msg["to_endpoints"])}
            _flight.record("ps.range_migration_requested", var=name,
                           lo=lo, hi=hi, to_shard=int(msg["to_shard"]))
            return {"ok": True, "pending": True}, b""
        if kind == "migrate_range_install":
            # recipient side: STAGE the inbound rows (not servable
            # until the donor's replicated commit — or a dataplane
            # touch proving the donor's map bump reached a trainer).
            # The ack names the LOCAL base id the rows will land at.
            if not self._active_role():
                return {"ok": False, "not_primary": True,
                        "error": "migrate_range_install sent to "
                        "non-active endpoint %s"
                        % self._own_endpoint}, b""
            if msg.get("has_block") and self._block_factory is None:
                return {"ok": False, "error":
                        "recipient %s has no block_factory to rebuild "
                        "the optimize block for %r"
                        % (self._own_endpoint, msg.get("name"))}, b""
            name = str(msg["name"])
            arrays: Dict[str, np.ndarray] = {}
            off = 0
            for h in msg.get("vars", []):
                n = int(np.dtype(h["dtype"]).itemsize
                        * int(np.prod(h["shape"]) if h["shape"]
                              else 1))
                arrays[h["name"]] = _array_from(h, raw[off:off + n])
                off += n
            if name not in arrays:
                return {"ok": False, "error":
                        "migrate_range_install payload lacks the base "
                        "table %r" % name}, b""
            ver = int(msg["mig_version"])
            lo, hi = int(msg["lo"]), int(msg["hi"])
            with self._lock:
                for r in self._range_overrides.get(name, ()):
                    if (int(r["lo"]) == lo and int(r["hi"]) == hi
                            and r.get("committed")
                            and int(r.get("version", 0)) >= ver
                            and int(r["shard"]) == self._shard_index):
                        return {"ok": True, "already_committed": True,
                                "local_base": int(r["local_base"])
                                }, b""
                tbl = self._executor._read_var(self._scope, name)
                local_base = (int(np.asarray(tbl).shape[0])
                              if tbl is not None
                              and hasattr(tbl, "shape") else 0)
                self._staged_ranges[name] = {
                    "version": ver, "arrays": arrays,
                    "lo": lo, "hi": hi,
                    "to_shard": int(msg["to_shard"]),
                    "local_base": local_base,
                    "round": int(msg.get("mig_round", 0)),
                    "watermark": dict(msg.get("watermark") or {})}
            _flight.record("ps.range_migration_install", var=name,
                           lo=lo, hi=hi, version=ver,
                           round=int(msg.get("mig_round", 0)))
            return {"ok": True, "staged": True,
                    "local_base": local_base}, b""
        if kind == "migrate_range_commit":
            if not self._active_role():
                return {"ok": False, "not_primary": True,
                        "error": "migrate_range_commit sent to "
                        "non-active endpoint %s"
                        % self._own_endpoint}, b""
            name = str(msg["name"])
            ver = int(msg["mig_version"])
            lo, hi = int(msg["lo"]), int(msg["hi"])
            with self._lock:
                for r in self._range_overrides.get(name, ()):
                    if (int(r["lo"]) == lo and int(r["hi"]) == hi
                            and r.get("committed")
                            and int(r.get("version", 0)) >= ver):
                        return {"ok": True,
                                "already_committed": True}, b""
                st = self._staged_ranges.get(name)
                if st is None or int(st["version"]) != ver:
                    return {"ok": False, "error":
                            "no staged range of %r at version %d on %s"
                            % (name, ver, self._own_endpoint)}, b""
                tbl = self._executor._read_var(self._scope, name)
                height = (int(np.asarray(tbl).shape[0])
                          if tbl is not None
                          and hasattr(tbl, "shape") else 0)
                if height != int(st["local_base"]):
                    # the landing zone drifted since the stage (a
                    # concurrent migration grew the table): refuse —
                    # the donor re-installs against the fresh base
                    self._staged_ranges.pop(name, None)
                    return {"ok": False, "error":
                            "staged local base %d of %r no longer "
                            "matches table height %d — re-install"
                            % (int(st["local_base"]), name,
                               height)}, b""
                self._commit_staged_range_locked(name)
            return {"ok": True}, b""
        if kind == "lease_renew":
            with self._lock:
                epoch = int(msg.get("epoch", 0))
                if epoch < self._seen_epoch or (
                        self._active_role() and epoch < self._epoch):
                    _counter("ps.fence_refused").inc()
                    _flight.record("ps.fence_refused",
                                   kind="lease_renew", epoch=epoch,
                                   seen=max(self._seen_epoch,
                                            self._epoch),
                                   shard=self._shard)
                    return {"ok": False, "fenced": True,
                            "epoch": max(self._seen_epoch,
                                         self._epoch)}, b""
                if self._active_role() and epoch > self._epoch:
                    # a legitimately elected higher-epoch primary is
                    # renewing at us: we are the stale one
                    self._demote_locked(epoch, "renewal from higher-"
                                        "epoch primary %s"
                                        % msg.get("frm"))
                self._refresh_lease_locked(epoch)
                return {"ok": True, "round": self._applied_round,
                        "epoch": self._seen_epoch}, b""
        if kind == "vote":
            with self._lock:
                epoch = int(msg.get("epoch", 0))
                cand_round = int(msg.get("cand_round", -1))
                cand = msg.get("candidate")
                # Raft votedFor semantics: the SAME candidate may
                # re-collect a promise at the SAME epoch — an injected
                # fault (or real packet loss) eating the grant reply
                # must not burn the epoch and livelock every retry
                fresh = epoch > max(self._promised_epoch,
                                    self._seen_epoch, self._epoch)
                re_grant = (epoch == self._promised_epoch
                            and cand is not None
                            and cand == self._promised_to
                            and epoch > max(self._seen_epoch,
                                            self._epoch))
                granted = (self._lease_mode()
                           and not self._active_role()
                           and self._lease_expired_locked()
                           and (fresh or re_grant)
                           and cand_round >= self._applied_round)
                if granted:
                    self._promised_epoch = epoch
                    self._promised_to = cand
                resp = {"ok": True, "granted": granted,
                        "round": self._applied_round,
                        "epoch": self._seen_epoch,
                        "active": self._active_role()}
            _flight.record("ps.vote", candidate=msg.get("candidate"),
                           epoch=int(msg.get("epoch", 0)),
                           granted=bool(resp["granted"]),
                           voter=self._own_endpoint)
            return resp, b""
        if kind == "repl_status":
            with self._lock:
                return {"ok": True, "active": self._active_role(),
                        "caught_up": self._caught_up,
                        "round": self._applied_round,
                        "index": self._index,
                        "epoch": self._epoch,
                        "seen_epoch": self._seen_epoch,
                        "lease_expired": (self._lease_mode()
                                          and self._lease_expired_locked()
                                          )}, b""
        if kind == "join_backup":
            # a relaunched server catching up: snapshot the scope into
            # its directory AND splice it back into the replication
            # stream in the same locked step, so every round applied
            # after the snapshot reaches it
            if not self._active_role():
                return {"ok": False, "error":
                        "join_backup sent to non-active endpoint %s"
                        % self._own_endpoint}, b""
            ep = msg.get("endpoint", "")
            with self._lock:
                snapshot_scope_to_dir(self._executor, self._scope,
                                      msg.get("dir", ""),
                                      names_map=True)
                # NOT the live _last_seq: a mid-round join must ship
                # the watermark of the state in the snapshot, or the
                # pending round's replays would be falsely skipped
                wm = dict(self._applied_watermark)
                if ep:
                    self._repl_dead.discard(ep)
                resp = {"ok": True, "round": self._applied_round,
                        "watermark": wm, "epoch": self._epoch}
                resp.update(self._repl_extra_locked())
                return resp, b""
        if kind == "heartbeat":
            with self._lock:
                evicted = sorted(self._evicted)
                eff = self._effective_fanin()
                smap = self._shard_map_payload_locked()
            return {"ok": True,
                    "status": {str(k): v
                               for k, v in
                               self.monitor.status().items()},
                    "evicted": evicted,
                    "fanin": self._fanin,
                    "effective_fanin": eff,
                    "active": self._active_role(),
                    "round": self._applied_round,
                    # process-wide counters, surfaced so an external
                    # probe (tests, the CI smoke) can assert on
                    # recovery without reaching into this process
                    "evictions": _counter("ps.evictions").value,
                    "readmissions": _counter("ps.readmissions").value,
                    "promotions": _counter("ps.promotions").value,
                    "shard_map": smap,
                    }, b""
        if kind == "shutdown":
            self._shutdown.set()
            with self._lock:
                self._cond.notify_all()
            return {"ok": True}, b""
        return {"ok": False, "error": "unknown kind %r" % kind}, b""

    def _traced_handle(self, msg: dict, raw: bytes):
        """Flight-record the incoming rpc token and run the handler
        under the client's propagated trace context (when the header
        carries one): the server span parents to the client's round /
        request span, and anything the handler does downstream — the
        optimize apply, a replication rpc to a backup — joins the same
        cross-process trace via the thread-local current context."""
        kind = msg.get("kind", "?")
        if kind not in _FLIGHT_QUIET:
            _flight.record("ps.rpc", kind=kind, cid=msg.get("cid"),
                           seq=msg.get("seq"), round=msg.get("round"),
                           fo=msg.get("fo"))
        tid, pspan = _dtrace.extract(msg)
        if tid is None:
            return self._handle(msg, raw)
        with _dtrace.child_span("rpc.server." + kind, trace_id=tid,
                                parent_span=pspan, cid=msg.get("cid"),
                                seq=msg.get("seq")):
            return self._handle(msg, raw)

    # -- socket plumbing --------------------------------------------------

    def _dispatch(self, msg: dict, raw: bytes):
        """Dedupe + handle one request. The client resends after a
        reconnect; a resend may arrive (a) after the original completed
        — return the cached response — or (b) while the original is
        STILL EXECUTING (it blocked in a barrier wait): wait on its
        completion event instead of running the handler twice, which
        would double-count a barrier / double-apply a grad. A resend of
        a request OLDER than the client's latest (a duplicated frame
        surfacing late) is answered with a stale marker and NEVER
        re-executed — the client discards the reply by seq anyway."""
        seq = msg.get("seq") if isinstance(msg, dict) else None
        cid = msg.get("cid") if isinstance(msg, dict) else None
        if seq is None or cid is None:
            return self._traced_handle(msg, raw)
        if (msg.get("kind") in ("send_grad", "send_barrier",
                                "push_sparse")
                and seq <= int(self._repl_watermark.get(cid, 0))):
            # failover replay of an rpc whose effect is already folded
            # into the replicated state this server holds (the
            # watermark travelled with the round stream / snapshot):
            # acknowledge without re-executing — exactly-once across
            # the promotion
            return {"ok": True, "replayed": True}, b""
        # the dedup token: the client's per-incarnation random nonce
        # (its trainer_id stand-in that survives nothing), the sync
        # round it believes it is in, and its per-connection sequence
        key = (msg.get("round", 0), seq)
        with self._dedupe_lock:
            cached = self._dedupe.get(cid)
            if cached is not None and cached[0] == key:
                ev = cached[1]
            elif seq <= self._last_seq.get(cid, 0):
                # duplicate of an ALREADY-SUPERSEDED request (a dup'd
                # frame surfacing after newer traffic): executing it
                # again would double-apply; its original response is
                # gone, so answer with a stale marker. (A legitimate
                # retry whose completed entry was LRU-pruned — >512
                # live cids between response loss and resend — also
                # lands here and fails loudly: exactly-once is kept at
                # the price of that narrow hard-fail; raise _DEDUPE_CAP
                # if a deployment actually churns that many clients.)
                return {"ok": False, "stale": True,
                        "error": "stale duplicate (seq %s <= %s)"
                        % (seq, self._last_seq.get(cid, 0))}, b""
            else:
                # dict insertion order doubles as the LRU order:
                # re-insert on every update so the oldest entry is
                # the longest-idle client
                prev_seq = int(self._last_seq.pop(cid, 0))
                self._last_seq[cid] = int(seq)
                ev = threading.Event()
                self._dedupe[cid] = [key, ev, None, b"", time.time()]
                if len(self._dedupe) > self._DEDUPE_CAP:
                    self._prune_dedupe_locked()
                cached = None
        if cached is not None:  # duplicate: original owns the handler
            if not ev.wait(timeout=_ROUND_TIMEOUT):
                return {"ok": False,
                        "error": "duplicate request (cid %s seq %s) "
                        "still in flight" % (cid, seq)}, b""
            with self._dedupe_lock:
                c2 = self._dedupe.get(cid)
            if c2 is not None and c2[0] == key:
                return c2[2], c2[3]
            return {"ok": False, "stale": True,
                    "error": "dedupe entry superseded"}, b""
        try:
            resp, rraw = self._traced_handle(msg, raw)
        except Exception as e:
            resp, rraw = {"ok": False, "error": "%s: %s"
                          % (type(e).__name__, e)}, b""
        if isinstance(resp, dict) and (resp.get("not_primary")
                                       or resp.get("wrong_shard")):
            # a redirect is NOT an execution: un-record the token so a
            # client's lease-wait retry (or its re-route of the SAME
            # rpc to the migrated var's real owner) re-runs the
            # handler exactly once — a cached redirect would poison
            # every retry of that token forever
            with self._dedupe_lock:
                ent = self._dedupe.get(cid)
                if ent is not None and ent[0] == key:
                    del self._dedupe[cid]
                if self._last_seq.get(cid) == int(seq):
                    if prev_seq:
                        self._last_seq[cid] = prev_seq
                    else:
                        self._last_seq.pop(cid, None)
            ev.set()
            return resp, rraw
        with self._dedupe_lock:
            ent = self._dedupe.get(cid)
            if ent is not None and ent[0] == key:
                ent[2], ent[3], ent[4] = resp, rraw, time.time()
        ev.set()
        return resp, rraw

    def _prune_dedupe_locked(self):
        """Cap the per-client caches: drop the least-recently-used
        completed RESPONSE entries (heartbeater clients come and go; an
        unbounded dict would grow with every incarnation). The tiny
        ``_last_seq`` watermark is kept much longer — pruning it with
        the response would re-open the stale-duplicate double-apply
        window for a still-live client — and is itself LRU-capped far
        above the response cache, where only long-dead clients fall
        off the end."""
        done = sorted(
            (cid for cid, e in self._dedupe.items() if e[1].is_set()),
            key=lambda c: self._dedupe[c][4])
        for cid in done[:max(0, len(self._dedupe) - self._DEDUPE_CAP)]:
            del self._dedupe[cid]
        while len(self._last_seq) > 16 * self._DEDUPE_CAP:
            self._last_seq.pop(next(iter(self._last_seq)))

    def _serve_conn(self, conn: socket.socket):
        with self._conn_lock:
            self._conns.add(conn)
        try:
            while not self._shutdown.is_set():
                got = _recv_msg(conn)
                if got is None:
                    return
                msg, raw = got
                # catch ANY handler error (malformed message, bad dtype,
                # missing keys) and reply — a dead connection thread
                # would leave the client blocked until its own timeout
                try:
                    resp, rraw = self._dispatch(msg, raw)
                except Exception as e:
                    resp, rraw = {"ok": False, "error": "%s: %s"
                                  % (type(e).__name__, e)}, b""
                if isinstance(msg, dict) and msg.get("seq") is not None:
                    # echo the token: the retrying client matches
                    # responses by seq and discards strays from dup'd
                    # frames
                    resp.setdefault("seq", msg.get("seq"))
                    resp.setdefault("cid", msg.get("cid"))
                if self._evict_after > 0:
                    # advertise the eviction deadline: clients of an
                    # eviction-armed server MUST heartbeat while their
                    # main socket is blocked in a barrier, or a healthy
                    # straggler round would read as death — the client
                    # auto-arms its heartbeater off this field
                    resp.setdefault("evict_after", self._evict_after)
                _send_msg(conn, resp, rraw)
        except OSError:
            pass
        finally:
            with self._conn_lock:
                self._conns.discard(conn)
            conn.close()

    def serve_forever(self) -> None:
        """Accept loop; returns after a shutdown message (the reference
        blocks inside the listen_and_serv op the same way)."""
        try:
            self._sock.settimeout(0.2)
        except OSError:
            return  # stop() closed the socket before the loop began
        try:
            while not self._shutdown.is_set():
                try:
                    conn, _ = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break  # listening socket closed by stop()
                t = threading.Thread(target=self._serve_conn,
                                     args=(conn,), daemon=True)
                t.start()
                if len(self._threads) > 64:
                    # churning heartbeat clients reconnect forever;
                    # finished handler threads must not pile up
                    self._threads = [x for x in self._threads
                                     if x.is_alive()]
                self._threads.append(t)
        finally:
            try:
                self._sock.close()
            except OSError:
                pass

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever,
                             name="ps-accept", daemon=True)
        t.start()
        self._threads.append(t)
        return t

    def stop(self, join_timeout: float = 5.0) -> None:
        """Tear the server down NOW: wake blocked rounds, close the
        listening socket (the bound port is released even while a
        client is mid-frame), sever live connections, and join the
        worker threads. Idempotent; safe from any thread."""
        self._shutdown.set()
        with self._lock:
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass
        for c in (list(self._repl_clients.values())
                  + list(self._mig_clients.values())):
            try:
                c.close()
            except OSError:
                pass
        self._repl_clients.clear()
        self._mig_clients.clear()
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        me = threading.current_thread()
        deadline = time.time() + join_timeout
        for t in list(self._threads):
            if t is me or not t.is_alive():
                continue
            t.join(timeout=max(0.0, deadline - time.time()))


class _RetryableRPC(Exception):
    """Transport-level failure worth a reconnect-and-reissue."""


class _RPCTimeout(_RetryableRPC):
    pass


class _RPCConnLost(_RetryableRPC):
    pass


class _NotPrimary(_RetryableRPC):
    """The endpoint answered ``not_primary`` — advance along the
    endpoint list instead of burning the retry budget."""


class WrongShard(RuntimeError):
    """The endpoint answered ``wrong_shard`` — the named var was
    MIGRATED to another shard group (ISSUE 13). Carries the server's
    shard map so the sharded router updates its routing and reissues
    the rpc (with a fresh token, at the real owner — the redirecting
    server un-recorded the original, so the rpc still executes
    exactly once)."""

    def __init__(self, what: str, shard_map: Optional[dict] = None,
                 name: Optional[str] = None):
        super().__init__(what)
        self.shard_map = shard_map or {}
        self.name = name


class PSClient:
    """One persistent connection per (endpoint, trainer) —
    grpc_client.cc keeps channels the same way. Every call retries
    with bounded exponential backoff + jitter on timeout/EOF/conn loss
    (``PADDLE_PS_RPC_RETRIES``, default 3); the ``(cid, round, seq)``
    dedup token makes the resend of a non-idempotent rpc
    (send_grad/barriers) safe — the server executes it exactly once.

    ``endpoint`` may be a comma-separated primary + backups list
    (``PADDLE_PSERVER_ENDPOINTS``): when the retry budget on the
    current endpoint is exhausted by TRANSPORT failures, the client
    fails over to the next endpoint, replays its round log of
    non-idempotent rpcs with their original dedup tokens, and reissues
    the in-flight rpc (see the module docstring)."""

    _clients: Dict[tuple, "PSClient"] = {}
    _lock = threading.Lock()

    def __init__(self, endpoint: str, trainer_id: Optional[int] = 0,
                 timeout: Optional[float] = None,
                 auto_heartbeat: bool = True,
                 rpc_deadline: Optional[float] = None,
                 max_retries: Optional[int] = None):
        self._endpoints = [e.strip() for e in str(endpoint).split(",")
                           if e.strip()]
        if not self._endpoints:
            raise ValueError("PSClient needs at least one endpoint")
        self._ep_idx = 0
        self._trainer_id = trainer_id
        # auto-arm the background heartbeater when the server turns
        # out to be eviction-armed (its responses advertise
        # evict_after). Off for the heartbeater's own inner client.
        self._auto_heartbeat = bool(auto_heartbeat)
        self._timeout = timeout if timeout is not None else float(
            os.environ.get("PADDLE_PS_CONNECT_TIMEOUT", "15"))
        # per-ATTEMPT read deadline: must exceed the server round
        # timeout so only a dead/hung server trips it
        self._rpc_deadline = rpc_deadline if rpc_deadline is not None \
            else float(os.environ.get("PADDLE_PS_RPC_DEADLINE",
                                      str(_ROUND_TIMEOUT + 30.0)))
        self._max_retries = max_retries if max_retries is not None \
            else int(os.environ.get("PADDLE_PS_RPC_RETRIES", "3"))
        # failover budget: total endpoint advances per CALL (0 when
        # there is nowhere to go)
        self._max_failovers = int(os.environ.get(
            "PADDLE_PS_FAILOVER_MAX",
            str(2 * max(0, len(self._endpoints) - 1))))
        self._failover_count = 0  # the "fo" epoch carried on every rpc
        # non-idempotent rpcs in flight, with their stamped dedup
        # tokens — replayed verbatim on a failover. Entries are
        # [msg, raw, pending_round]: SYNC entries clear when the
        # round's barrier commits (the round is then applied AND
        # replicated on every shard the caller barriers); ASYNC
        # entries are round-gated — the server's ack tags each op with
        # the replication round that will carry it (pending_round) and
        # reports the last replicated round (durable_round), and an
        # entry is pruned only once its round is durable, making a
        # failover mid-async-push exactly-once (ISSUE 8; the cap below
        # is now a safety net, not the contract)
        self._replay_log: List[list] = []
        # sharded mode: the ShardedPSClient owns phase 2 of the round
        # barrier — this shard's log survives until EVERY shard acked
        self._defer_barrier_commit = False
        # total seconds per call a client will wait at a mid-promotion
        # backup (lease_wait_ms hints) before treating it as one more
        # failover hop
        self._lease_wait_s = float(
            os.environ.get("PADDLE_PS_LEASE_WAIT_S", "20"))
        self._replay_cap = int(
            os.environ.get("PADDLE_PS_REPLAY_LOG_CAP", "1024"))
        self._replay_overflowed = False
        self._backoff_base = float(
            os.environ.get("PADDLE_PS_RPC_BACKOFF_MS", "50")) / 1e3
        self._backoff_cap = float(
            os.environ.get("PADDLE_PS_RPC_BACKOFF_CAP_MS", "2000")) / 1e3
        # a failover probes endpoints that may be dead: use a short
        # connect window, not the boot-tolerant default
        self._failover_connect = float(os.environ.get(
            "PADDLE_PS_FAILOVER_CONNECT_TIMEOUT",
            str(min(self._timeout, 5.0))))
        # the sharded router's adopted map version, stamped (``mv``)
        # on every rpc so a recipient can tell a map-bump-proving
        # client from a hash-routed stale one
        self._map_version_hint: Optional[int] = None
        self._io_lock = threading.Lock()
        self._seq = 0  # per-client sequence: lets the server dedupe the
        # reconnect-resend in _call (send_grad/barriers are not
        # idempotent without it). The random client nonce scopes seq so
        # a RESTARTED trainer's fresh seq=1 never matches a stale cache
        # entry from its previous incarnation.
        self._round = 0  # completed send_barriers (the dedup token's
        # round component: (cid, round, seq))
        self._cid = os.urandom(8).hex()
        # one TraceContext per sync round (regenerated when _round
        # advances): every rpc/retry/failover of the round rides one
        # cross-process trace. Only populated while spans are armed.
        self._trace_ctx = None
        self._trace_round = -1
        self._jitter = random.Random(int.from_bytes(os.urandom(4),
                                                    "little"))
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self.evicted_peers: set = set()
        try:
            self._sock = self._connect()
        except RuntimeError:
            if len(self._endpoints) == 1:
                raise
            # the primary may be down with a backup alive (a trainer
            # relaunched mid-failover): defer to the first _call,
            # whose failover path walks the rest of the list
            self._sock = None

    @property
    def _endpoint(self) -> str:
        return self._endpoints[self._ep_idx]

    @property
    def endpoint(self) -> str:
        """The endpoint currently in use (moves on failover)."""
        return self._endpoint

    def _connect(self, timeout: Optional[float] = None) -> socket.socket:
        host, port = self._endpoint.rsplit(":", 1)
        if timeout is None:
            timeout = self._timeout
        deadline = time.time() + timeout
        last: Optional[OSError] = None
        while True:  # the pserver process may still be booting
            try:
                sock = socket.create_connection(
                    (host or "127.0.0.1", int(port)),
                    timeout=max(timeout, 1.0))
                # reads get a DEADLINE above the server's round bound:
                # a functioning server always replies within
                # _ROUND_TIMEOUT (slow barriers get an error reply), so
                # a longer client deadline only fires when the server
                # is dead/hung mid-round — failing fast (then retrying
                # boundedly) instead of hanging the trainer's sync send
                # loop forever (grpc_client.cc deadline+retry).
                sock.settimeout(self._rpc_deadline)
                return sock
            except OSError as e:
                last = e
                if time.time() > deadline:
                    raise RuntimeError(
                        "cannot reach pserver %s within %.0fs (%r) — is "
                        "the pserver program (listen_and_serv) running, "
                        "with PADDLE_PSERVER_RPC=1 for cross-process "
                        "mode?" % (self._endpoint, timeout, last))
                time.sleep(0.2)

    @classmethod
    def for_endpoint(cls, endpoint: str, trainer_id: int = 0):
        with cls._lock:
            key = (endpoint, trainer_id)
            c = cls._clients.get(key)
            if c is None:
                c = cls(endpoint, trainer_id)
                cls._clients[key] = c
                hb_ms = os.environ.get("PADDLE_PS_HEARTBEAT_MS")
                if hb_ms:
                    c.start_heartbeat(float(hb_ms) / 1e3)
            return c

    @classmethod
    def reset(cls):
        with cls._lock:
            for c in cls._clients.values():
                c.close()
            cls._clients.clear()

    def close(self) -> None:
        self.stop_heartbeat()
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None

    # -- background heartbeat (keeps this trainer alive in the server's
    # monitor while the MAIN connection is blocked in a barrier) ---------

    def start_heartbeat(self, interval_s: float = 1.0) -> None:
        """Ping the server every ``interval_s`` from a dedicated
        connection; surfaces peer evictions (``evicted_peers``) with a
        log line so a surviving trainer knows why its barrier suddenly
        completed. Env ``PADDLE_PS_HEARTBEAT_MS`` auto-arms this for
        ``for_endpoint`` clients."""
        if self._hb_thread is not None and self._hb_thread.is_alive():
            return
        self._hb_stop.clear()

        def loop():
            hb = None
            hb_ep = None
            while not self._hb_stop.wait(interval_s):
                try:
                    if hb is not None and hb_ep != self._endpoint:
                        # the main client failed over: heartbeats must
                        # follow it — pinging the abandoned endpoint
                        # keeps nobody alive anywhere
                        hb.close()
                        hb = None
                    if hb is None:
                        hb_ep = self._endpoint
                        hb = PSClient(hb_ep,
                                      trainer_id=self._trainer_id,
                                      auto_heartbeat=False)
                    resp = hb.heartbeat_full()
                    evicted = {int(t) for t in resp.get("evicted", [])}
                    new = evicted - self.evicted_peers
                    self.evicted_peers |= evicted
                    for t in sorted(new):
                        print("[ps_rpc] pserver %s evicted trainer %d; "
                              "continuing with effective fanin %s"
                              % (self._endpoint, t,
                                 resp.get("effective_fanin")),
                              file=sys.stderr, flush=True)
                except Exception:
                    # best-effort: a failed ping must never kill the
                    # trainer; the next tick retries (fresh connection)
                    if hb is not None:
                        hb.close()
                    hb = None
            if hb is not None:
                hb.close()

        self._hb_thread = threading.Thread(
            target=loop, name="ps-heartbeat-%d" % self._trainer_id,
            daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        self._hb_stop.set()

    # -- request path -----------------------------------------------------

    def _attempt(self, msg: dict, raw: bytes):
        """One send + seq-matched receive on the cached socket; raises
        a _RetryableRPC on timeout/EOF/conn loss after dropping the
        socket (it may hold a late/partial reply — reusing it would
        desync framing or hand the NEXT call the OLD response)."""
        if self._sock is None:
            self._sock = self._connect()
        kind = msg.get("kind", "?")
        quiet = kind in _FLIGHT_QUIET
        t0 = time.perf_counter()
        if not quiet:
            _flight.record("rpc.send", kind=kind, seq=msg.get("seq"),
                           cid=msg.get("cid"), round=msg.get("round"),
                           fo=msg.get("fo"), ep=self._endpoint)
        deadline = time.time() + self._rpc_deadline
        try:
            _send_msg(self._sock, msg, raw)
            while True:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise socket.timeout("rpc deadline")
                self._sock.settimeout(remaining)
                got = _recv_msg(self._sock)
                if got is None:
                    raise _RPCConnLost(
                        "pserver %s closed the connection"
                        % self._endpoint)
                resp, resp_raw = got
                rseq = resp.get("seq") if isinstance(resp, dict) else None
                if rseq is not None and rseq != msg["seq"]:
                    continue  # stale reply from a dup'd earlier frame
                # per-ATTEMPT reply latency (retries observe
                # separately): the axis rpc.retries lacks — a rising
                # retry rate with healthy latencies means a mis-set
                # per-attempt deadline, not a slow server
                _histogram("rpc.latency_ms", method=kind).observe(
                    (time.perf_counter() - t0) * 1e3)
                if msg.get("trace_id"):
                    _dtrace.record_span(
                        "rpc.client." + kind, t0, cat="rpc",
                        trace_id=msg["trace_id"],
                        parent_span=msg.get("parent_span"),
                        endpoint=self._endpoint, seq=msg.get("seq"))
                if not quiet:
                    _flight.record("rpc.recv", kind=kind,
                                   seq=msg.get("seq"),
                                   ok=bool(resp.get("ok"))
                                   if isinstance(resp, dict) else None)
                return resp, resp_raw
        except socket.timeout:
            self._drop_sock()
            _counter("rpc.timeouts", method=kind).inc()
            if not quiet:
                _flight.record("rpc.timeout", kind=kind,
                               seq=msg.get("seq"), ep=self._endpoint)
            raise _RPCTimeout(
                "pserver %s did not reply within the %.0fs RPC deadline "
                "(kind=%s)" % (self._endpoint, self._rpc_deadline,
                               msg.get("kind"))) from None
        except _RPCConnLost:
            self._drop_sock()
            if not quiet:
                _flight.record("rpc.conn_lost", kind=kind,
                               seq=msg.get("seq"), ep=self._endpoint)
            raise
        except OSError as e:
            self._drop_sock()
            if not quiet:
                _flight.record("rpc.conn_lost", kind=kind,
                               seq=msg.get("seq"), ep=self._endpoint)
            raise _RPCConnLost("pserver %s connection failed: %s"
                               % (self._endpoint, e)) from e

    def _drop_sock(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None

    def _stamp_trace(self, msg: dict) -> None:
        """Propagate trace context on the rpc header (Dapper-style: it
        rides the existing JSON frame; old-frame peers ignore the extra
        fields). An ambient context — a serving request span, a
        server-side handler issuing replication — wins; otherwise the
        client keeps one trace per sync round so every rpc, retry, and
        failover of the round lands in a single cross-process trace.
        No-op (no id generation) while the span layer is disarmed."""
        from ..observability import tracing as _tracing

        if not _tracing.active():
            return
        ctx = _dtrace.current()
        if ctx is None:
            if self._trace_ctx is None \
                    or self._trace_round != self._round:
                self._trace_ctx = _dtrace.TraceContext.new()
                self._trace_round = self._round
            ctx = self._trace_ctx
        _dtrace.inject(msg, ctx)

    def _call(self, msg: dict, raw: bytes = b""):
        if self._trainer_id is not None:
            msg.setdefault("trainer_id", self._trainer_id)
        with self._io_lock:
            self._seq += 1
            msg["seq"] = self._seq
            msg["cid"] = self._cid
            msg["round"] = self._round
            msg["fo"] = self._failover_count
            if self._map_version_hint is not None:
                msg["mv"] = int(self._map_version_hint)
            self._stamp_trace(msg)
            entry = None
            if (len(self._endpoints) > 1 and msg["kind"] in
                    ("send_grad", "send_barrier", "push_sparse")):
                entry = [dict(msg), bytes(raw), None]
                self._replay_log.append(entry)
                if len(self._replay_log) > self._replay_cap:
                    self._replay_log.pop(0)
                    if not self._replay_overflowed:
                        self._replay_overflowed = True
                        print("[ps_rpc] replay log exceeded %d entries"
                              " despite round-gated pruning; oldest"
                              " rpcs age out — a failover replay will"
                              " be PARTIAL (raise"
                              " PADDLE_PS_REPLAY_LOG_CAP, or lower the"
                              " server's PADDLE_PS_ASYNC_REPL_EVERY)"
                              % self._replay_cap,
                              file=sys.stderr, flush=True)
            resp, resp_raw = self._issue(msg, raw)
            if isinstance(resp, dict) and resp.get("wrong_shard"):
                # the var migrated: this rpc never executed here, and
                # it never will — drop its replay entry and hand the
                # server's map to the sharded router for the re-route
                if entry is not None:
                    try:
                        self._replay_log.remove(entry)
                    except ValueError:
                        pass
                raise WrongShard(
                    "pserver %s no longer owns %r: %s"
                    % (self._endpoint, resp.get("name"),
                       resp.get("error")),
                    shard_map=resp.get("shard_map"),
                    name=resp.get("name"))
            if entry is not None and isinstance(resp, dict) \
                    and resp.get("pending_round") is not None:
                # async ack: the op rides this replication round
                entry[2] = int(resp["pending_round"])
            if isinstance(resp, dict) \
                    and resp.get("durable_round") is not None:
                # rounds <= durable_round are replicated: their ops
                # survive the primary and never need replaying
                dr = int(resp["durable_round"])
                self._replay_log = [
                    e for e in self._replay_log
                    if e[2] is None or e[2] > dr]
            if (msg["kind"] == "send_barrier" and resp.get("ok")
                    and not self._defer_barrier_commit):
                # the barrier returned => the round is applied AND
                # replicated: its effects survive a primary death, so
                # nothing before this point ever needs replaying
                self._replay_log.clear()
        ea = resp.get("evict_after") if isinstance(resp, dict) else None
        if ea and self._auto_heartbeat and (
                self._hb_thread is None or not self._hb_thread.is_alive()):
            # the server evicts silent trainers: keep this one alive
            # while its main socket blocks in a barrier, even when the
            # operator forgot PADDLE_PS_HEARTBEAT_MS
            self.start_heartbeat(max(0.05, float(ea) / 4.0))
        if not resp.get("ok"):
            raise RuntimeError("pserver error: %s" % resp.get("error"))
        return resp, resp_raw

    def _issue(self, msg: dict, raw: bytes):
        """Bounded retry on the current endpoint; on exhaustion (or a
        ``not_primary`` redirect) advance along the endpoint list,
        replay the round log, and reissue — bounded by the failover
        budget. io-locked by caller."""
        kind = msg.get("kind", "?")
        attempts = 0
        failovers = 0
        delay = self._backoff_base
        wait_budget = self._lease_wait_s
        last_err: Optional[Exception] = None
        while True:
            try:
                resp, resp_raw = self._attempt(msg, raw)
                if isinstance(resp, dict) and resp.get("not_primary"):
                    e = _NotPrimary(
                        "pserver %s is not the primary (%s)"
                        % (self._endpoint, resp.get("error")))
                    e.wait_ms = resp.get("lease_wait_ms")
                    raise e
                return resp, resp_raw
            except _NotPrimary as e:
                wait_ms = getattr(e, "wait_ms", None)
                if wait_ms and wait_budget > 0:
                    # the backup is mid-promotion (waiting out the
                    # dead primary's lease / gathering its quorum):
                    # hold HERE instead of burning failover budget on
                    # redirect loops — bounded by the wait budget
                    dt = min(float(wait_ms) / 1e3, 0.3)
                    wait_budget -= dt
                    time.sleep(dt)
                    attempts, delay = 0, self._backoff_base
                    continue
                # a redirect, not a transport failure: advance without
                # burning the retry budget
                last_err = e
                failovers += 1
                if failovers > self._max_failovers:
                    raise RuntimeError(
                        "%s — no endpoint in %s accepted the dataplane "
                        "after %d failover(s)"
                        % (e, self._endpoints, failovers - 1)) from e
                self._failover(e, msg, redirect=True)
                attempts, delay = 0, self._backoff_base
            except _RetryableRPC as e:
                attempts += 1
                last_err = e
                if attempts > self._max_retries:
                    failovers += 1
                    if failovers > self._max_failovers:
                        raise RuntimeError(
                            "%s — gave up after %d attempt(s); the "
                            "server is dead or hung (raise "
                            "PADDLE_PS_RPC_DEADLINE / "
                            "PADDLE_PS_RPC_RETRIES if rounds "
                            "legitimately run longer)"
                            % (e, attempts)) from e
                    self._failover(e, msg)
                    attempts, delay = 0, self._backoff_base
                    continue
                _counter("rpc.retries", method=kind).inc()
                # exponential backoff + jitter (grpc_client.cc
                # retry semantics); the dedup token makes the
                # reissue safe even for non-idempotent kinds
                time.sleep(delay * (0.5 + self._jitter.random()))
                delay = min(delay * 2.0, self._backoff_cap)
            except RuntimeError as e:
                # the RECONNECT inside a retry failed (server gone
                # or its backlog full of our own dead sockets)
                failovers += 1
                if failovers > self._max_failovers:
                    # keep the error that started the retrying — "why
                    # it failed" beats "why the retry failed"
                    if last_err is not None:
                        raise RuntimeError(
                            "%s (while reconnecting after: %s)"
                            % (e, last_err)) from e
                    raise
                self._failover(last_err if last_err is not None else e,
                               msg)
                attempts, delay = 0, self._backoff_base

    def _failover(self, cause: Exception, msg: dict,
                  redirect: bool = False) -> None:
        """Advance to the next endpoint that accepts a connection and
        the round-log replay (deterministic list order — the
        lowest-index live endpoint ends up promoted). Raises
        RuntimeError when no endpoint works."""
        n = len(self._endpoints)
        start = self._ep_idx
        self._failover_count += 1
        msg["fo"] = self._failover_count
        t0 = time.perf_counter()
        _flight.record("rpc.failover.begin",
                       frm=self._endpoints[start], fo=self._failover_count,
                       cause=type(cause).__name__,
                       redirect=bool(redirect))
        last: Exception = cause
        wait_budget = self._lease_wait_s
        k = 1
        while k < n:
            self._ep_idx = (start + k) % n
            self._drop_sock()
            try:
                self._sock = self._connect(
                    timeout=self._failover_connect)
                self._replay()
            except _NotPrimary as e:
                wait_ms = getattr(e, "wait_ms", None)
                if wait_ms and wait_budget > 0:
                    # the replay target is mid-promotion: wait it out
                    # on THIS endpoint instead of walking on (the rest
                    # of the list is the dead primary)
                    dt = min(float(wait_ms) / 1e3, 0.3)
                    wait_budget -= dt
                    time.sleep(dt)
                    continue
                last = e
                self._drop_sock()
                k += 1
                continue
            except (_RetryableRPC, RuntimeError, OSError) as e:
                last = e
                self._drop_sock()
                k += 1
                continue
            _counter("ps.failovers",
                     cause="redirect" if redirect else "transport").inc()
            _flight.record("rpc.failover", frm=self._endpoints[start],
                           to=self._endpoint, fo=self._failover_count,
                           replayed=len(self._replay_log))
            # the span the merged timeline shows the failover as (ISSUE
            # 5 acceptance): parented into the round trace the failed
            # rpc belongs to, covering connect + replay
            _dtrace.record_span(
                "ps.failovers", t0, cat="rpc",
                trace_id=msg.get("trace_id"),
                parent_span=msg.get("parent_span"),
                cause="redirect" if redirect else "transport",
                frm=self._endpoints[start], to=self._endpoint)
            print("[ps_rpc] trainer %s failed over %s -> %s "
                  "(replayed %d rpc(s); after: %s)"
                  % (self._trainer_id,
                     self._endpoints[start], self._endpoint,
                     len(self._replay_log), cause),
                  file=sys.stderr, flush=True)
            return
        self._ep_idx = start
        _flight.record("rpc.failover.failed", frm=self._endpoints[start],
                       fo=self._failover_count)
        raise RuntimeError(
            "no reachable pserver among %s (last failover error: %s; "
            "failing over after: %s)" % (self._endpoints, last, cause))

    def _replay(self) -> None:
        """Reissue the round log on the endpoint just connected, with
        the ORIGINAL dedup tokens: rpcs the new primary already holds
        (via replication) are acknowledged as ``replayed`` without
        re-executing; the rest rebuild the in-flight round."""
        _flight.record("rpc.replay", n=len(self._replay_log),
                       ep=self._endpoint)
        for m, r, _pending in list(self._replay_log):
            m["fo"] = self._failover_count
            delay = self._backoff_base
            for attempt in range(self._max_retries + 1):
                try:
                    resp, _ = self._attempt(m, r)
                    break
                except _RetryableRPC:
                    # transient fault on an otherwise-healthy new
                    # endpoint (e.g. an injected drop): retry HERE —
                    # advancing past it would abandon a live primary
                    if attempt >= self._max_retries:
                        raise
                    _counter("rpc.retries",
                             method=m.get("kind", "?")).inc()
                    time.sleep(delay * (0.5 + self._jitter.random()))
                    delay = min(delay * 2.0, self._backoff_cap)
            if resp.get("not_primary"):
                e = _NotPrimary(
                    "pserver %s refused the failover replay"
                    % self._endpoint)
                e.wait_ms = resp.get("lease_wait_ms")
                raise e
            if not (resp.get("ok") or resp.get("replayed")
                    or resp.get("stale")):
                raise RuntimeError(
                    "pserver error during failover replay of %s: %s"
                    % (m.get("kind"), resp.get("error")))

    def send_grad(self, name: str, value, round: Optional[int] = None
                  ) -> None:
        """``round`` (optional) is the TRAINING round this grad
        belongs to — workers that track one stamp it (``tr`` on the
        wire) so a server that already applied that round (eviction
        sailed it without this trainer) drops the re-send instead of
        folding it into the NEXT round."""
        arr = np.ascontiguousarray(np.asarray(value))
        msg = {"kind": "send_grad", "name": name,
               "array": _array_header(arr)}
        if round is not None:
            msg["tr"] = int(round)
        self._call(msg, arr.tobytes())

    def seed_round(self, n: int) -> None:
        """Floor the completed-round counter (ISSUE 19): a trainer
        resuming after a whole-job cold restart seeds the job restore
        cut — the servers' applied round — so the server-side
        stale-primary guard starts from the restored state instead of
        zero. Callers must also fast-forward their training loop past
        the cut: seeding it and then RE-DRIVING older rounds would
        push this counter past the servers' applied round, which
        reads as 'refusing to serve stale params' on every pull."""
        self._round = max(self._round, int(n))

    def send_barrier(self, round: Optional[int] = None) -> None:
        self.barrier_prepare(round=round)
        self._round += 1

    def barrier_prepare(self, round: Optional[int] = None) -> dict:
        """Phase 1 of the two-phase round barrier: issue the barrier
        rpc. With ``_defer_barrier_commit`` set (sharded mode) the
        replay log SURVIVES this shard's ack — the round is durable
        only when every shard acked, so a sister shard's failover can
        still replay this round here (the dedup watermark makes that
        exactly-once). Single-group clients clear on ack as before.
        Returns the server's response — it may carry the current
        ``shard_map`` (the atomic adoption point for live migrations)
        and, with ``round`` stamped, ``stale_round`` when this
        training round already applied here."""
        msg = {"kind": "send_barrier"}
        if round is not None:
            msg["tr"] = int(round)
        resp, _ = self._call(msg)
        return resp

    def barrier_commit(self) -> None:
        """Phase 2 (sharded mode): every shard acked its barrier — the
        round is durable everywhere, drop the replay log and advance
        the round."""
        with self._io_lock:
            self._replay_log.clear()
        self._round += 1

    def get_param(self, name: str) -> np.ndarray:
        resp, raw = self._call({"kind": "get_param", "name": name})
        return _array_from(resp["array"], raw)

    def fetch_barrier(self) -> None:
        self._call({"kind": "fetch_barrier"})

    def pull_sparse(self, name: str, row_ids) -> np.ndarray:
        """Pull value rows for LOCAL row ids from this server's table
        shard (pslib PullSparseVarsSync counterpart)."""
        ids = np.ascontiguousarray(np.asarray(row_ids, dtype=np.int64))
        resp, raw = self._call({"kind": "pull_sparse", "name": name,
                                "array": _array_header(ids)},
                               ids.tobytes())
        return _array_from(resp["array"], raw)

    def push_sparse(self, name: str, rows, values, param: str = "",
                    global_height: Optional[int] = None) -> None:
        """Push (local row ids, grad rows) to this server's shard; the
        server applies its optimize block immediately (async, pslib
        PushSparseVarsAsync counterpart). ``param`` names the table var
        so the server can size the SelectedRows height.
        ``global_height`` is the table's GLOBAL height when the caller
        slices a range-partitioned table (the sharded router): the
        server's ``ps.table_rows`` gauge reports it so the hot-shard
        steerer sizes plans from the whole table, not this shard's
        slice."""
        rows = np.ascontiguousarray(np.asarray(rows, dtype=np.int64))
        vals = np.ascontiguousarray(np.asarray(values))
        msg = {"kind": "push_sparse", "name": name,
               "param": param,
               "rows": _array_header(rows),
               "array": _array_header(vals)}
        if global_height:
            msg["gh"] = int(global_height)
        self._call(msg, rows.tobytes() + vals.tobytes())

    def checkpoint(self, dirname: str) -> None:
        """Ask the server to snapshot its vars (checkpoint_notify)."""
        self._call({"kind": "checkpoint", "dir": dirname})

    def replicate(self, round_no: int, var_headers: List[dict],
                  raw: bytes, watermark: Dict[str, int],
                  mode: str = "full",
                  base_round: Optional[int] = None,
                  epoch: int = 0,
                  extra: Optional[dict] = None) -> dict:
        """Primary-side: ship one applied round (full anchor or
        changed-vars/rows/chunks delta + dedup watermark) to the
        backup this client points at; returns the backup's ack —
        which may carry ``repl_gap`` (re-anchor me) or ``fenced`` (a
        newer epoch rules; demote yourself). ``extra`` carries the
        shard-map / migration fields (ISSUE 13)."""
        msg = {"kind": "replicate", "repl_round": int(round_no),
               "vars": var_headers, "watermark": watermark,
               "repl_mode": mode,
               "repl_base_round": (-1 if base_round is None
                                   else int(base_round)),
               "epoch": int(epoch)}
        if extra:
            msg.update(extra)
        resp, _ = self._call(msg, raw)
        return resp

    def repl_status(self) -> dict:
        """role/round probe: ``{"active":, "caught_up":, "round":}``."""
        resp, _ = self._call({"kind": "repl_status"})
        return resp

    def migrate(self, name: str, to_shard: int,
                to_endpoints: str) -> dict:
        """Ask THIS endpoint chain's primary (the donor) to migrate
        var ``name`` to the group at ``to_endpoints`` (shard index
        ``to_shard``). The transfer executes at the donor's next
        round barrier; the ack only records the intent."""
        resp, _ = self._call({"kind": "migrate_begin",
                              "name": name,
                              "to_shard": int(to_shard),
                              "to_endpoints": str(to_endpoints)})
        return resp

    def migrate_range(self, name: str, lo: int, hi: int,
                      src_lo: int, src_hi: int, to_shard: int,
                      to_endpoints: str) -> dict:
        """Ask THIS endpoint chain's primary (the donor) to migrate
        rows ``[lo, hi)`` (GLOBAL ids; ``src_lo``/``src_hi`` the
        donor-LOCAL window) of sparse table ``name`` to the group at
        ``to_endpoints``. The transfer executes at the donor's next
        round barrier; the ack only records the intent (ISSUE 18)."""
        resp, _ = self._call({"kind": "migrate_range_begin",
                              "name": name,
                              "lo": int(lo), "hi": int(hi),
                              "src_lo": int(src_lo),
                              "src_hi": int(src_hi),
                              "to_shard": int(to_shard),
                              "to_endpoints": str(to_endpoints)})
        return resp

    def heartbeat(self) -> Dict[int, float]:
        resp, _ = self._call({"kind": "heartbeat"})
        return {int(k): v for k, v in resp["status"].items()}

    def heartbeat_full(self) -> dict:
        """Full heartbeat response: per-trainer ages plus ``evicted``
        / ``fanin`` / ``effective_fanin`` (the log-and-continue signal
        for survivors)."""
        resp, _ = self._call({"kind": "heartbeat"})
        return resp

    def shutdown_server(self) -> None:
        self._call({"kind": "shutdown"})


class PSWitness:
    """External quorum witness (ISSUE 13): a tiny vote-only endpoint
    OUTSIDE every replication group, named by ``PADDLE_PS_WITNESSES``
    (comma-separated) in each ``PSServer``'s environment. Primaries
    renew their lease with it exactly like with group peers (the
    renewal carries ``shard`` + ``lease_ms``, so ONE witness serves
    every shard of a job); a candidate's election additionally needs
    at least one live witness GRANT, and the witness grants only when
    its OWN per-shard lease view expired — positive evidence the
    primary stopped renewing, which a forged connection-REFUSED
    tombstone cannot fake. A shard the witness never heard a renewal
    for starts with a boot-grace lease (it must not rubber-stamp the
    first election it ever sees). Holds no parameter state; restart
    at will.

    Counters: ``ps.witness_votes{shard=}`` (every vote handled; the
    grant rides the flight line ``ps.witness_vote``) and
    ``ps.witness_renewals{shard=}``."""

    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        if _fault.get_identity() is None:
            _fault.set_identity(endpoint)
        # shard -> {"deadline", "lease_s", "seen_epoch", "promised"}
        self._state: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host or "127.0.0.1", int(port)))
        self._sock.listen(16)
        self._threads: List[threading.Thread] = []

    def _shard_state_locked(self, shard: str, lease_ms) -> dict:
        st = self._state.get(shard)
        if st is None:
            lease_s = max(float(lease_ms or 1500.0) / 1e3, 0.05)
            st = {"deadline": time.monotonic() + lease_s,
                  "lease_s": lease_s, "seen_epoch": 0, "promised": 0}
            self._state[shard] = st
        return st

    def _handle(self, msg: dict, raw: bytes):
        kind = msg.get("kind")
        shard = str(msg.get("shard", "0"))
        if kind == "lease_renew":
            with self._lock:
                st = self._shard_state_locked(shard,
                                              msg.get("lease_ms"))
                epoch = int(msg.get("epoch", 0))
                if epoch < st["seen_epoch"]:
                    return {"ok": False, "fenced": True,
                            "epoch": st["seen_epoch"]}, b""
                st["seen_epoch"] = max(st["seen_epoch"], epoch)
                if msg.get("lease_ms"):
                    st["lease_s"] = max(
                        float(msg["lease_ms"]) / 1e3, 0.05)
                st["deadline"] = time.monotonic() + st["lease_s"]
            _counter("ps.witness_renewals", shard=shard).inc()
            return {"ok": True, "epoch": int(msg.get("epoch", 0))}, b""
        if kind == "vote":
            with self._lock:
                st = self._shard_state_locked(shard,
                                              msg.get("lease_ms"))
                epoch = int(msg.get("epoch", 0))
                cand = msg.get("candidate")
                # votedFor: the same candidate may re-collect a
                # promise whose grant reply was lost on the wire —
                # a burned epoch must not livelock its retries
                fresh = epoch > max(st["promised"], st["seen_epoch"])
                re_grant = (epoch == st["promised"]
                            and cand is not None
                            and cand == st.get("promised_to")
                            and epoch > st["seen_epoch"])
                granted = (time.monotonic() > st["deadline"]
                           and (fresh or re_grant))
                if granted:
                    st["promised"] = epoch
                    st["promised_to"] = cand
            _counter("ps.witness_votes", shard=shard).inc()
            _flight.record("ps.witness_vote", shard=shard,
                           candidate=msg.get("candidate"),
                           epoch=int(msg.get("epoch", 0)),
                           granted=granted, witness=self.endpoint)
            # round -1: a witness holds no rounds and never vetoes a
            # candidate's staleness — that is the group voters' job
            return {"ok": True, "granted": granted, "round": -1,
                    "witness": True}, b""
        if kind == "witness_status":
            with self._lock:
                return {"ok": True, "witness": True,
                        "shards": {s: {
                            "expired": time.monotonic() > st["deadline"],
                            "seen_epoch": st["seen_epoch"],
                            "promised": st["promised"]}
                            for s, st in self._state.items()}}, b""
        if kind == "shutdown":
            self._shutdown.set()
            return {"ok": True}, b""
        # anything else (a misrouted dataplane rpc): loud refusal
        return {"ok": False, "witness": True,
                "error": "witness %s only answers lease_renew/vote, "
                "got %r" % (self.endpoint, kind)}, b""

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._shutdown.is_set():
                got = _recv_msg(conn)
                if got is None:
                    return
                msg, raw = got
                try:
                    resp, rraw = self._handle(msg, raw)
                except Exception as e:
                    resp, rraw = {"ok": False, "error": "%s: %s"
                                  % (type(e).__name__, e)}, b""
                if isinstance(msg, dict) and msg.get("seq") is not None:
                    resp.setdefault("seq", msg.get("seq"))
                    resp.setdefault("cid", msg.get("cid"))
                _send_msg(conn, resp, rraw)
        except OSError:
            pass
        finally:
            conn.close()

    def serve_forever(self) -> None:
        try:
            self._sock.settimeout(0.2)
        except OSError:
            return  # stop() closed the socket before the loop began
        try:
            while not self._shutdown.is_set():
                try:
                    conn, _ = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                t = threading.Thread(target=self._serve_conn,
                                     args=(conn,), daemon=True)
                t.start()
                if len(self._threads) > 64:
                    # every renewal sweep opens a fresh connection;
                    # finished handler threads must not pile up for
                    # the lifetime of the job
                    self._threads = [x for x in self._threads
                                     if x.is_alive()]
                self._threads.append(t)
        finally:
            try:
                self._sock.close()
            except OSError:
                pass

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever,
                             name="ps-witness", daemon=True)
        t.start()
        self._threads.append(t)
        return t

    def stop(self) -> None:
        self._shutdown.set()
        try:
            self._sock.close()
        except OSError:
            pass
