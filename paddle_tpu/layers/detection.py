"""Detection layer APIs.

Parity: /root/reference/python/paddle/fluid/layers/detection.py (28
public APIs; first wave here covers the graph-side box/anchor/NMS
surface the SSD/YOLO/Faster-RCNN configs touch).
"""
from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper

__all__ = [
    "prior_box",
    "anchor_generator",
    "iou_similarity",
    "box_coder",
    "box_clip",
    "yolo_box",
    "roi_align",
    "roi_pool",
    "prroi_pool",
    "multiclass_nms",
    "locality_aware_nms",
    "retinanet_detection_output",
    "detection_map",
    "multi_box_head",
]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", input=input)
    dtype = helper.input_dtype()
    boxes = helper.create_variable_for_type_inference(dtype)
    variances = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "flip": flip,
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
            "min_max_aspect_ratios_order": min_max_aspect_ratios_order,
        },
        infer_shape=False)
    # [H, W, num_priors, 4] (prior_box_op.cc InferShape; ratios expand
    # to {1} ∪ {r, 1/r if flip})
    expanded = [1.0]
    for r in aspect_ratios:
        if not any(abs(float(r) - e) < 1e-6 for e in expanded):
            expanded.append(float(r))
            if flip:
                expanded.append(1.0 / float(r))
    num_priors = len(expanded) * len(min_sizes) + len(max_sizes or [])
    if input.shape is not None:
        shape = (int(input.shape[2]), int(input.shape[3]),
                 num_priors, 4)
        boxes.shape = shape
        variances.shape = shape
    return boxes, variances


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper("anchor_generator", input=input)
    dtype = helper.input_dtype()
    anchors = helper.create_variable_for_type_inference(dtype)
    variances = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "anchor_generator",
        inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [variances]},
        attrs={
            "anchor_sizes": list(anchor_sizes or [64.0]),
            "aspect_ratios": list(aspect_ratios or [1.0]),
            "variances": list(variance),
            "stride": list(stride or [16.0, 16.0]),
            "offset": offset,
        },
        infer_shape=False)
    return anchors, variances


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", input=x)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op("iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"box_normalized": box_normalized},
                     infer_shape=False)
    out.shape = (int(x.shape[0]), int(y.shape[0]))
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    helper = LayerHelper("box_coder", input=target_box)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    attrs = {"code_type": code_type, "box_normalized": box_normalized,
             "axis": axis}
    from ..framework import Variable

    if isinstance(prior_box_var, Variable):
        inputs["PriorBoxVar"] = [prior_box_var]
    elif isinstance(prior_box_var, (list, tuple)):
        attrs["variance"] = list(prior_box_var)
    helper.append_op("box_coder", inputs=inputs,
                     outputs={"OutputBox": [out]}, attrs=attrs,
                     infer_shape=False)
    # encode: [num_target, num_prior, 4]; decode keeps target's shape
    if code_type == "encode_center_size":
        out.shape = (int(target_box.shape[0]), int(prior_box.shape[0]), 4)
    else:
        out.shape = tuple(target_box.shape)
    return out


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", input=input)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op("box_clip",
                     inputs={"Input": [input], "ImInfo": [im_info]},
                     outputs={"Output": [out]}, infer_shape=False)
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None):
    helper = LayerHelper("yolo_box", input=x)
    dtype = helper.input_dtype()
    boxes = helper.create_variable_for_type_inference(dtype)
    scores = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "yolo_box",
        inputs={"X": [x], "ImgSize": [img_size]},
        outputs={"Boxes": [boxes], "Scores": [scores]},
        attrs={"anchors": list(anchors), "class_num": class_num,
               "conf_thresh": conf_thresh,
               "downsample_ratio": downsample_ratio,
               "clip_bbox": clip_bbox},
        infer_shape=False)
    return boxes, scores


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None):
    helper = LayerHelper("roi_align", input=input)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(
        "roi_align",
        inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"spatial_scale": spatial_scale,
               "pooled_height": pooled_height,
               "pooled_width": pooled_width,
               "sampling_ratio": sampling_ratio},
        infer_shape=False)
    return out


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    """Precise ROI pooling (reference layers/nn.py:12680,
    prroi_pool_op.cc): exact bilinear-surface integration per bin."""
    helper = LayerHelper("prroi_pool", input=input)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    inputs = {"X": [input], "ROIs": [rois]}
    if batch_roi_nums is not None:
        inputs["BatchRoINums"] = [batch_roi_nums]
    helper.append_op(
        "prroi_pool",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={"spatial_scale": spatial_scale,
               "pooled_height": pooled_height,
               "pooled_width": pooled_width},
        infer_shape=False)
    out.shape = (int(rois.shape[0]) if rois.shape else -1,
                 int(input.shape[1]), pooled_height, pooled_width)
    out.dtype = input.dtype
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, name=None):
    helper = LayerHelper("roi_pool", input=input)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(
        "roi_pool",
        inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out]},
        attrs={"spatial_scale": spatial_scale,
               "pooled_height": pooled_height,
               "pooled_width": pooled_width},
        infer_shape=False)
    return out


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """Locality-aware NMS for text detection (reference
    detection.py locality_aware_nms, locality_aware_nms_op.cc)."""
    helper = LayerHelper("locality_aware_nms", input=bboxes)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    out.lod_level = 1
    helper.append_op(
        "locality_aware_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out]},
        attrs={"background_label": background_label,
               "score_threshold": score_threshold,
               "nms_top_k": nms_top_k, "nms_threshold": nms_threshold,
               "nms_eta": nms_eta, "keep_top_k": keep_top_k,
               "normalized": normalized},
        infer_shape=False)
    return out


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """RetinaNet decode + NMS over FPN levels (reference
    retinanet_detection_output_op.cc)."""
    helper = LayerHelper("retinanet_detection_output", input=bboxes[0])
    out = helper.create_variable_for_type_inference(
        helper.input_dtype("input"))
    out.lod_level = 1
    helper.append_op(
        "retinanet_detection_output",
        inputs={"BBoxes": list(bboxes), "Scores": list(scores),
                "Anchors": list(anchors), "ImInfo": [im_info]},
        outputs={"Out": [out]},
        attrs={"score_threshold": score_threshold,
               "nms_top_k": nms_top_k, "nms_threshold": nms_threshold,
               "nms_eta": nms_eta, "keep_top_k": keep_top_k},
        infer_shape=False)
    return out


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None,
                  out_states=None, ap_version="integral"):
    """Stateful mAP evaluator (reference detection.py detection_map,
    detection_map_op.h)."""
    helper = LayerHelper("detection_map", input=detect_res)

    map_out = helper.create_variable_for_type_inference("float32")
    acc_pos = (out_states[0] if out_states
               else helper.create_variable_for_type_inference("int32"))
    acc_tp = (out_states[1] if out_states
              else helper.create_variable_for_type_inference("float32"))
    acc_fp = (out_states[2] if out_states
              else helper.create_variable_for_type_inference("float32"))
    inputs = {"DetectRes": [detect_res], "Label": [label]}
    if has_state is not None:
        inputs["HasState"] = [has_state]
    if input_states is not None:
        inputs["PosCount"] = [input_states[0]]
        inputs["TruePos"] = [input_states[1]]
        inputs["FalsePos"] = [input_states[2]]
    helper.append_op(
        "detection_map", inputs=inputs,
        outputs={"AccumPosCount": [acc_pos], "AccumTruePos": [acc_tp],
                 "AccumFalsePos": [acc_fp], "MAP": [map_out]},
        attrs={"class_num": class_num,
               "background_label": background_label,
               "overlap_threshold": overlap_threshold,
               "evaluate_difficult": evaluate_difficult,
               "ap_type": ap_version},
        infer_shape=False)
    return map_out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD prediction head (reference detection.py:1970): per feature
    map, a conv for box locations and one for class confidences plus
    prior boxes; results concatenate across maps."""
    from .nn import conv2d, reshape, transpose
    from .tensor import concat

    n_in = len(inputs)
    if min_sizes is None:
        # the SSD ratio schedule (reference: min/max from base_size);
        # with <=2 maps the schedule degenerates — the reference
        # requires explicit sizes there
        assert n_in > 2, ("multi_box_head: pass explicit min_sizes/"
                          "max_sizes when len(inputs) <= 2")
        assert min_ratio is not None and max_ratio is not None
        min_sizes, max_sizes = [], []
        step = int(np.floor((max_ratio - min_ratio) / (n_in - 2))) \
            if n_in > 2 else 0
        for ratio in range(min_ratio, max_ratio + 1,
                           step if step else 1):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
            if len(min_sizes) == n_in - 1:
                break
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, x in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i]
        step_wh = (steps[i] if steps
                   else (step_w[i] if step_w else 0.0,
                         step_h[i] if step_h else 0.0))
        if not isinstance(step_wh, (list, tuple)):
            step_wh = (step_wh, step_wh)
        boxes, variances = prior_box(
            x, image,
            min_sizes=[mins] if not isinstance(mins, (list, tuple))
            else list(mins),
            max_sizes=[maxs] if maxs and not isinstance(
                maxs, (list, tuple)) else (maxs or None),
            aspect_ratios=ar if isinstance(ar, (list, tuple)) else [ar],
            variance=list(variance), flip=flip, clip=clip,
            steps=step_wh, offset=offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        # prior_box published its [H, W, num_priors, 4] shape
        num_priors = int(boxes.shape[2])
        loc = conv2d(x, num_priors * 4, kernel_size, padding=pad,
                     stride=stride)
        loc = transpose(loc, perm=[0, 2, 3, 1])
        loc = reshape(loc, shape=[0, -1, 4])
        conf = conv2d(x, num_priors * num_classes, kernel_size,
                      padding=pad, stride=stride)
        conf = transpose(conf, perm=[0, 2, 3, 1])
        conf = reshape(conf, shape=[0, -1, num_classes])
        boxes_all.append(reshape(boxes, shape=[-1, 4]))
        vars_all.append(reshape(variances, shape=[-1, 4]))
        locs.append(loc)
        confs.append(conf)

    mbox_locs = concat(locs, axis=1)
    mbox_confs = concat(confs, axis=1)
    boxes_cat = concat(boxes_all, axis=0)
    vars_cat = concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, boxes_cat, vars_cat


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    helper = LayerHelper("multiclass_nms", input=bboxes)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    out.lod_level = 1
    helper.append_op(
        "multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out]},
        attrs={"background_label": background_label,
               "score_threshold": score_threshold,
               "nms_top_k": nms_top_k,
               "nms_threshold": nms_threshold,
               "nms_eta": nms_eta,
               "keep_top_k": keep_top_k,
               "normalized": normalized},
        infer_shape=False)
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    helper = LayerHelper("bipartite_match", input=dist_matrix)
    idx = helper.create_variable_for_type_inference("int32")
    dist = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "bipartite_match", inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [idx],
                 "ColToRowMatchDist": [dist]},
        attrs={"match_type": match_type,
               "dist_threshold": dist_threshold},
        infer_shape=False)
    cols = int(dist_matrix.shape[-1])
    # dense (non-LoD) DistMat is ONE batch in the host kernel; LoD input
    # has one row-group per sequence (unknown statically)
    n = 1 if not getattr(dist_matrix, "lod_level", 0) else -1
    idx.shape = (n, cols)
    dist.shape = (n, cols)
    return idx, dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    helper = LayerHelper("target_assign", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    w = helper.create_variable_for_type_inference("float32")
    inputs = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices]
    helper.append_op("target_assign", inputs=inputs,
                     outputs={"Out": [out], "OutWeight": [w]},
                     attrs={"mismatch_value": mismatch_value},
                     infer_shape=False)
    n = int(matched_indices.shape[0])
    m = int(matched_indices.shape[1])
    k = int(input.shape[-1])
    out.shape = (n, m, k)
    w.shape = (n, m, 1)
    return out, w


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    helper = LayerHelper("density_prior_box", input=input)
    dtype = helper.input_dtype()
    boxes = helper.create_variable_for_type_inference(dtype)
    variances = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={"densities": list(densities or []),
               "fixed_sizes": list(fixed_sizes or []),
               "fixed_ratios": list(fixed_ratios or []),
               "variances": list(variance), "clip": clip,
               "step_w": steps[0], "step_h": steps[1], "offset": offset,
               "flatten_to_2d": flatten_to_2d},
        infer_shape=False)
    return boxes, variances


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Decode + NMS (reference layers/detection.py detection_output =
    box_coder(decode_center_size) + transpose + multiclass_nms)."""
    from .nn import softmax, transpose

    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    scores_t = transpose(softmax(scores), perm=[0, 2, 1])
    return multiclass_nms(decoded, scores_t,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold, nms_eta=nms_eta,
                          background_label=background_label)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mismatch_value=0, normalize=True, sample_size=None):
    """SSD multibox loss (reference layers/detection.py ssd_loss
    composition): IoU match gt->priors, box_coder-ENCODE the matched gt
    against priors (so training and detection_output's decode agree),
    assign loc/conf targets, smooth-L1 + softmax losses. All negatives
    weigh into the confidence term (the reference mines the top-k
    hardest; that refinement is a TODO). Single-image / dense-batch
    contract: LoD-batched ground truth is not supported yet."""
    from .loss import smooth_l1, softmax_with_cross_entropy
    from .nn import reduce_sum, reshape

    if getattr(gt_box, "lod_level", 0):
        raise NotImplementedError(
            "ssd_loss over LoD-batched ground truth is not supported "
            "yet; feed per-image dense gt")
    iou = iou_similarity(gt_box, prior_box)  # [num_gt, num_prior]
    matched, _ = bipartite_match(iou, match_type, overlap_threshold)
    # regression target = encoded offsets, matching the decode side
    encoded = box_coder(prior_box,
                        prior_box_var if prior_box_var is not None
                        else [0.1, 0.1, 0.2, 0.2],
                        gt_box, code_type="encode_center_size")
    loc_tgt, loc_w = target_assign(encoded, matched,
                                   mismatch_value=mismatch_value)
    lab_tgt, _conf_w = target_assign(gt_label, matched,
                                     mismatch_value=background_label)
    B = int(location.shape[0])
    P = int(prior_box.shape[0])
    loc_r = reshape(location, [B, P, 4])
    loc_l = smooth_l1(loc_r, loc_tgt)
    loc_l = loc_l * loc_w
    num_cls = int(confidence.shape[-1])
    conf_r = reshape(confidence, [B * P, num_cls])
    lab_r = reshape(lab_tgt, [B * P, 1])
    conf_l = softmax_with_cross_entropy(conf_r, lab_r)
    conf_l = reshape(conf_l, [B, P, 1])
    total = (reduce_sum(loc_l) * loc_loss_weight
             + reduce_sum(conf_l) * conf_loss_weight)
    if normalize:
        denom = reduce_sum(loc_w) + 1e-6
        total = total / denom
    return total


__all__ += ["bipartite_match", "target_assign", "density_prior_box",
            "detection_output", "ssd_loss"]


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """RPN proposal generation (reference detection.py:2713 over
    generate_proposals_op.cc; host kernel in ops/proposal_ops.py)."""
    helper = LayerHelper("generate_proposals", input=scores, name=name)
    rpn_rois = helper.create_variable_for_type_inference(bbox_deltas.dtype)
    rpn_roi_probs = helper.create_variable_for_type_inference(scores.dtype)
    helper.append_op(
        "generate_proposals",
        inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                "ImInfo": [im_info], "Anchors": [anchors],
                "Variances": [variances]},
        outputs={"RpnRois": [rpn_rois], "RpnRoiProbs": [rpn_roi_probs]},
        attrs={"pre_nms_topN": pre_nms_top_n,
               "post_nms_topN": post_nms_top_n, "nms_thresh": nms_thresh,
               "min_size": min_size, "eta": eta},
        infer_shape=False)
    rpn_rois.stop_gradient = True
    rpn_roi_probs.stop_gradient = True
    return rpn_rois, rpn_roi_probs


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """RPN anchor sampling (reference detection.py:289 over
    rpn_target_assign_op.cc). Returns (predicted_scores,
    predicted_location, target_label, target_bbox, bbox_inside_weight)."""
    from .nn import reshape

    helper = LayerHelper("rpn_target_assign", input=anchor_box)
    loc_index = helper.create_variable_for_type_inference("int32")
    score_index = helper.create_variable_for_type_inference("int32")
    target_label = helper.create_variable_for_type_inference("int32")
    target_bbox = helper.create_variable_for_type_inference(
        anchor_box.dtype)
    bbox_inside_weight = helper.create_variable_for_type_inference(
        anchor_box.dtype)
    helper.append_op(
        "rpn_target_assign",
        inputs={"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
                "IsCrowd": [is_crowd], "ImInfo": [im_info]},
        outputs={"LocationIndex": [loc_index], "ScoreIndex": [score_index],
                 "TargetLabel": [target_label], "TargetBBox": [target_bbox],
                 "BBoxInsideWeight": [bbox_inside_weight]},
        attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
               "rpn_straddle_thresh": rpn_straddle_thresh,
               "rpn_positive_overlap": rpn_positive_overlap,
               "rpn_negative_overlap": rpn_negative_overlap,
               "rpn_fg_fraction": rpn_fg_fraction,
               "use_random": use_random},
        infer_shape=False)
    for v in (loc_index, score_index, target_label, target_bbox,
              bbox_inside_weight):
        v.stop_gradient = True
    cls_flat = reshape(cls_logits, [-1, 1])
    bbox_flat = reshape(bbox_pred, [-1, 4])
    # index vars have runtime-only shapes — append gathers without the
    # static shape-inference pass
    predicted_cls = helper.create_variable_for_type_inference(
        cls_logits.dtype)
    predicted_loc = helper.create_variable_for_type_inference(
        bbox_pred.dtype)
    helper.append_op("gather",
                     inputs={"X": [cls_flat], "Index": [score_index]},
                     outputs={"Out": [predicted_cls]},
                     attrs={"overwrite": True}, infer_shape=False)
    helper.append_op("gather",
                     inputs={"X": [bbox_flat], "Index": [loc_index]},
                     outputs={"Out": [predicted_loc]},
                     attrs={"overwrite": True}, infer_shape=False)
    return (predicted_cls, predicted_loc, target_label, target_bbox,
            bbox_inside_weight)


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip, name=None):
    """(reference detection.py:3358 over box_decoder_and_assign_op.h)."""
    helper = LayerHelper("box_decoder_and_assign", input=prior_box,
                         name=name)
    decoded = helper.create_variable_for_type_inference(prior_box.dtype)
    assigned = helper.create_variable_for_type_inference(prior_box.dtype)
    helper.append_op(
        "box_decoder_and_assign",
        inputs={"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
                "TargetBox": [target_box], "BoxScore": [box_score]},
        outputs={"DecodeBox": [decoded], "OutputAssignBox": [assigned]},
        attrs={"box_clip": box_clip}, infer_shape=False)
    return decoded, assigned


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, name=None):
    """(reference detection.py:3274 over distribute_fpn_proposals_op.h).
    Returns (multi_rois list, restore_ind)."""
    helper = LayerHelper("distribute_fpn_proposals", input=fpn_rois,
                         name=name)
    num_lvl = max_level - min_level + 1
    multi_rois = [helper.create_variable_for_type_inference(fpn_rois.dtype)
                  for _ in range(num_lvl)]
    restore_ind = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "distribute_fpn_proposals",
        inputs={"FpnRois": [fpn_rois]},
        outputs={"MultiFpnRois": multi_rois,
                 "RestoreIndex": [restore_ind]},
        attrs={"min_level": min_level, "max_level": max_level,
               "refer_level": refer_level, "refer_scale": refer_scale},
        infer_shape=False)
    return multi_rois, restore_ind


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, name=None):
    """(reference detection.py:3423 over collect_fpn_proposals_op.h)."""
    helper = LayerHelper("collect_fpn_proposals", input=multi_rois[0],
                         name=name)
    num_lvl = max_level - min_level + 1
    fpn_rois = helper.create_variable_for_type_inference(
        multi_rois[0].dtype)
    helper.append_op(
        "collect_fpn_proposals",
        inputs={"MultiLevelRois": list(multi_rois[:num_lvl]),
                "MultiLevelScores": list(multi_scores[:num_lvl])},
        outputs={"FpnRois": [fpn_rois]},
        attrs={"post_nms_topN": post_nms_top_n}, infer_shape=False)
    return fpn_rois


def polygon_box_transform(input, name=None):
    """(reference detection.py:858 over polygon_box_transform_op.cc)."""
    helper = LayerHelper("polygon_box_transform", input=input, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("polygon_box_transform", inputs={"Input": [input]},
                     outputs={"Output": [out]}, infer_shape=False)
    out.shape = tuple(input.shape)
    return out


__all__ += ["generate_proposals", "rpn_target_assign",
            "box_decoder_and_assign", "distribute_fpn_proposals",
            "collect_fpn_proposals", "polygon_box_transform"]


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    """YOLOv3 training loss (reference detection.py:894 over
    yolov3_loss_op.h; see ops/tail_ops2.py)."""
    helper = LayerHelper("yolov3_loss", input=x, name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    obj_mask = helper.create_variable_for_type_inference(x.dtype)
    match = helper.create_variable_for_type_inference("int32")
    inputs = {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]}
    if gt_score is not None:
        inputs["GTScore"] = [gt_score]
    helper.append_op(
        "yolov3_loss", inputs=inputs,
        outputs={"Loss": [loss], "ObjectnessMask": [obj_mask],
                 "GTMatchMask": [match]},
        attrs={"anchors": list(anchors),
               "anchor_mask": list(anchor_mask),
               "class_num": class_num, "ignore_thresh": ignore_thresh,
               "downsample_ratio": downsample_ratio,
               "use_label_smooth": use_label_smooth},
        infer_shape=False)
    loss.shape = (int(x.shape[0]),)
    return loss


__all__ += ["yolov3_loss"]
