"""Parameter-server program transpiler.

Parity: /root/reference/python/paddle/fluid/transpiler/
distribute_transpiler.py (:95 slice_variable, :254 config, :540
transpile, :1146 get_pserver_program). With ``slice_var_up`` (the
default), large dense params are SLICED into row blocks spread over
pservers — the trainer splits each grad, sends blocks to their
hosting servers, and concats the recv'd param blocks; per-endpoint
server programs run the optimizer on just their block (matching the
reference's split_byref/concat rewrite). Trainer grads route through
send/barrier/recv ops, and server programs carry listen_and_serv with
optimizer sub-blocks, so transpiler-contract tests (reference
test_dist_transpiler.py) assert the same op sequences.

Runtime note (TPU-native): the send/recv ops execute against an
in-process table registry when endpoints are local ("emulated PS") —
the production distributed path for TPU pods is the collective fleet
(allreduce over ICI) and sharded embeddings via all-to-all
(parallel/sharded_embedding), per SURVEY §2.5: PS only for giant sparse
tables.
"""
from __future__ import annotations

import math
from typing import Dict, List

from .. import framework
from ..parallel.transpiler import OPTIMIZER_OP_TYPES


class DistributeTranspilerConfig:
    """(reference distribute_transpiler.py:141)"""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    enable_dc_asgd = False
    mode = "pserver"
    print_log = False
    wait_port = True
    runtime_split_send_recv = False
    sync_mode = True
    # geo-SGD: push parameter deltas every N local steps
    geo_sgd_mode = False
    geo_sgd_need_push_nums = 100


class VarBlock:
    def __init__(self, varname, offset, size):
        self.varname = varname
        self.offset = offset
        self.size = size

    def __str__(self):
        return "%s:%d:%d" % (self.varname, self.offset, self.size)


def slice_variable(var_list, slice_count, min_block_size):
    """Split vars into per-pserver blocks (reference
    distribute_transpiler.py:95): split dim0; block count bounded by
    slice_count and min_block_size."""
    blocks = []
    for var in var_list:
        split_count = slice_count
        var_numel = 1
        for s in var.shape:
            var_numel *= int(s)
        max_pserver_count = int(
            math.floor(var_numel / float(min_block_size)))
        if max_pserver_count == 0:
            max_pserver_count = 1
        if max_pserver_count < slice_count:
            split_count = max_pserver_count
        block_size = int(math.ceil(var_numel / float(split_count)))
        if len(var.shape) >= 2:
            dim1 = 1
            for s in var.shape[1:]:
                dim1 *= int(s)
            remains = block_size % dim1
            if remains != 0:
                block_size += dim1 - remains
        split_count = int(math.ceil(var_numel / float(block_size)))
        for block_id in range(split_count):
            curr_block_size = min(block_size,
                                  var_numel - (block_id * block_size))
            blocks.append(VarBlock(var.name, block_id, curr_block_size))
    return blocks


class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False

    # -- public API (reference :540) --------------------------------------
    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint="127.0.0.1:6174"):
        self.origin_program = program or framework.default_main_program()
        self.startup_program = (startup_program
                                or framework.default_startup_program())
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self.pserver_endpoints = (pservers.split(",")
                                  if isinstance(pservers, str) else
                                  list(pservers))

        if self.config.mode == "nccl2":
            # collective mode: grads allreduced, no PS machinery
            from ..parallel.transpiler import insert_allreduce_ops

            insert_allreduce_ops(self.origin_program, trainers)
            self._transpiled = True
            return

        block = self.origin_program.global_block()
        eps_all = self.pserver_endpoints

        # distributed sparse tables (pslib path,
        # distributed_lookup_table_op.cc): embedding(is_distributed=True)
        # tables are ROW-SLICED across pservers; their lookup becomes a
        # sparse pull, their grad a sparse push, and their optimizer op
        # moves server-side
        self.dist_tables: Dict[str, dict] = {}
        for op in block.ops:
            if op.type in ("lookup_table", "lookup_table_v2") \
                    and op.attrs.get("is_distributed"):
                w = op.input("W")[0]
                v = block._find_var_recursive(w)
                rows = int(v.shape[0])
                per = int(math.ceil(rows / float(len(eps_all))))
                starts, counts = [], []
                for k in range(len(eps_all)):
                    s = min(k * per, rows)
                    starts.append(s)
                    counts.append(min(per, rows - s))
                self.dist_tables[w] = {
                    "dim": int(v.shape[1]),
                    "dtype": getattr(v, "dtype", "float32") or "float32",
                    "starts": starts, "counts": counts,
                    "squeeze": op.type == "lookup_table",
                    "padding_idx": int(op.attrs.get("padding_idx", -1)),
                }

        # param/grad pairs from optimizer ops; drop the optimizer ops —
        # updates happen on the pservers. Distributed tables are NOT in
        # the dense send/recv set (their updates ride the sparse push).
        params_grads = []
        opt_ops = []
        self._table_opt_ops: Dict[str, object] = {}
        for op in block.ops:
            if op.type in OPTIMIZER_OP_TYPES:
                p = op.input("Param")[0]
                g = op.input("Grad")[0]
                if p in self.dist_tables:
                    self._table_opt_ops[p] = op
                    self.dist_tables[p]["grad"] = g
                    continue
                opt_ops.append(op)
                params_grads.append((p, g))
        self.params_grads = params_grads
        self._opt_ops = opt_ops

        if self.dist_tables:
            self._rewrite_dist_table_ops(block, eps_all)
            # the trainer never touches the table itself (pull/push only)
            # — initializing the FULL table on every trainer would OOM at
            # exactly the giant-vocab scale this path exists for. The
            # init ops move aside for get_startup_program, which copies
            # them (slice-shaped) into each SERVER's startup.
            sblk = self.startup_program.global_block()
            self._table_init_ops = [
                op for op in sblk.ops
                if any(o in self.dist_tables for o in op.output_arg_names)
            ]
            moved = set(id(op) for op in self._table_init_ops)
            sblk.ops = [op for op in sblk.ops if id(op) not in moved]

        # dense block-slicing (reference :95 wired into :540): a large
        # dense param is split into row blocks spread over pservers —
        # the trainer splits its grad, sends each block to its server,
        # and concats the recv'd param blocks back; each server runs
        # the optimizer on just its block
        eps = self.pserver_endpoints
        self.dense_blocks: Dict[str, List[dict]] = {}
        self._block_origin: Dict[str, tuple] = {}
        if self.config.slice_var_up and len(eps) > 1:
            for (p, g) in params_grads:
                v = block._find_var_recursive(p)
                if v is None or not v.shape:
                    continue
                vb = slice_variable([v], len(eps),
                                    self.config.min_block_size)
                if len(vb) <= 1:
                    continue
                dim1 = 1
                for s in v.shape[1:]:
                    dim1 *= int(s)
                rows = [b.size // max(dim1, 1) for b in vb]
                entries = []
                for k, r in enumerate(rows):
                    pb = "%s.block%d" % (p, k)
                    gb = "%s.block%d" % (g, k)
                    entries.append({"pname": pb, "gname": gb,
                                    "rows": r, "bidx": k,
                                    "origin_grad": g})
                    self._block_origin[pb] = (p, r, k)
                    self._block_origin[gb] = (g, r, k)
                self.dense_blocks[p] = entries

        # round-robin placement units: whole params AND blocks share
        # one rolling counter (RoundRobin dispatcher)
        self.param_to_ep: Dict[str, str] = {}
        self.grad_to_ep: Dict[str, str] = {}
        unit = 0
        for (p, g) in params_grads:
            if p in self.dense_blocks:
                for e in self.dense_blocks[p]:
                    e["ep"] = eps[unit % len(eps)]
                    unit += 1
            else:
                self.param_to_ep[p] = eps[unit % len(eps)]
                self.grad_to_ep[g] = eps[unit % len(eps)]
                unit += 1

        new_ops = [op for op in block.ops if op.type not in OPTIMIZER_OP_TYPES]

        def _append(op_type, ins, outs, attrs):
            op = framework.Operator(block, op_type, ins, outs, attrs)
            op._id = self.origin_program._next_op_id()
            new_ops.append(op)

        # block vars on the trainer (grad splits + recv'd param blocks)
        for p, entries in self.dense_blocks.items():
            v = block._find_var_recursive(p)
            tail = list(v.shape[1:])
            g = entries[0]["origin_grad"]
            for e in entries:
                block.create_var(name=e["pname"],
                                 shape=[e["rows"]] + tail, dtype=v.dtype)
                block.create_var(name=e["gname"],
                                 shape=[e["rows"]] + tail, dtype=v.dtype)
            _append("split", {"X": [g]},
                    {"Out": [e["gname"] for e in entries]},
                    {"sections": [e["rows"] for e in entries],
                     "axis": 0})

        # send grads -> barrier -> recv params -> barrier (sync mode)
        for p, g in params_grads:
            if p in self.dense_blocks:
                for e in self.dense_blocks[p]:
                    _append("send", {"X": [e["gname"]]}, {"Out": []},
                            {"epmap": [e["ep"]], "sync_mode": sync_mode,
                             "table_name": e["gname"]})
            else:
                _append("send", {"X": [g]}, {"Out": []},
                        {"epmap": [self.grad_to_ep[g]],
                         "sync_mode": sync_mode, "table_name": g})
        if sync_mode:
            _append("send_barrier", {}, {},
                    {"endpoints": eps, "trainer_id": trainer_id})
        for p, g in params_grads:
            if p in self.dense_blocks:
                for e in self.dense_blocks[p]:
                    _append("recv", {}, {"Out": [e["pname"]]},
                            {"epmap": [e["ep"]],
                             "table_name": e["pname"]})
                _append("concat",
                        {"X": [e["pname"]
                               for e in self.dense_blocks[p]]},
                        {"Out": [p]}, {"axis": 0})
            else:
                _append("recv", {}, {"Out": [p]},
                        {"epmap": [self.param_to_ep[p]],
                         "table_name": p})
        if sync_mode:
            _append("fetch_barrier", {}, {},
                    {"endpoints": eps, "trainer_id": trainer_id})
        block.ops = new_ops
        self._transpiled = True

    def _rewrite_dist_table_ops(self, block, eps):
        """Swap each distributed table's lookup for a sparse pull, its
        grad op for a sparse push, and drop its trainer-side optimizer
        op (the update happens on the hosting pservers)."""
        new_ops = []
        for op in block.ops:
            if op.type in ("lookup_table", "lookup_table_v2") \
                    and op.input("W")[0] in self.dist_tables:
                w = op.input("W")[0]
                t = self.dist_tables[w]
                nop = framework.Operator(
                    block, "distributed_lookup_table",
                    {"Ids": [op.input("Ids")[0]]},
                    {"Outputs": [op.output("Out")[0]]},
                    {"table_name": w, "endpoints": list(eps),
                     "row_starts": t["starts"], "row_counts": t["counts"],
                     "embed_dim": t["dim"], "squeeze_last": t["squeeze"],
                     "padding_idx": t["padding_idx"],
                     "dtype": str(t.get("dtype", "float32"))})
                nop._id = self.origin_program._next_op_id()
                new_ops.append(nop)
                continue
            if op.type in ("lookup_table_grad", "lookup_table_v2_grad",
                           "lookup_table_sparse_grad") \
                    and op.input("W") \
                    and op.input("W")[0] in self.dist_tables:
                w = op.input("W")[0]
                t = self.dist_tables[w]
                nop = framework.Operator(
                    block, "distributed_push_sparse",
                    {"Ids": [op.input("Ids")[0]],
                     "OutGrad": [op.input("Out@GRAD")[0]]},
                    {},
                    {"table_name": w, "grad_name": t.get("grad",
                                                         w + "@GRAD"),
                     "endpoints": list(eps),
                     "row_starts": t["starts"], "row_counts": t["counts"],
                     "squeeze_last": t["squeeze"],
                     "padding_idx": t["padding_idx"]})
                nop._id = self.origin_program._next_op_id()
                new_ops.append(nop)
                continue
            if op.type in OPTIMIZER_OP_TYPES \
                    and op.input("Param")[0] in self.dist_tables:
                continue  # applied server-side per push
            if op.type == "sum" and op.output("Out") \
                    and any(op.output("Out")[0] == t.get("grad")
                            for t in self.dist_tables.values()):
                # a shared table looked up N times sums N grad partials;
                # each partial became its own sparse push, so the sum
                # (whose inputs no longer exist) goes too
                continue
            new_ops.append(op)
        block.ops = new_ops

    def get_trainer_program(self, wait_port=True):
        if not self._transpiled:
            raise RuntimeError("transpile() first")
        return self.origin_program

    def get_pserver_program(self, endpoint):
        """Server program for one endpoint (reference :1146): one
        listen_and_serv op whose sub-blocks run each hosted param's
        optimizer op against incoming grads."""
        if not self._transpiled:
            raise RuntimeError("transpile() first")
        pserver_program = framework.Program()
        pblock = pserver_program.global_block()
        hosted = [(p, g) for (p, g) in self.params_grads
                  if p not in self.dense_blocks
                  and self.param_to_ep[p] == endpoint]
        origin_block = self.origin_program.global_block()
        opt_blocks = []
        grad_to_block_id = []

        # dense row-blocks hosted here: the optimizer sub-block runs on
        # the BLOCK (param/grad/accumulators all block-shaped)
        for p, entries in self.dense_blocks.items():
            g = entries[0]["origin_grad"]
            pv = origin_block._find_var_recursive(p)
            tail = list(pv.shape[1:])
            full_rows = int(pv.shape[0])
            for e in entries:
                if e["ep"] != endpoint:
                    continue
                sfx = ".block%d" % e["bidx"]
                pblock.create_var(name=e["pname"],
                                  shape=[e["rows"]] + tail,
                                  dtype=pv.dtype, persistable=True)
                pblock.create_var(name=e["gname"],
                                  shape=[e["rows"]] + tail,
                                  dtype=pv.dtype)
                sub = pserver_program._create_block()
                for op in self._opt_ops:
                    if op.input("Param")[0] != p:
                        continue

                    def _map(names):
                        out = []
                        for n in names:
                            if n == p:
                                out.append(e["pname"])
                            elif n == g:
                                out.append(e["gname"])
                            else:
                                v = origin_block._find_var_recursive(n)
                                if (v is not None and v.shape
                                        and tuple(v.shape)
                                        and int(v.shape[0]) == full_rows
                                        and list(v.shape[1:]) == tail):
                                    # full-shaped accumulator
                                    # (velocity/moment): block slice
                                    bn = n + sfx
                                    if not pblock.has_var_local(bn):
                                        pblock.create_var(
                                            name=bn,
                                            shape=[e["rows"]] + tail,
                                            dtype=v.dtype,
                                            persistable=True)
                                    self._block_origin.setdefault(
                                        bn, (n, e["rows"], e["bidx"]))
                                    out.append(bn)
                                else:
                                    if v is not None and \
                                            not pblock.has_var_local(n):
                                        pblock.create_var(
                                            name=n, shape=v.shape,
                                            dtype=v.dtype,
                                            persistable=v.persistable)
                                    out.append(n)
                        return out

                    nop = framework.Operator(
                        sub, op.type,
                        {k: _map(vv) for k, vv in op.inputs.items()},
                        {k: _map(vv) for k, vv in op.outputs.items()},
                        dict(op.attrs))
                    nop._id = pserver_program._next_op_id()
                    sub.ops.append(nop)
                pserver_program._rollback()
                opt_blocks.append(sub)
                grad_to_block_id.append("%s:%d" % (e["gname"], sub.idx))

        for p, g in hosted:
            pv = origin_block._find_var_recursive(p)
            pblock.create_var(name=p, shape=pv.shape, dtype=pv.dtype,
                              persistable=True)
            gv = origin_block._find_var_recursive(g)
            pblock.create_var(name=g, shape=None if gv is None else gv.shape,
                              dtype="float32" if gv is None else gv.dtype)
            sub = pserver_program._create_block()
            for op in self._opt_ops:
                if op.input("Param")[0] != p:
                    continue
                # copy the optimizer op (and its aux vars) into the sub
                for name in op.input_arg_names:
                    v = origin_block._find_var_recursive(name)
                    if v is not None and not pblock.has_var_local(name):
                        pblock.create_var(name=name, shape=v.shape,
                                          dtype=v.dtype,
                                          persistable=v.persistable)
                nop = framework.Operator(
                    sub, op.type,
                    {k: list(vv) for k, vv in op.inputs.items()},
                    {k: list(vv) for k, vv in op.outputs.items()},
                    dict(op.attrs))
                nop._id = pserver_program._next_op_id()
                sub.ops.append(nop)
            pserver_program._rollback()
            opt_blocks.append(sub)
            grad_to_block_id.append("%s:%d" % (g, sub.idx))

        # distributed sparse-table slices hosted here: the var holds
        # THIS endpoint's row block [count, dim]; the sparse push writes
        # a SelectedRows grad (LOCAL rows) and runs the optimizer
        # sub-block, whose kernels take the sparse path
        ep_idx = self.pserver_endpoints.index(endpoint)
        for w, t in getattr(self, "dist_tables", {}).items():
            count = t["counts"][ep_idx]
            if count <= 0:
                continue
            pblock.create_var(name=w, shape=[count, t["dim"]],
                              dtype=t.get("dtype", "float32"),
                              persistable=True)
            gname = t.get("grad", w + "@GRAD")
            pblock.create_var(name=gname, shape=None,
                              dtype=t.get("dtype", "float32"))
            opt = getattr(self, "_table_opt_ops", {}).get(w)
            sub = pserver_program._create_block()
            if opt is not None:
                for name in opt.input_arg_names:
                    v = origin_block._find_var_recursive(name)
                    if v is not None and not pblock.has_var_local(name):
                        shape = v.shape
                        if name not in (w, gname) and shape is not None \
                                and tuple(shape) and \
                                tuple(shape)[0] == t["starts"][-1] \
                                + t["counts"][-1]:
                            # optimizer accumulator shaped like the full
                            # table (momentum velocity): host the slice
                            shape = [count] + list(shape[1:])
                        pblock.create_var(name=name, shape=shape,
                                          dtype=v.dtype,
                                          persistable=v.persistable)
                nop = framework.Operator(
                    sub, opt.type,
                    {k: list(vv) for k, vv in opt.inputs.items()},
                    {k: list(vv) for k, vv in opt.outputs.items()},
                    dict(opt.attrs))
                nop._id = pserver_program._next_op_id()
                sub.ops.append(nop)
            pserver_program._rollback()
            opt_blocks.append(sub)
            grad_to_block_id.append("%s:%d" % (gname, sub.idx))

        op = framework.Operator(
            pblock, "listen_and_serv", {"X": []}, {},
            {"endpoint": endpoint,
             "optimize_blocks": opt_blocks,
             "grad_to_block_id": grad_to_block_id,
             "sync_mode": self.sync_mode,
             "Fanin": self.trainer_num})
        op._id = pserver_program._next_op_id()
        pblock.ops.append(op)
        return pserver_program

    def get_startup_program(self, endpoint, pserver_program=None,
                            startup_program=None):
        """Startup program initializing everything the endpoint's server
        program references (params, optimizer accumulators, lr var)."""
        sp = framework.Program()
        blk = sp.global_block()
        src = (startup_program or self.startup_program).global_block()
        if pserver_program is not None:
            hosted = set()
            for b in pserver_program.blocks:
                for op in b.ops:
                    hosted.update(op.input_arg_names)
                    hosted.update(op.output_arg_names)
        else:
            hosted = {p for (p, g) in self.params_grads
                      if self.param_to_ep.get(p) == endpoint}
            hosted |= {e["pname"]
                       for entries in getattr(self, "dense_blocks",
                                              {}).values()
                       for e in entries if e["ep"] == endpoint}
        # distributed-table slices: this endpoint initializes only ITS
        # row block, so the copied init op's shape attr is overridden
        ep_idx = (self.pserver_endpoints.index(endpoint)
                  if endpoint in self.pserver_endpoints else -1)
        slice_shapes = {}
        if ep_idx >= 0:
            for w, t in getattr(self, "dist_tables", {}).items():
                count = t["counts"][ep_idx]
                if count > 0:
                    slice_shapes[w] = [count, t["dim"]]
                    full = t["starts"][-1] + t["counts"][-1]
                    opt = getattr(self, "_table_opt_ops", {}).get(w)
                    if opt is not None:
                        for name in opt.input_arg_names:
                            v = src._find_var_recursive(name)
                            if (v is not None and name != w
                                    and v.shape and tuple(v.shape)
                                    and tuple(v.shape)[0] == full):
                                slice_shapes[name] = \
                                    [count] + list(v.shape[1:])
        # dense row-blocks hosted here: each hosted block name maps
        # back to its origin var (_block_origin) so the origin's init
        # op is cloned once per block, outputs renamed + shape attr
        # overridden to the block shape. (Random inits are drawn
        # per-block — distribution-equivalent to slicing one draw.)
        origin_to_blocks: Dict[str, List[str]] = {}
        for bn, (orig, rows, k) in getattr(self, "_block_origin",
                                           {}).items():
            if bn in hosted:
                origin_to_blocks.setdefault(orig, []).append(bn)

        for op in list(src.ops) + list(getattr(self, "_table_init_ops",
                                               [])):
            outs = op.output_arg_names
            if any(o in hosted for o in outs):
                attrs = dict(op.attrs)
                for name in outs:
                    v = src._find_var_recursive(name)
                    shape = slice_shapes.get(name,
                                             v.shape if v is not None
                                             else None)
                    if v is not None and not blk.has_var_local(name):
                        blk.create_var(name=name, shape=shape,
                                       dtype=v.dtype, persistable=True)
                    if name in slice_shapes and "shape" in attrs:
                        attrs["shape"] = list(slice_shapes[name])
                nop = framework.Operator(
                    blk, op.type,
                    {k: list(vv) for k, vv in op.inputs.items()},
                    {k: list(vv) for k, vv in op.outputs.items()},
                    attrs)
                nop._id = sp._next_op_id()
                blk.ops.append(nop)
                continue
            block_outs = [o for o in outs if o in origin_to_blocks]
            if not block_outs:
                continue
            orig = block_outs[0]
            v = src._find_var_recursive(orig)
            tail = list(v.shape[1:]) if v is not None and v.shape \
                else []
            for bn in origin_to_blocks[orig]:
                _, rows, _k = self._block_origin[bn]
                attrs = dict(op.attrs)
                if "shape" in attrs:
                    attrs["shape"] = [rows] + tail
                if attrs.get("seed"):
                    # a seeded random init must not draw IDENTICAL
                    # blocks; derive a distinct per-block seed
                    attrs["seed"] = int(attrs["seed"]) + 7919 * (_k + 1)
                if not blk.has_var_local(bn):
                    blk.create_var(name=bn, shape=[rows] + tail,
                                   dtype=v.dtype if v is not None
                                   else "float32", persistable=True)
                nop = framework.Operator(
                    blk, op.type,
                    {k: list(vv) for k, vv in op.inputs.items()},
                    {k: [bn if n == orig else n for n in vv]
                     for k, vv in op.outputs.items()},
                    attrs)
                nop._id = sp._next_op_id()
                blk.ops.append(nop)
        return sp
