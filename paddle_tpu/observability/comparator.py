"""Watched-metric threshold comparator — the ONE comparison
implementation behind both the CI perf gate (``tools/bench_diff.py``,
now a thin CLI over this module) and the canary protocol
(``observability/canary.py``).

Compares per-workload numbers between a BASE and a HEAD record and
flags every watched higher-is-better metric that regresses past a
relative threshold (or lower-is-better one that grows past it), with
absolute noise floors so sub-millisecond jitter on a near-zero base
never reads as a 150% "regression". Understands all three record
shapes this repo emits:

- ``bench.py`` output           (``{"extras": {workload: {...}}}``)
- ``bench.py --multichip``      (``{"configs": {config: {...}}}``)
- merged job ``metrics.json``   (``{"counters_total": {counter: value}}``
                                from observability.distributed.merge_job_dir)

Two API layers:

- the generator layer (``diff_records`` / ``diff_counters``) yields
  raw tuples — the historical bench_diff surface, kept verbatim so the
  CLI and existing callers stay byte-compatible;
- ``compare(base, head)`` wraps both generators into a ``Comparison``
  with a machine-readable verdict (``to_dict()`` is JSON-safe: the
  ``rel=inf`` zero-base rows serialize as the string ``"inf"``), which
  is what the canary audits and ``bench_diff --json`` emits.
"""
from __future__ import annotations

import json
import math
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = [
    "WATCHED", "ABS_NOISE_FLOOR", "COUNTER_WATCH_GROWS_BAD",
    "load", "workloads", "counter_totals",
    "diff_records", "diff_counters", "compare", "Comparison",
    "Objective",
]

# per-workload metrics worth gating; direction: +1 higher is better,
# -1 lower is better. The profile-block metrics (bench.py `profile`:
# flops-derived mfu_est, measured overlap_frac / critical_path_ms)
# resolve through the record's "profile" sub-dict — _lookup descends.
WATCHED = (
    ("images_per_sec", +1), ("tokens_per_sec", +1),
    ("examples_per_sec", +1), ("steps_per_sec", +1),
    ("tokens_or_images_per_sec", +1),
    ("step_ms", -1), ("collective_bytes", -1),
    ("mfu_est", +1), ("overlap_frac", +1),
    ("critical_path_ms", -1), ("exposed_collective_ms", -1),
    # ISSUE-14 single-chip phase attribution: the fused-optimizer /
    # fused-epilogue / async-feed wins must show up HERE (optimizer
    # phase time and critical-path feed cost strictly down) — and a
    # change that silently regresses them fails the gate
    ("feed_ms", -1), ("optimizer_ms", -1),
    # device-truth counterparts (XPlane-folded; observability/
    # device_trace.py) + the host-vs-device agreement ratio — a
    # silently-diverging host estimate (the number the bucket planner
    # steers by) regresses agreement even when every host metric holds
    ("device_overlap_frac", +1), ("device_critical_path_ms", -1),
    ("host_device_agreement", +1),
    # serving records (tools/serving_bench.py --out): closed-loop
    # throughput/latency, queue wait, real batch size, padding waste,
    # and the compile count the bucket ladder exists to bound — a
    # serving regression fails CI exactly like a training one
    ("rows_per_s", +1), ("p50_ms", -1), ("p99_ms", -1),
    ("serving_queue_ms_p50", -1), ("serving_queue_ms_p99", -1),
    ("serving_batch_size_mean", +1),
    ("serving_padding_waste_frac", -1), ("jit_traces", -1),
    # decode records (tools/serving_bench.py --decode): the SLO axes
    # of the continuous-batching tier — time-to-first-token and
    # inter-token latency — plus token throughput and its margin over
    # the static wait-for-all baseline measured in the SAME record. A
    # change that silently regresses per-token scheduling (TTFT/ITL
    # blowup, the continuous-vs-static win evaporating) fails CI here.
    # Raw tokens_per_s is in the record for humans but NOT watched:
    # it tracks box load run-over-run; the speedup ratio is measured
    # against a baseline run in the same process under the same load,
    # so it isolates the scheduling margin from the machine
    ("ttft_p50_ms", -1), ("ttft_p99_ms", -1),
    ("itl_p50_ms", -1), ("itl_p99_ms", -1),
    ("decode_speedup_vs_static", +1),
    ("kv_occupancy_frac", +1), ("preemptions", -1),
    # PS scale records (tools/ps_scale_bench.py): the per-round
    # blake2b bill under incremental chunk digesting, and the delta
    # wire bytes for the same touched-rows workload — a change that
    # silently regresses incremental digesting back toward full
    # re-hashing (or row slices back toward whole-table ships) fails
    # here run-over-run
    ("ps_digest_ms", -1), ("rounds_per_s", +1),
    ("repl_delta_bytes_per_round", -1),
    # crash-consistent round store (ISSUE 19): the per-round durable
    # frame must stay incremental (a regression back toward persisting
    # whole-table snapshots at every commit shows up as byte growth)
    # and the cold restore must stay cheap
    ("ckpt_delta_bytes_per_round", -1), ("ckpt_restore_ms", -1),
    # PS rebalance canaries (ISSUE 18): hot/cold per-shard row-load
    # ratio off the ps.row_heat counters. Counter-derived, so it is
    # deterministic under chaos injection where wall-clock throughput
    # is not — a migrate_range plan that fails to move the heat shows
    # up as a flat-or-rising skew and rolls back
    ("ps_row_load_skew", -1),
    # placement records (ISSUE 15, bench `placement` block): how well
    # the searched plan's PREDICTED step time tracks the measured one
    # (min/max ratio). A collapse means the cost model drifted off the
    # machine — the plan may still "work" while steering wrong.
    ("placement_agreement", +1),
    # objective-driven canaries (ISSUE 20): records that carry the
    # scalar objective score of an A/B decision gate it here too — a
    # change that silently degrades what the steering loop is
    # optimizing for fails CI even when every raw metric stays inside
    # its own flat threshold
    ("objective_score", +1),
)

# absolute noise floors for measured-timing metrics: a relative
# threshold alone turns sub-millisecond jitter on a near-zero base
# (0.2ms -> 0.5ms exposed time on a tiny CI smoke) into a +150%
# "regression". A delta must clear BOTH the relative threshold and
# this absolute floor to flag. Deterministic metrics have no floor.
ABS_NOISE_FLOOR = {
    "step_ms": 2.0, "critical_path_ms": 2.0,
    "exposed_collective_ms": 2.0, "overlap_frac": 0.1,
    # feed staging on a loaded box jitters at the ~ms level; the
    # optimizer phase is a measured re-execution slice
    "feed_ms": 1.0, "optimizer_ms": 2.0,
    "device_overlap_frac": 0.1, "device_critical_path_ms": 2.0,
    "host_device_agreement": 0.1,
    # serving latencies on a loaded CI box jitter in the single-digit
    # ms; batch size / padding waste depend on thread-arrival raggedness
    "p50_ms": 5.0, "p99_ms": 10.0,
    "serving_queue_ms_p50": 5.0, "serving_queue_ms_p99": 10.0,
    "serving_batch_size_mean": 1.0, "serving_padding_waste_frac": 0.15,
    # decode SLO axes jitter on a loaded CI box: TTFT includes queued
    # prefill chunks, ITL one padded decode step; occupancy depends on
    # stream arrival raggedness; a couple of preemptions either way is
    # arena-pressure noise, not a scheduling regression
    "ttft_p50_ms": 25.0, "ttft_p99_ms": 120.0,
    "itl_p50_ms": 3.0, "itl_p99_ms": 10.0,
    "decode_speedup_vs_static": 0.3, "kv_occupancy_frac": 0.15,
    "preemptions": 2.0,
    # hashing time on a loaded CI box jitters; byte counts do not
    "ps_digest_ms": 5.0,
    # a cold restore reads + verifies + splices files: fs-cache and
    # scheduler noise at the tens-of-ms level on a loaded CI box
    "ckpt_restore_ms": 20.0,
    # predicted-vs-measured ratio moves with CI-box timing noise
    "placement_agreement": 0.15,
    # the objective score inherits jitter from every weighted term
    "objective_score": 0.05,
}

# counter totals (metrics.json) where growth is a regression.
# ps.replication_bytes guards the ISSUE-8 delta-replication win: a
# code change that silently regresses the PS back to full-blob
# shipping shows up as growth of the byte counters (and of the
# mode=full series specifically) for the same drilled workload.
COUNTER_WATCH_GROWS_BAD = ("parallel.collective_bytes",
                           "parallel.collective_ops",
                           "executor.compile_fallbacks",
                           "ps.replication_bytes",
                           # live-migration traffic (ISSUE 18): a
                           # regression from row-range moves back to
                           # whole-var moves ships the cold 99% of the
                           # table — kind=var bytes grow where
                           # kind=range bytes should be
                           "ps.migration_bytes",
                           # durable round frames (ISSUE 19): growth
                           # of the bytes persisted per committed
                           # round (and of the mode=full series
                           # specifically) means the crash-consistent
                           # store regressed toward whole-table
                           # snapshots
                           "checkpoint.round_bytes",
                           # fused single-chip program op count
                           # (tools/sc_smoke.py): deterministic —
                           # growth means the fusion passes regressed
                           "sc.program_ops",
                           # the serving smokes must stay error-free:
                           # any growth (including 0 -> n) is a bug
                           # the functional assertions may have missed
                           "serving.errors", "serving.batch_errors",
                           "serving.stream_errors")


def load(path):
    with open(path) as f:
        doc = json.load(f)
    # the bench driver wraps bench.py's JSON line as {"parsed": {...}}
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return doc


def workloads(doc):
    """{workload: record} from any of the three supported shapes."""
    if "configs" in doc and isinstance(doc["configs"], dict):
        return dict(doc["configs"])  # multichip bench
    if "extras" in doc and isinstance(doc["extras"], dict):
        return {k: v for k, v in doc["extras"].items()
                if isinstance(v, dict) and not k.endswith("_error")}
    return {}


def counter_totals(doc):
    # merged job metrics.json (merge_job_dir) names the key
    # counters_total; accept the plain spelling too
    for key in ("counters_total", "totals"):
        if isinstance(doc.get(key), dict):
            return doc[key]
    if isinstance(doc.get("metrics_totals"), dict):
        return doc["metrics_totals"]  # multichip bench embeds them
    return {}


def diff_records(base, head, threshold
                 ) -> Iterator[Tuple[str, str, object, object,
                                     float, bool]]:
    """Yield (workload, metric, base, head, rel_delta, regressed)."""
    b_wl, h_wl = workloads(base), workloads(head)
    for name in sorted(set(b_wl) & set(h_wl)):
        b, h = b_wl[name], h_wl[name]
        for metric, direction in WATCHED:
            bv, hv = _lookup(b, metric), _lookup(h, metric)
            if bv is None or hv is None:
                continue
            if not bv:
                # growth from a zero base has no relative delta: show
                # the row (rel=inf) but don't hard-fail — a single-chip
                # BASE vs multichip HEAD legitimately goes 0 -> N
                # collective bytes, and the watched counter totals
                # below still gate structural from-zero growth
                if not hv:
                    continue
                yield name, metric, bv, hv, float("inf"), False
                continue
            rel = (hv - bv) / abs(bv)
            regressed = (-direction * rel) > threshold and \
                abs(hv - bv) > ABS_NOISE_FLOOR.get(metric, 0.0)
            yield name, metric, bv, hv, rel, regressed
        # a SILENT placement-plan change between runs is a regression:
        # same workload, same knobs, different plan digest means the
        # search (or its report) drifted without anyone deciding it
        bd = _plan_digest(b)
        hd = _plan_digest(h)
        if bd and hd and bd != hd:
            yield (name, "placement.plan_digest", bd[:12], hd[:12],
                   float("inf"), True)


def _plan_digest(rec):
    p = rec.get("placement")
    if isinstance(p, dict):
        d = p.get("plan_digest")
        if isinstance(d, str):
            return d
    return None


def _lookup(rec, metric):
    """A metric straight off the record, or from its profile block
    (mfu_est / overlap_frac / critical_path_ms), its diag (single-chip
    collective_bytes lives there), or its placement block
    (placement_agreement)."""
    v = rec.get(metric)
    if v is None and isinstance(rec.get("profile"), dict):
        v = rec["profile"].get(metric)
    if v is None and isinstance(rec.get("diag"), dict):
        v = rec["diag"].get(metric)
    if v is None and isinstance(rec.get("placement"), dict):
        v = rec["placement"].get(metric)
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    return None


def diff_counters(base, head, threshold
                  ) -> Iterator[Tuple[str, object, object, float, bool]]:
    b_t, h_t = counter_totals(base), counter_totals(head)
    for key in sorted(set(b_t) & set(h_t)):
        bv, hv = b_t[key], h_t[key]
        if not isinstance(bv, (int, float)):
            continue
        # exact key or its labeled series ("...{kind=...}") — a bare
        # prefix test would also catch parallel.collective_bytes_saved,
        # whose growth is an improvement
        grows_bad = any(key == w or key.startswith(w + "{")
                        for w in COUNTER_WATCH_GROWS_BAD)
        if not bv:
            if not hv:
                continue
            # zero -> nonzero growth of a watched counter is always a
            # regression (e.g. the first compile fallback appearing)
            yield key, bv, hv, float("inf"), grows_bad
            continue
        rel = (hv - bv) / abs(bv)
        yield key, bv, hv, rel, grows_bad and rel > threshold


class Objective:
    """A weighted multi-metric objective: per-metric weight, direction
    and absolute noise floor fold every compared row into ONE scalar
    score, with full per-term provenance for the audit trail.

    - ``weights``: {metric: weight > 0}. Weights are normalized (they
      only express RELATIVE importance): {"a": 2, "b": 2} scores
      identically to {"a": 1, "b": 1}.
    - ``directions``: per-metric override; required for metrics not in
      ``WATCHED``. An override that CONTRADICTS the watched direction
      is a configuration bug and raises (a rule author flipping
      ``step_ms`` to higher-is-better is never what they meant).
    - ``floors``: per-metric absolute noise floor override; defaults
      to ``ABS_NOISE_FLOOR``. A mean absolute delta at-or-under the
      floor contributes 0 to the score (noise is not signal in EITHER
      direction).
    - ``hard_floors``: {metric: absolute bound on the HEAD value} —
      SLO-style unconditional vetoes. For a lower-is-better metric the
      head may never EXCEED the bound (p99_ms may never pass 250ms);
      for higher-is-better it may never DROP BELOW it. A hard-floor
      violation vetoes promotion regardless of the score.

    The score is the weight-normalized sum over configured metrics of
    ``direction * mean(rel_delta)`` (positive = net improvement). A
    configured metric missing from the comparison contributes 0 but
    keeps its weight in the normalization and is flagged in its term —
    silently dropping a term would inflate the remaining ones.
    """

    __slots__ = ("weights", "directions", "floors", "hard_floors")

    def __init__(self, weights: Dict[str, float], *,
                 directions: Optional[Dict[str, int]] = None,
                 floors: Optional[Dict[str, float]] = None,
                 hard_floors: Optional[Dict[str, float]] = None):
        if not isinstance(weights, dict) or not weights:
            raise ValueError("Objective needs a non-empty weights dict")
        self.weights = {}
        for m, w in weights.items():
            w = float(w)
            if w <= 0:
                raise ValueError("objective weight for %r must be > 0, "
                                 "got %r" % (m, w))
            self.weights[m] = w
        self.hard_floors = {m: float(v)
                            for m, v in (hard_floors or {}).items()}
        watched = dict(WATCHED)
        directions = directions or {}
        self.directions = {}
        for m in sorted(set(self.weights) | set(self.hard_floors)):
            explicit = directions.get(m)
            if explicit is not None:
                explicit = int(explicit)
                if explicit not in (-1, 1):
                    raise ValueError("direction for %r must be +1 or "
                                     "-1, got %r" % (m, explicit))
                if m in watched and watched[m] != explicit:
                    raise ValueError(
                        "direction conflict for %r: objective says %+d "
                        "but WATCHED says %+d" % (m, explicit,
                                                  watched[m]))
                self.directions[m] = explicit
            elif m in watched:
                self.directions[m] = watched[m]
            else:
                raise ValueError(
                    "metric %r is not in WATCHED; an objective over it "
                    "needs an explicit direction" % (m,))
        self.floors = {}
        for m in self.weights:
            fl = (floors or {}).get(m)
            self.floors[m] = float(fl) if fl is not None \
                else float(ABS_NOISE_FLOOR.get(m, 0.0))

    def score_rows(self, rows: List[tuple]
                   ) -> Tuple[float, List[Dict]]:
        """Fold comparison rows into ``(score, terms)``. Each term
        carries its full provenance (weight, direction, mean relative
        delta, floor decision, contribution)."""
        wsum = sum(self.weights.values())
        by_metric: Dict[str, List[tuple]] = {}
        for row in rows:
            _wl, m, bv, hv, rel, _bad = row
            if m in self.weights and isinstance(rel, float) and \
                    math.isfinite(rel) and \
                    isinstance(bv, (int, float)) and \
                    isinstance(hv, (int, float)):
                by_metric.setdefault(m, []).append((float(bv),
                                                    float(hv),
                                                    float(rel)))
        score = 0.0
        terms = []
        for m in sorted(self.weights):
            weight = self.weights[m] / wsum
            got = by_metric.get(m)
            if not got:
                terms.append({"metric": m, "weight": weight,
                              "missing": True, "gain": 0.0,
                              "contribution": 0.0})
                continue
            rel = sum(r for _b, _h, r in got) / len(got)
            abs_delta = sum(abs(h - b) for b, h, _r in got) / len(got)
            gain = rel * self.directions[m]
            floored = abs_delta <= self.floors[m]
            contribution = 0.0 if floored else weight * gain
            score += contribution
            terms.append({
                "metric": m, "weight": weight,
                "direction": self.directions[m],
                "base": got[0][0], "head": got[0][1],
                "rel": rel, "gain": gain, "abs_delta": abs_delta,
                "floor": self.floors[m], "floored": floored,
                "contribution": contribution,
            })
        return score, terms

    def hard_floor_violations(self, rows: List[tuple]) -> List[Dict]:
        """Every (metric, workload) where the HEAD value sits past its
        SLO bound, regardless of relative movement."""
        out = []
        for _wl, m, _bv, hv, _rel, _bad in rows:
            bound = self.hard_floors.get(m)
            if bound is None or not isinstance(hv, (int, float)):
                continue
            d = self.directions[m]
            if (d < 0 and float(hv) > bound) or \
                    (d > 0 and float(hv) < bound):
                out.append({"metric": m, "workload": _wl,
                            "bound": bound, "head": float(hv)})
        return out

    def to_dict(self) -> Dict:
        return {"weights": dict(self.weights),
                "directions": dict(self.directions),
                "floors": dict(self.floors),
                "hard_floors": dict(self.hard_floors)}

    @classmethod
    def from_dict(cls, doc: Dict) -> "Objective":
        return cls(doc.get("weights") or {},
                   directions=doc.get("directions") or None,
                   floors=doc.get("floors") or None,
                   hard_floors=doc.get("hard_floors") or None)


class Comparison:
    """The structured result of ``compare``: every row both generators
    yielded, the regression count, and a one-word verdict the canary
    writes into its audit trail.

    With an ``objective`` attached, record-row regressions stop being
    individually fatal — they become weighted score terms, so a net
    win can carry one bounded regression. Three things still veto
    unconditionally: nothing comparable (``no_overlap``), a regressed
    WATCHED counter total (structural/error counters are never
    tradeable), and an objective ``hard_floor`` violation."""

    __slots__ = ("rows", "counter_rows", "threshold",
                 "counters_threshold", "objective")

    def __init__(self, rows, counter_rows, threshold,
                 counters_threshold, objective=None):
        self.rows: List[tuple] = rows
        self.counter_rows: List[tuple] = counter_rows
        self.threshold = threshold
        self.counters_threshold = counters_threshold
        self.objective: Optional[Objective] = objective

    @property
    def compared(self) -> int:
        return len(self.rows) + len(self.counter_rows)

    @property
    def regressions(self) -> int:
        return sum(1 for r in self.rows if r[-1]) + \
            sum(1 for r in self.counter_rows if r[-1])

    @property
    def regressed_metrics(self) -> List[str]:
        return [r[1] for r in self.rows if r[-1]] + \
            [r[0] for r in self.counter_rows if r[-1]]

    @property
    def counter_regressions(self) -> int:
        return sum(1 for r in self.counter_rows if r[-1])

    @property
    def objective_score(self) -> Optional[float]:
        """Weighted net score (positive = improvement); None when no
        objective is attached."""
        if self.objective is None:
            return None
        score, _terms = self.objective.score_rows(self.rows)
        return score

    def objective_result(self) -> Optional[Dict]:
        """Full objective evaluation: score, per-term provenance, and
        hard-floor violations. None without an objective."""
        if self.objective is None:
            return None
        score, terms = self.objective.score_rows(self.rows)
        violations = self.objective.hard_floor_violations(self.rows)
        return {"score": score, "terms": terms,
                "hard_floor_violations": violations,
                "ok": bool(self.compared > 0 and not violations and
                           self.counter_regressions == 0 and
                           score > 0)}

    @property
    def ok(self) -> bool:
        if self.objective is not None:
            res = self.objective_result()
            return bool(res and res["ok"])
        return self.compared > 0 and self.regressions == 0

    @property
    def verdict(self) -> str:
        """Flat mode: ``"ok"`` | ``"regression"`` | ``"no_overlap"``
        (nothing in common to compare — treated as NOT ok: a canary
        that measured nothing comparable must never promote).
        Objective mode: ``"objective_improved"`` |
        ``"objective_regression"`` | ``"hard_floor"`` |
        ``"counter_regression"`` | ``"no_overlap"``."""
        if not self.compared:
            return "no_overlap"
        if self.objective is not None:
            res = self.objective_result()
            if res["hard_floor_violations"]:
                return "hard_floor"
            if self.counter_regressions:
                return "counter_regression"
            return "objective_improved" if res["score"] > 0 \
                else "objective_regression"
        return "regression" if self.regressions else "ok"

    def improvement(self, metric: str) -> Optional[float]:
        """Signed relative improvement of ``metric`` across all
        workload rows (positive = better, direction-aware); None when
        the metric was not compared or sits on a zero base."""
        directions = dict(WATCHED)
        best = None
        for _wl, m, _bv, _hv, rel, _bad in self.rows:
            if m != metric or not math.isfinite(rel):
                continue
            gain = rel * directions.get(m, +1)
            best = gain if best is None else max(best, gain)
        return best

    def to_dict(self) -> Dict:
        """JSON-safe: non-finite relative deltas become ``"inf"``."""
        def _rel(rel):
            return rel if isinstance(rel, float) and math.isfinite(rel) \
                else "inf"

        doc = {
            "verdict": self.verdict,
            "ok": self.ok,
            "compared": self.compared,
            "regressions": self.regressions,
            "threshold": self.threshold,
            "counters_threshold": self.counters_threshold,
            "rows": [
                {"workload": wl, "metric": m, "base": bv, "head": hv,
                 "rel": _rel(rel), "regressed": bool(bad)}
                for wl, m, bv, hv, rel, bad in self.rows],
            "counter_rows": [
                {"counter": key, "base": bv, "head": hv,
                 "rel": _rel(rel), "regressed": bool(bad)}
                for key, bv, hv, rel, bad in self.counter_rows],
        }
        if self.objective is not None:
            # key present ONLY in objective mode — the default dict is
            # byte-identical with every pre-objective audit/CI record
            doc["objective"] = {
                "config": self.objective.to_dict(),
                "result": self.objective_result(),
            }
        return doc


def compare(base, head, threshold: float = 0.10,
            counters_threshold: float = 0.25,
            objective: Optional[Objective] = None) -> Comparison:
    """One call over both generators. ``base``/``head`` are already-
    parsed record documents (use ``load`` for files). With an
    ``objective``, ``ok``/``verdict`` switch to weighted-score
    semantics; the default (None) path is unchanged."""
    return Comparison(
        rows=list(diff_records(base, head, threshold)),
        counter_rows=list(diff_counters(base, head,
                                        counters_threshold)),
        threshold=threshold,
        counters_threshold=counters_threshold,
        objective=objective)
