"""Dynamic micro-batching: request queue + shape-bucketed assembly.

The throughput story of the whole serving subsystem lives here. XLA
earns its keep on one compiled dispatch over a LARGE batch; per-request
dispatch (batch of 1) leaves the MXU mostly idle. The batcher queues
requests as futures, lets a short window (``batch_timeout_ms``) collect
concurrent arrivals, and assembles them into one feed.

The second half of the story is the BUCKET LADDER. ``jax.jit`` traces
and compiles per input *shape*: serving raw observed batch sizes means
every distinct total (3 rows, then 5, then 7, ...) is a fresh multi-ms
XLA compile on the serving path — a latency cliff per novel size,
unbounded cache growth. Batches are instead padded up to a fixed ladder
of sizes (default powers of two up to ``max_batch_size``) so the jit
cache converges to ``len(ladder)`` entries that warmup can pre-compile
before traffic arrives. The price is padded rows (counted in
``serving.padding_waste`` so the ladder can be tuned against real
traffic); results are sliced back per request so callers never see the
padding.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import metrics as _m

__all__ = ["BatchPolicy", "DynamicBatcher", "PendingRequest",
           "default_ladder", "pick_bucket", "plan_ladder"]


def default_ladder(max_batch_size: int) -> Tuple[int, ...]:
    """Powers of two up to ``max_batch_size``, plus the max itself."""
    if max_batch_size < 1:
        raise ValueError("max_batch_size must be >= 1, got %r"
                         % max_batch_size)
    ladder = []
    b = 1
    while b < max_batch_size:
        ladder.append(b)
        b *= 2
    ladder.append(max_batch_size)
    return tuple(ladder)


def pick_bucket(ladder: Sequence[int], rows: int) -> int:
    """Smallest ladder entry >= rows."""
    for b in ladder:
        if b >= rows:
            return b
    raise ValueError("rows=%d exceeds ladder max %d" % (rows, ladder[-1]))


class BatchPolicy:
    """How micro-batches form: size cap, collection window, bucket
    ladder. ``batch_timeout_ms=0`` means dispatch whatever is queued the
    moment a worker is free (lowest latency, smallest batches)."""

    def __init__(self, max_batch_size: int = 8,
                 batch_timeout_ms: float = 2.0,
                 ladder: Optional[Sequence[int]] = None):
        self.max_batch_size = int(max_batch_size)
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self.batch_timeout_ms = float(batch_timeout_ms)
        if self.batch_timeout_ms < 0:
            raise ValueError("batch_timeout_ms must be >= 0")
        if ladder is None:
            self.ladder = default_ladder(self.max_batch_size)
        else:
            self.ladder = tuple(sorted(set(int(b) for b in ladder)))
            if not self.ladder or self.ladder[0] < 1:
                raise ValueError("ladder entries must be >= 1: %r"
                                 % (ladder,))
            if self.ladder[-1] < self.max_batch_size:
                raise ValueError(
                    "ladder max %d < max_batch_size %d (batches up to "
                    "the cap could not be bucketed)"
                    % (self.ladder[-1], self.max_batch_size))
            if self.ladder[-1] > self.max_batch_size:
                # a bucket above the cap can never be REQUIRED (rows
                # are capped), but a gap below it would silently pad
                # every batch past the documented per-dispatch limit
                raise ValueError(
                    "ladder entry %d exceeds max_batch_size %d"
                    % (self.ladder[-1], self.max_batch_size))

    def __repr__(self):
        return ("BatchPolicy(max_batch_size=%d, batch_timeout_ms=%g, "
                "ladder=%r)" % (self.max_batch_size, self.batch_timeout_ms,
                                self.ladder))


class PendingRequest:
    """One queued request: its feed, row count, completion future, and
    the timestamps/deadline the engine needs for queue_ms + expiry.
    ``trace_ctx`` carries the submitter's trace context across the
    queue — the dispatch happens on a worker thread, where the
    submitter's thread-local context is out of reach."""

    __slots__ = ("feed", "rows", "future", "deadline", "t_enqueue",
                 "trace_ctx")

    def __init__(self, feed: Dict[str, np.ndarray], rows: int,
                 deadline: Optional[float] = None, trace_ctx=None):
        self.feed = feed
        self.rows = int(rows)
        self.future: Future = Future()
        self.deadline = deadline          # time.monotonic() timestamp
        self.t_enqueue = time.monotonic()
        self.trace_ctx = trace_ctx


class DynamicBatcher:
    """Bounded FIFO of PendingRequests + batch formation + padding.

    Thread contract: any number of producer threads (``try_put``), any
    number of consumer workers (``next_batch``). Requests are never
    split across batches — a request's rows stay contiguous so its
    output slice is one view.
    """

    def __init__(self, policy: BatchPolicy, max_queue: int = 64):
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self.policy = policy
        self.max_queue = int(max_queue)
        self._queue: "deque[PendingRequest]" = deque()
        self._cond = threading.Condition()
        self._closed = False

    # -- producer side -----------------------------------------------------

    def try_put(self, pending: PendingRequest) -> bool:
        """Enqueue, or return False when the queue is at capacity (the
        engine turns that into ServerOverloaded — backpressure happens
        HERE, at admission, not by blocking the client thread)."""
        if pending.rows > self.policy.max_batch_size:
            # requests are never split, so this one could never be
            # scheduled — admitting it would pin the queue head and
            # spin every consumer forever
            raise ValueError(
                "request rows=%d exceed max_batch_size=%d"
                % (pending.rows, self.policy.max_batch_size))
        with self._cond:
            if self._closed or len(self._queue) >= self.max_queue:
                return False
            self._queue.append(pending)
            _m.set_queue_depth(len(self._queue))
            self._cond.notify()
            return True

    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def empty(self) -> bool:
        return self.depth() == 0

    def close(self) -> None:
        """Wake all waiting workers; subsequent try_put is refused."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- consumer side -----------------------------------------------------

    def next_batch(self, poll_timeout: float = 0.1
                   ) -> Optional[List[PendingRequest]]:
        """Block up to ``poll_timeout`` for the first request, then hold
        the batch open ``batch_timeout_ms`` (or until ``max_batch_size``
        rows) for more arrivals. Returns None on an idle poll."""
        cap = self.policy.max_batch_size
        with self._cond:
            if not self._queue and not self._closed:
                self._cond.wait(poll_timeout)
            if not self._queue:
                return None
            batch: List[PendingRequest] = []
            rows = 0
            window_end = time.monotonic() + self.policy.batch_timeout_ms / 1e3
            while True:
                while self._queue and rows + self._queue[0].rows <= cap:
                    p = self._queue.popleft()
                    batch.append(p)
                    rows += p.rows
                # full, or the next request wouldn't fit this batch
                if rows >= cap or self._queue:
                    break
                remaining = window_end - time.monotonic()
                if remaining <= 0 or self._closed:
                    break
                self._cond.wait(remaining)
            _m.set_queue_depth(len(self._queue))
            if self._queue:
                # leftover work: another worker can start on it now
                self._cond.notify()
        return batch

    # -- assembly ----------------------------------------------------------

    def assemble(self, batch: Sequence[PendingRequest]
                 ) -> Tuple[Dict[str, np.ndarray],
                            List[Tuple[int, int]], int, int]:
        """Concatenate the batch's feeds along axis 0 and pad to the
        bucket size. Returns (feed, [(offset, rows)] per request,
        bucket, padded_rows)."""
        rows = sum(p.rows for p in batch)
        bucket = pick_bucket(self.policy.ladder, rows)
        pad = bucket - rows
        feed: Dict[str, np.ndarray] = {}
        for name in batch[0].feed:
            parts = [np.asarray(p.feed[name]) for p in batch]
            arr = parts[0] if len(parts) == 1 else np.concatenate(parts, 0)
            if pad:
                # zero rows, not repeated real rows: repeats of a real
                # sample would change batch-statistic outputs, zeros are
                # sliced away before anyone sees them
                arr = np.concatenate(
                    [arr, np.zeros((pad,) + arr.shape[1:], arr.dtype)], 0)
            feed[name] = arr
        slices = []
        off = 0
        for p in batch:
            slices.append((off, p.rows))
            off += p.rows
        return feed, slices, bucket, pad

    @staticmethod
    def split_outputs(outputs: Dict[str, np.ndarray],
                      slices: Sequence[Tuple[int, int]],
                      batch_rows: int) -> List[Dict[str, np.ndarray]]:
        """Per-request output dicts: slice [offset, offset+rows) off
        every output's leading axis (drops the padding rows too).

        Every output must actually BE batch-major over ``batch_rows``
        (the padded feed's leading dim): a scalar or per-batch
        aggregate fetch (e.g. a mean) cannot be attributed to
        individual requests, and slicing it anyway would silently hand
        each caller the wrong elements — refuse loudly instead."""
        arrs = {}
        for name, arr in outputs.items():
            arr = np.asarray(arr)
            if arr.ndim == 0 or arr.shape[0] != batch_rows:
                raise ValueError(
                    "output %r has shape %s, not batch-major over the "
                    "%d dispatched rows — per-batch aggregates cannot "
                    "be unbatched; fetch per-row outputs when serving"
                    % (name, arr.shape, batch_rows))
            arrs[name] = arr
        out = []
        for off, rows in slices:
            out.append({name: arr[off:off + rows]
                        for name, arr in arrs.items()})
        return out


# -- ladder replanning (self-driving runtime) -------------------------------
#
# The default power-of-two ladder is shape-agnostic; real traffic is
# not. When measured padding waste rises (the steering daemon watches
# serving.padding_waste per dispatched batch), the ladder can be
# REPLANNED from the observed real-rows-per-batch distribution:
# quantile rungs put bucket boundaries where batches actually land, so
# the common sizes pad by little while the jit-cache bound
# (len(ladder) compiles, warmup pre-compilable) is preserved.

def plan_ladder(max_batch_size: int, batch_rows: Sequence[int],
                max_rungs: int = 6) -> Tuple[int, ...]:
    """A bucket ladder fitted to observed real-rows-per-batch:
    distinct quantile rungs (p25/p50/p75/p90/max observed) plus the
    ``max_batch_size`` cap, validated against the same rules
    ``BatchPolicy`` enforces. Falls back to ``default_ladder`` when no
    usable observations exist."""
    if max_batch_size < 1:
        raise ValueError("max_batch_size must be >= 1, got %r"
                         % max_batch_size)
    rows = sorted(min(max_batch_size, max(1, int(r)))
                  for r in batch_rows
                  if isinstance(r, (int, float)) and r > 0)
    if not rows:
        return default_ladder(max_batch_size)
    rungs = {max_batch_size}
    for q in (0.25, 0.5, 0.75, 0.9, 1.0):
        # ceil-style index: the rung must COVER the quantile's batches
        rungs.add(rows[min(len(rows) - 1,
                           int(np.ceil(q * (len(rows) - 1))))])
    ladder = tuple(sorted(rungs))
    if len(ladder) > max_rungs:
        # keep the cap and the largest rungs (the small end pads the
        # least absolute rows; the big end bounds compile count)
        ladder = tuple(sorted(rungs))[-max_rungs:]
        if ladder[-1] != max_batch_size:
            ladder = tuple(sorted(set(ladder) | {max_batch_size}))
    BatchPolicy(max_batch_size=max_batch_size, ladder=ladder)  # validate
    return ladder


def _steer_serving_ladder(report, max_batch_size=None,
                          batch_rows=None, max_rungs=6, **_ctx):
    """``report → plan`` steerer: the report is optional (this steerer
    keys on live traffic, not a step profile); the observed
    real-rows-per-batch sequence and the batch cap come from context.
    The returned plan IS the ladder tuple — ``BatchPolicy(ladder=...)``
    applies it."""
    if max_batch_size is None:
        raise ValueError("serving_ladder steerer needs "
                         "max_batch_size=<cap> in context")
    if not batch_rows:
        raise ValueError("serving_ladder steerer needs "
                         "batch_rows=<observed real rows per batch>")
    return plan_ladder(int(max_batch_size), batch_rows,
                       max_rungs=int(max_rungs))


from ..observability import steering as _steering  # noqa: E402

_steering.register_steerer(
    "serving_ladder", _steer_serving_ladder,
    "bucket ladder replanned from measured padding waste (ISSUE 16)")
