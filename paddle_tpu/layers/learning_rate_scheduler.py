"""In-graph learning-rate schedules.

Parity: /root/reference/python/paddle/fluid/layers/
learning_rate_scheduler.py (noam/exponential/natural_exp/inverse_time/
polynomial/piecewise/cosine decay + linear_lr_warmup). Each builds a
small op subgraph reading the auto-incremented global step counter, so
the schedule runs inside the compiled step like everything else.
"""
from __future__ import annotations

import math

from .. import framework
from ..layer_helper import LayerHelper
from . import ops as layers_ops
from . import tensor as layers_tensor

__all__ = [
    "autoincreased_step_counter",
    "noam_decay",
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "cosine_decay",
    "linear_lr_warmup",
]


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistable int64 counter incremented once per executed step
    (reference layers/tensor.py autoincreased_step_counter)."""
    helper = LayerHelper("global_step_counter")
    counter = layers_tensor.create_global_var(
        name=counter_name or framework.unique_name.generate(
            "@LR_DECAY_COUNTER@"),
        shape=[1], value=float(begin - step), dtype="int64",
        persistable=True)
    helper.append_op(
        "increment", inputs={"X": [counter]}, outputs={"Out": [counter]},
        attrs={"step": float(step)}, infer_shape=False)
    counter.stop_gradient = True
    return counter


def _step_f32():
    return layers_tensor.cast(autoincreased_step_counter(), "float32")


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    from .nn import elementwise_min
    from .ops import pow as pow_layer

    step = _step_f32()
    a = pow_layer(step, factor=-0.5)
    b = _scale(step, float(warmup_steps) ** -1.5)
    return _scale(elementwise_min(a, b),
                  float(learning_rate) * float(d_model) ** -0.5)


def _scale(x, s, bias=0.0):
    from .ops import scale

    return scale(x, scale=float(s), bias=float(bias))


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _step_f32()
    exponent = _scale(step, 1.0 / decay_steps)
    if staircase:
        from .ops import floor

        exponent = floor(exponent)
    return _scale(_pow_const(decay_rate, exponent), learning_rate)


def _pow_const(base, exponent):
    """base ** exponent with a scalar python base."""
    from .ops import exp, scale

    return exp(scale(exponent, scale=math.log(base)))


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _step_f32()
    exponent = _scale(step, 1.0 / decay_steps)
    if staircase:
        from .ops import floor

        exponent = floor(exponent)
    from .ops import exp

    return _scale(exp(_scale(exponent, -decay_rate)), learning_rate)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    step = _step_f32()
    ratio = _scale(step, 1.0 / decay_steps)
    if staircase:
        from .ops import floor

        ratio = floor(ratio)
    from .nn import elementwise_div

    denom = _scale(ratio, decay_rate, bias=1.0)
    one = layers_tensor.fill_constant([1], "float32", float(learning_rate))
    return elementwise_div(one, denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    step = _step_f32()
    from .nn import elementwise_div, elementwise_min

    if cycle:
        from .ops import ceil

        div = ceil(_scale(step, 1.0 / decay_steps))
        # avoid zero on step 0
        decay_steps_var = _scale(div, float(decay_steps))
        capped = step
    else:
        decay_steps_var = layers_tensor.fill_constant(
            [1], "float32", float(decay_steps))
        capped = elementwise_min(
            step, layers_tensor.fill_constant([1], "float32",
                                              float(decay_steps)))
    frac = elementwise_div(capped, decay_steps_var)
    one_minus = _scale(frac, -1.0, bias=1.0)
    poly = _pow_var(one_minus, power)
    return _scale(poly, learning_rate - end_learning_rate,
                  bias=end_learning_rate)


def _pow_var(x, p):
    from .ops import pow as pow_layer

    return pow_layer(x, factor=float(p))


def piecewise_decay(boundaries, values):
    """Stepwise LR via nested where-selects (reference builds
    conditional blocks; a select chain is the compile-friendly form)."""
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) must be len(boundaries)+1")
    step = _step_f32()
    from .tensor import fill_constant

    lr = fill_constant([1], "float32", float(values[-1]))
    # build from the last boundary backwards: step < b -> values[i]
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        cond = less_than_scalar(step, float(b))
        vconst = fill_constant([1], "float32", float(v))
        lr = _select(cond, vconst, lr)
    return lr


def less_than_scalar(x, v):
    from .control_flow import less_than
    from .tensor import fill_constant

    return less_than(x, fill_constant([1], x.dtype, float(v)))


def _select(cond, a, b):
    helper = LayerHelper("where", input=a)
    out = helper.create_variable_for_type_inference(a.dtype)
    helper.append_op("where", inputs={"Condition": [cond], "X": [a],
                                      "Y": [b]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def cosine_decay(learning_rate, step_each_epoch, epochs):
    from .ops import cos, floor

    step = _step_f32()
    epoch = floor(_scale(step, 1.0 / step_each_epoch))
    cosv = cos(_scale(epoch, math.pi / epochs))
    return _scale(_scale(cosv, 0.5, bias=0.5), learning_rate)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _step_f32()
    from .tensor import fill_constant

    warm = _scale(step, (end_lr - start_lr) / float(warmup_steps),
                  bias=start_lr)
    cond = less_than_scalar(step, float(warmup_steps))
    if isinstance(learning_rate, (float, int)):
        learning_rate = fill_constant([1], "float32",
                                      float(learning_rate))
    return _select(cond, warm, learning_rate)
