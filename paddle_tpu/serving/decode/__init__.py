"""Continuous-batching autoregressive decode: the serving tier's
second engine kind.

The one-shot tier (``serving.engine``) schedules per REQUEST: a batch
forms, runs once, returns. An autoregressive workload emits hundreds of
tokens per request, each token a separate model step over a growing
KV history — per-request scheduling would hold a batch slot hostage
for the LONGEST stream in the batch. This package schedules per
TOKEN STEP instead:

- ``kvcache``   — ``PagedKVCache``: every resident sequence's KV
  history in fixed-size blocks over one preallocated arena (opt-in
  bf16/int8 shared-scale storage), strict alloc/free accounting,
  eviction under pressure;
- ``model``     — ``TinyDecodeLM``: the seeded deterministic toy
  transformer the CPU-host tests and chaos drills decode with
  (bit-identical regeneration is what makes token-level failover
  exactly-once);
- ``scheduler`` — ``DecodeScheduler``: per-step plan — token-budgeted
  prefill chunks, ladder-bucketed decode batch, lowest-priority-first
  preemption;
- ``engine``    — ``DecodeEngine``: the step thread + streaming
  ``submit()`` front (``DecodeStream`` iterators, TTFT/ITL histograms,
  ``(request_id, token_index)`` resume, drain/stop lifecycle).

The HTTP front serves it as ``POST /generate`` (chunked token events);
``FleetRouter.generate()`` puts hedged-retry failover on top.
"""
from __future__ import annotations

from . import engine, kvcache, model, scheduler  # noqa: F401
from .engine import DecodeConfig, DecodeEngine, DecodeStream  # noqa: F401
from .kvcache import KVCacheConfig, KVCacheFull, PagedKVCache  # noqa: F401
from .model import TinyDecodeLM  # noqa: F401
from .scheduler import DecodeScheduler, SeqState, StepPlan  # noqa: F401

__all__ = [
    "DecodeConfig", "DecodeEngine", "DecodeStream",
    "KVCacheConfig", "KVCacheFull", "PagedKVCache",
    "TinyDecodeLM", "DecodeScheduler", "SeqState", "StepPlan",
]
