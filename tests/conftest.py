"""Test harness config: force a virtual 8-device CPU platform so mesh /
collective tests run anywhere (SURVEY.md §4: the reference has no fake
device backend and skips multi-GPU tests without hardware — we do better
via XLA host-platform device simulation)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
