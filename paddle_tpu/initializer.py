"""Parameter initializers.

Parity: /root/reference/python/paddle/fluid/initializer.py — each
initializer appends its init op (fill_constant / uniform_random /
gaussian_random / ...) to the *startup program* block holding the param.
"""
from __future__ import annotations

import math

import numpy as np

from .core import dtypes as _dt


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    def _seed(self, block):
        return getattr(block.program, "random_seed", 0)


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self._value = value

    def __call__(self, var, block):
        return block.append_op(
            "fill_constant",
            outputs={"Out": var},
            attrs={
                "shape": list(var.shape),
                "dtype": _dt.dtype_to_enum(var.dtype),
                "value": float(self._value),
            },
            infer_shape=False,
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self._low, self._high, self._seed_ = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            "uniform_random",
            outputs={"Out": var},
            attrs={
                "shape": list(var.shape),
                "min": self._low,
                "max": self._high,
                "seed": self._seed_ or self._seed(block),
                "dtype": _dt.dtype_to_enum(var.dtype),
            },
            infer_shape=False,
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed_ = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "gaussian_random",
            outputs={"Out": var},
            attrs={
                "shape": list(var.shape),
                "mean": self._mean,
                "std": self._std,
                "seed": self._seed_ or self._seed(block),
                "dtype": _dt.dtype_to_enum(var.dtype),
            },
            infer_shape=False,
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed_ = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "truncated_gaussian_random",
            outputs={"Out": var},
            attrs={
                "shape": list(var.shape),
                "mean": self._mean,
                "std": self._std,
                "seed": self._seed_ or self._seed(block),
                "dtype": _dt.dtype_to_enum(var.dtype),
            },
            infer_shape=False,
        )


def _fan_in_out(var):
    shape = var.shape
    if len(shape) < 2:
        return (shape[0] if shape else 1), (shape[0] if shape else 1)
    receptive = 1
    for d in shape[2:]:
        receptive *= d
    return shape[1] * receptive if len(shape) > 2 else shape[0], \
        shape[0] * receptive if len(shape) > 2 else shape[1]


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self._uniform, self._fan_in, self._fan_out, self._seed_ = (
            uniform, fan_in, fan_out, seed)

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fan_in = self._fan_in if self._fan_in is not None else fi
        fan_out = self._fan_out if self._fan_out is not None else fo
        if self._uniform:
            limit = math.sqrt(6.0 / (fan_in + fan_out))
            return UniformInitializer(-limit, limit, self._seed_)(var, block)
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return NormalInitializer(0.0, std, self._seed_)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self._uniform, self._fan_in, self._seed_ = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fan_in = self._fan_in if self._fan_in is not None else fi
        if self._uniform:
            limit = math.sqrt(6.0 / fan_in)
            return UniformInitializer(-limit, limit, self._seed_)(var, block)
        std = math.sqrt(2.0 / fan_in)
        return NormalInitializer(0.0, std, self._seed_)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self._value = np.asarray(value)

    def __call__(self, var, block):
        v = self._value
        dtype = _dt.to_numpy_dtype(var.dtype)
        if v.dtype.kind in "fc":
            key, vals = "fp32_values", [float(x) for x in v.reshape(-1)]
        else:
            key, vals = "int32_values", [int(x) for x in v.reshape(-1)]
        return block.append_op(
            "assign_value",
            outputs={"Out": var},
            attrs={
                "shape": list(v.shape),
                "dtype": _dt.dtype_to_enum(var.dtype),
                key: vals,
            },
            infer_shape=False,
        )


class BilinearInitializer(Initializer):
    """Bilinear upsample kernel init (for conv2d_transpose)."""

    def __call__(self, var, block):
        shape = var.shape
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        size = shape[2] * shape[3]
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            w = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            weight.reshape(-1)[i % size] = w
        weight = np.broadcast_to(weight.reshape(shape[0], shape[1], -1)[0, 0],
                                 (shape[0], shape[1], size)).reshape(shape)
        return NumpyArrayInitializer(weight)(var, block)


# Aliases used across the fluid API
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def force_init_on_cpu():
    return False
