"""Multi-process batch sharding reader.

Parity: /root/reference/python/paddle/fluid/contrib/reader/
distributed_reader.py — wraps a batch reader so each trainer process
consumes its 1/N slice, driven by PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM (the env contract paddle.distributed.launch sets).
"""
from __future__ import annotations

import os

__all__ = ["distributed_batch_reader"]


def distributed_batch_reader(batch_reader):
    trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if trainer_id >= trainers_num:
        raise ValueError(
            "PADDLE_TRAINER_ID (%d) must be < PADDLE_TRAINERS_NUM (%d)"
            % (trainer_id, trainers_num))

    def decorator():
        for i, batch in enumerate(batch_reader()):
            if i % trainers_num == trainer_id:
                yield batch

    return decorator
