"""Transformer encoder family (BERT-base config 3 / WMT config 4).

Parity model: the reference's transformer test configs
(/root/reference/python/paddle/fluid/tests/unittests/dist_transformer.py
and the fused multihead path operators/fused/multihead_matmul_op.cu).
Built from plain fluid.layers graph ops — under whole-program
compilation XLA fuses QKV projections and attention into MXU-shaped
matmuls, which is the TPU replacement for the reference's hand-fused
CUDA encoder kernels.
"""
from __future__ import annotations


from .. import layers


def _dense(x, size, act=None, name=None):
    return layers.fc(x, size=size, act=act, num_flatten_dims=2)


def _padding_bias(lengths, maxlen, batch, dtype="float32"):
    """Additive key-padding mask [B, 1, 1, maxlen]: 0 for visible keys,
    -1e9 past ``lengths``. Formula is 1e9*(vis-1) — bias BEFORE scale;
    scaling a -1e9 bias collapsed both cases to one float32 constant
    (a silent no-op mask, caught in round-5 review)."""
    vis = layers.cast(layers.sequence_mask(lengths, maxlen=int(maxlen)),
                      dtype)
    return layers.reshape(
        layers.scale(vis, scale=1e9, bias=-1.0, bias_after_scale=False),
        [batch, 1, 1, int(maxlen)])


def multi_head_attention(q_in, num_heads, d_model, dropout=0.0,
                         is_test=False, attn_bias=None, kv_in=None,
                         use_flash=None, kv_lengths=None, causal=False):
    """Attention over [B, T, D]: self-attention by default, or
    encoder-decoder cross attention when ``kv_in`` (the encoder output,
    [B, T_src, D]) is given. ``attn_bias`` is an additive mask
    broadcastable to [B, H, T_q, T_kv] (the reference's
    src_slf_attn_bias: 0 for visible positions, a large negative value
    for masked ones — padding or causal).

    ``kv_lengths`` ([B] int) is the KERNEL-SIDE padding mask: pass the
    per-example valid lengths instead of an additive bias and masked
    self-attention routes through the pallas flash kernels (padded key
    blocks are skipped entirely). ``causal=True`` composes with it
    (decoder self-attention). Use ``attn_bias`` only for masks that
    are not expressible as (causal x per-row-length).

    ``use_flash``: None = auto — the pallas flash path for unmasked
    INFERENCE at any length, for masked (kv_lengths) attention at any
    length, and for unmasked dropout-free TRAINING when T >= 2048:
    with tuned 512x1024 blocks the kernels measure 1.45x (S=2048) to
    2.32x (S=4096) FASTER than XLA's dense lowering on v5e fwd+bwd,
    and at S=8192/16384 they train in 68/190 ms/step where dense does
    not compile at all; at T <= 1024 the two are within variance, so
    short unmasked sequences keep the dense path (bench
    comparability). True/False force."""
    B, T, D = q_in.shape
    kv = q_in if kv_in is None else kv_in
    T_kv = kv.shape[1]
    head = d_model // num_heads
    q = _dense(q_in, d_model)
    k = _dense(kv, d_model)
    v = _dense(kv, d_model)

    def split_heads(x, t):
        x = layers.reshape(x, [B, t, num_heads, head])
        return layers.transpose(x, [0, 2, 1, 3])  # [B, H, t, head]

    q = split_heads(q, T)
    k, v = split_heads(k, T_kv), split_heads(v, T_kv)
    if use_flash is None:
        # self-attention only: the kernel grid assumes T_q == T_kv
        use_flash = attn_bias is None and kv_in is None and (
            is_test or dropout == 0) and (
            kv_lengths is not None or is_test or T >= 2048)
    elif use_flash:
        # honor the force or say why it cannot be honored — silently
        # falling back would invalidate kernel benchmarks/debugging
        if attn_bias is not None:
            raise ValueError(
                "use_flash=True: the flash kernel has no additive-mask "
                "support; express the mask as causal=True and/or "
                "kv_lengths (padding)")
        if dropout != 0 and not is_test:
            raise ValueError(
                "use_flash=True: attention dropout is not supported in "
                "the flash kernel; set dropout=0")
    if use_flash and attn_bias is None and (is_test or dropout == 0):
        # no additive mask -> the flash path (pallas kernels on TPU:
        # the T x T score matrix never hits HBM in EITHER direction —
        # the backward recomputes probabilities blockwise from the
        # saved logsumexp, so training memory is O(T·D)). Attention
        # dropout keeps the dense lowering (no dropout state in the
        # kernel). kv_lengths rides into the kernel as the padding
        # mask.
        from ..layer_helper import LayerHelper

        helper = LayerHelper("flash_attention", input=q_in)
        ctx = helper.create_variable_for_type_inference(q_in.dtype)
        ins = {"Q": [q], "K": [k], "V": [v]}
        if kv_lengths is not None:
            ins["Lengths"] = [kv_lengths]
        helper.append_op("flash_attention",
                         inputs=ins,
                         outputs={"Out": [ctx]},
                         attrs={"causal": bool(causal),
                                "scale": float(head) ** -0.5},
                         infer_shape=False)
        ctx.shape = (B, num_heads, T, head)
    else:
        q = layers.scale(q, scale=float(head) ** -0.5)
        scores = layers.matmul(q, k, transpose_y=True)  # [B, H, T, T]
        if attn_bias is not None:
            scores = layers.elementwise_add(scores, attn_bias)
        if kv_lengths is not None:
            # dense fallback of the kernel-side padding mask
            scores = layers.elementwise_add(
                scores, _padding_bias(kv_lengths, T_kv, B,
                                      scores.dtype))
        if causal:
            scores = layers.elementwise_add(
                scores, _causal_bias(T, dtype=scores.dtype))
        weights = layers.softmax(scores)
        if dropout:
            weights = layers.dropout(
                weights, dropout_prob=dropout, is_test=is_test,
                dropout_implementation="upscale_in_train")
        ctx = layers.matmul(weights, v)  # [B, H, T, head]
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [B, T, d_model])
    return _dense(ctx, d_model)


def encoder_layer(x, num_heads, d_model, d_ff, dropout=0.0, is_test=False,
                  attn_bias=None, kv_lengths=None):
    attn = multi_head_attention(x, num_heads, d_model, dropout, is_test,
                                attn_bias, kv_lengths=kv_lengths)
    if dropout:
        attn = layers.dropout(attn, dropout_prob=dropout, is_test=is_test,
                              dropout_implementation="upscale_in_train")
    x = layers.layer_norm(layers.elementwise_add(x, attn),
                          begin_norm_axis=2)
    ff = _dense(x, d_ff, act="gelu")
    ff = _dense(ff, d_model)
    if dropout:
        ff = layers.dropout(ff, dropout_prob=dropout, is_test=is_test,
                            dropout_implementation="upscale_in_train")
    return layers.layer_norm(layers.elementwise_add(x, ff),
                             begin_norm_axis=2)


def transformer_encoder(src_ids, pos_ids, vocab_size, max_len=512,
                        num_layers=12, num_heads=12, d_model=768,
                        d_ff=3072, dropout=0.0, is_test=False,
                        attn_bias=None, src_lengths=None):
    """BERT-style encoder over int64 [B, T] token + position ids.
    ``attn_bias`` masks padding (additive, broadcastable to
    [B, H, T, T]); ``src_lengths`` ([B] int) is the same mask in
    kernel form — padded self-attention routes the pallas flash
    kernels. Returns [B, T, d_model] encodings."""
    emb = layers.embedding(src_ids, size=[vocab_size, d_model])
    pos = layers.embedding(pos_ids, size=[max_len, d_model])
    x = layers.elementwise_add(emb, pos)
    x = layers.layer_norm(x, begin_norm_axis=2)
    for _ in range(num_layers):
        x = encoder_layer(x, num_heads, d_model, d_ff, dropout, is_test,
                          attn_bias, kv_lengths=src_lengths)
    return x


def bert_base_pretrain(src_ids, pos_ids, masked_positions, vocab_size=30522,
                       max_len=512, num_layers=12, num_heads=12,
                       d_model=768, d_ff=3072, dropout=0.0, is_test=False,
                       attn_bias=None):
    """Masked-LM head over the encoder: predictions at masked positions.
    masked_positions: int64 [B, M] token indices into T; ``attn_bias``
    masks padding as in transformer_encoder."""
    enc = transformer_encoder(src_ids, pos_ids, vocab_size, max_len,
                              num_layers, num_heads, d_model, d_ff,
                              dropout, is_test, attn_bias)
    B, T, D = enc.shape
    M = masked_positions.shape[1]
    flat = layers.reshape(enc, [B * T, D])
    # flat row index = b*T + position
    row_base = layers.reshape(
        layers.range(0, B * T, T, "int64"), [B, 1])
    gather_idx = layers.reshape(
        layers.elementwise_add(masked_positions,
                               layers.expand(row_base, [1, M])),
        [B * M])
    picked = layers.gather(flat, gather_idx)  # [B*M, D]
    logits = layers.fc(picked, size=vocab_size, num_flatten_dims=1)
    return layers.reshape(logits, [B, M, vocab_size])


def decoder_layer(y, enc, num_heads, d_model, d_ff, dropout=0.0,
                  is_test=False, self_bias=None, cross_bias=None,
                  tgt_lengths=None):
    """Post-LN decoder block: causal self-attention, encoder-decoder
    cross attention, FFN (reference dist_transformer.py decoder stack).
    With ``tgt_lengths``, causal+padding self-attention routes the
    flash kernels (pass self_bias=None then)."""
    sa = multi_head_attention(y, num_heads, d_model, dropout, is_test,
                              self_bias, kv_lengths=tgt_lengths,
                              causal=tgt_lengths is not None)
    y = layers.layer_norm(layers.elementwise_add(y, sa),
                          begin_norm_axis=2)
    ca = multi_head_attention(y, num_heads, d_model, dropout, is_test,
                              cross_bias, kv_in=enc)
    y = layers.layer_norm(layers.elementwise_add(y, ca),
                          begin_norm_axis=2)
    ff = _dense(y, d_ff, act="gelu")
    ff = _dense(ff, d_model)
    return layers.layer_norm(layers.elementwise_add(y, ff),
                             begin_norm_axis=2)


def _causal_bias(T, dtype="float32"):
    """Additive causal mask [1, 1, T, T]: 0 on/below the diagonal,
    -1e9 above (future positions)."""
    import numpy as np

    m = np.triu(np.full((T, T), -1e9, dtype=dtype), k=1)
    return layers.assign(m.reshape(1, 1, T, T))


def transformer_wmt(src_ids, src_pos, tgt_ids, tgt_pos, vocab_size,
                    max_len=256, num_layers=6, num_heads=8, d_model=512,
                    d_ff=2048, dropout=0.0, is_test=False,
                    src_lengths=None, tgt_lengths=None):
    """Transformer-base seq2seq (WMT north-star config 4 — reference
    tests/unittests/dist_transformer.py): encoder stack over source
    tokens, decoder stack with causal self-attention + cross attention,
    projection to target vocab logits [B, T_tgt, V].

    With ``src_lengths``/``tgt_lengths`` ([B] int), the PADDED
    encoder self-attention and the causal+padded decoder
    self-attention route the pallas flash kernels (the realistic
    masked-training case); cross attention (rectangular T_tgt x T_src)
    stays dense with an additive bias built from ``src_lengths``."""
    enc = transformer_encoder(src_ids, src_pos, vocab_size, max_len,
                              num_layers, num_heads, d_model, d_ff,
                              dropout, is_test,
                              src_lengths=src_lengths)
    emb = layers.embedding(tgt_ids, size=[vocab_size, d_model])
    pos = layers.embedding(tgt_pos, size=[max_len, d_model])
    y = layers.layer_norm(layers.elementwise_add(emb, pos),
                          begin_norm_axis=2)
    B, T, _ = y.shape
    self_bias = None if tgt_lengths is not None else _causal_bias(int(T))
    cross_bias = None
    if src_lengths is not None:
        cross_bias = _padding_bias(src_lengths, src_ids.shape[1], B)
    for _ in range(num_layers):
        y = decoder_layer(y, enc, num_heads, d_model, d_ff, dropout,
                          is_test, self_bias=self_bias,
                          cross_bias=cross_bias,
                          tgt_lengths=tgt_lengths)
    logits = layers.fc(y, size=vocab_size, num_flatten_dims=2)
    return logits
