// C inference API over the paddle_tpu predictor.
//
// Parity: /root/reference/paddle/fluid/inference/capi/ (pd_predictor.cc
// PD_NewPredictor / PD_PredictorRun / pd_config.cc) — a plain C ABI for
// embedding the predictor in C/C++/Go/R applications.
//
// TPU-native stance: the compute runtime is JAX/XLA, reachable through
// the Python layer, so this library embeds CPython (Py_Initialize) and
// drives paddle_tpu.inference.Predictor through the C API; the XLA
// compile/dispatch path underneath is identical to the Python one. The
// reference's C API wraps its C++ AnalysisPredictor the same way this
// wraps ours — one stable C ABI in front of the real runtime.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC capi.cc -o libptcapi.so \
//            $(python3-config --includes --ldflags --embed)
//
// ABI (mirrors pd_predictor.h naming):
//   PD_Predictor* PD_NewPredictor(const char* model_dir);
//   int  PD_PredictorRun(PD_Predictor*, const char* input_name,
//                        const float* data, const int64_t* shape,
//                        int ndims, float* out, int64_t out_capacity,
//                        int64_t* out_size);
//   void PD_DeletePredictor(PD_Predictor*);
//   const char* PD_GetLastError();

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

std::mutex g_mu;
std::string g_last_error;
bool g_py_inited = false;

void set_error(const std::string &msg) { g_last_error = msg; }

void fetch_py_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyObject *s = value ? PyObject_Str(value) : nullptr;
  const char *msg = s ? PyUnicode_AsUTF8(s) : nullptr;
  set_error(msg ? msg : "unknown python error");
  // PyUnicode_AsUTF8 can itself raise (unencodable str()); never leave
  // an exception pending past this point
  PyErr_Clear();
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

void ensure_python() {
  if (!g_py_inited) {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // release the GIL acquired by initialization, or every other
      // thread's PyGILState_Ensure would deadlock forever
      PyEval_SaveThread();
    }
    g_py_inited = true;
  }
}

}  // namespace

extern "C" {

struct PD_Predictor {
  PyObject *predictor;  // paddle_tpu.inference.Predictor
};

const char *PD_GetLastError() {
  // copy under the same mutex the writers hold; a thread-local buffer
  // keeps the returned pointer stable for the calling thread
  static thread_local std::string tl_error;
  std::lock_guard<std::mutex> lk(g_mu);
  tl_error = g_last_error;
  return tl_error.c_str();
}

PD_Predictor *PD_NewPredictor(const char *model_dir) {
  std::lock_guard<std::mutex> lk(g_mu);
  g_last_error.clear();
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  PD_Predictor *out = nullptr;
  PyObject *mod = PyImport_ImportModule("paddle_tpu.inference");
  if (!mod) {
    fetch_py_error();
    PyGILState_Release(gil);
    return nullptr;
  }
  PyObject *cfg_cls = PyObject_GetAttrString(mod, "AnalysisConfig");
  PyObject *pred_fn = PyObject_GetAttrString(mod, "create_paddle_predictor");
  PyObject *cfg = cfg_cls ? PyObject_CallFunction(cfg_cls, "s", model_dir)
                          : nullptr;
  PyObject *pred = (pred_fn && cfg)
                       ? PyObject_CallFunctionObjArgs(pred_fn, cfg, nullptr)
                       : nullptr;
  if (pred) {
    out = new PD_Predictor{pred};
  } else {
    fetch_py_error();
  }
  Py_XDECREF(cfg);
  Py_XDECREF(cfg_cls);
  Py_XDECREF(pred_fn);
  Py_XDECREF(mod);
  PyGILState_Release(gil);
  return out;
}

int PD_PredictorRun(PD_Predictor *p, const char *input_name,
                    const float *data, const int64_t *shape, int ndims,
                    float *out, int64_t out_capacity, int64_t *out_size) {
  // out_size must never be left uninitialized: callers that check it
  // before rc would otherwise read garbage on early-failure paths. It
  // carries the produced element count on success (and on the
  // buffer-too-small failure, so callers can resize); 0 otherwise.
  if (out_size) *out_size = 0;
  if (!p || !p->predictor) {
    std::lock_guard<std::mutex> lk(g_mu);
    set_error("null predictor");
    return -1;
  }
  std::lock_guard<std::mutex> lk(g_mu);
  g_last_error.clear();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  // build a numpy array via the buffer-free float list path (no numpy
  // C API dependency): numpy.frombuffer over a bytes object + reshape
  PyObject *np = PyImport_ImportModule("numpy");
  int64_t numel = 1;
  for (int i = 0; i < ndims; ++i) numel *= shape[i];
  PyObject *buf = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data), numel * sizeof(float));
  PyObject *arr = nullptr;
  if (np && buf) {
    PyObject *flat = PyObject_CallMethod(np, "frombuffer", "Os", buf,
                                         "float32");
    if (flat) {
      PyObject *shp = PyTuple_New(ndims);
      for (int i = 0; i < ndims; ++i)
        PyTuple_SetItem(shp, i, PyLong_FromLongLong(shape[i]));
      arr = PyObject_CallMethod(flat, "reshape", "O", shp);
      Py_DECREF(shp);
      Py_DECREF(flat);
    }
  }
  PyObject *result = nullptr;
  if (arr) {
    PyObject *feed = PyDict_New();
    PyDict_SetItemString(feed, input_name, arr);
    result = PyObject_CallMethod(p->predictor, "run", "O", feed);
    Py_DECREF(feed);
  }
  if (result && PyList_Check(result) && PyList_Size(result) > 0) {
    PyObject *first_t = PyList_GetItem(result, 0);  // borrowed PaddleTensor
    PyObject *first = PyObject_CallMethod(first_t, "as_ndarray", nullptr);
    PyObject *f32 = first ? PyObject_CallMethod(first, "astype", "s",
                                                "float32")
                          : nullptr;
    Py_XDECREF(first);
    PyObject *ravel = f32 ? PyObject_CallMethod(f32, "ravel", nullptr)
                          : nullptr;
    PyObject *bytes = ravel ? PyObject_CallMethod(ravel, "tobytes", nullptr)
                            : nullptr;
    if (bytes) {
      int64_t n = PyBytes_Size(bytes) / (int64_t)sizeof(float);
      if (out_size) *out_size = n;
      if (n <= out_capacity) {
        std::memcpy(out, PyBytes_AsString(bytes), n * sizeof(float));
        rc = 0;
      } else {
        set_error("output buffer too small");
      }
      Py_DECREF(bytes);
    }
    Py_XDECREF(ravel);
    Py_XDECREF(f32);
  }
  // a pending Python exception must always be drained before releasing
  // the GIL, whatever message is already recorded
  if (PyErr_Occurred()) {
    if (rc != 0 && g_last_error.empty()) {
      fetch_py_error();
    } else {
      PyErr_Clear();
    }
  } else if (rc != 0 && g_last_error.empty()) {
    set_error("run failed");
  }
  Py_XDECREF(result);
  Py_XDECREF(arr);
  Py_XDECREF(buf);
  Py_XDECREF(np);
  PyGILState_Release(gil);
  return rc;
}

void PD_DeletePredictor(PD_Predictor *p) {
  if (!p) return;
  std::lock_guard<std::mutex> lk(g_mu);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(p->predictor);
  PyGILState_Release(gil);
  delete p;
}

}  // extern "C"
