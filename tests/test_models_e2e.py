"""End-to-end model tests — the book contract.

Mirrors the reference's tests/book suite (train a classic model a few
iterations, assert convergence, round-trip save/load) and the
ParallelExecutor parity tests
(/root/reference/python/paddle/fluid/tests/book/test_recognize_digits.py:64,
 tests/unittests/test_parallel_executor_mnist.py,
 tests/unittests/test_imperative_mnist.py).
"""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import models


def _synth_mnist(rng, n):
    """Separable synthetic digits: class k lights up a distinct patch."""
    y = rng.randint(0, 10, (n, 1)).astype("int64")
    x = rng.rand(n, 1, 28, 28).astype("float32") * 0.1
    for i in range(n):
        k = int(y[i, 0])
        x[i, 0, 2 * k:2 * k + 3, 2 * k:2 * k + 3] += 1.0
    return x, y


def _snapshot_persistables(program, scope):
    out = {}
    blk = program.global_block()
    for name in blk.vars:
        v = blk._find_var_recursive(name)
        sv = scope.find_var(name)
        if v is not None and v.persistable and sv is not None \
                and sv.is_initialized():
            out[name] = np.asarray(sv.raw().array).copy()
    return out


def _restore_persistables(scope, snap):
    import jax.numpy as jnp

    for name, arr in snap.items():
        scope.var(name).get_tensor()._array = jnp.asarray(arr)


def _build_lenet_train(batch, lr=0.01):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data(name="img", shape=[batch, 1, 28, 28],
                         dtype="float32")
        label = fluid.data(name="label", shape=[batch, 1], dtype="int64")
        pred = models.lenet(img)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.AdamOptimizer(lr).minimize(loss)
    return main, startup, pred, loss


class TestLeNetStaticConvergence:
    def test_loss_decreases(self):
        B = 32
        main, startup, pred, loss = _build_lenet_train(B)
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            losses = []
            for i in range(40):
                x, y = _synth_mnist(rng, B)
                (l,) = exe.run(main, feed={"img": x, "label": y},
                               fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < 0.7 * losses[0], (losses[0], losses[-1])
        assert losses[-1] < 1.0, losses[-1]


class TestSaveLoadInference:
    def test_roundtrip(self):
        B = 16
        main, startup, pred, loss = _build_lenet_train(B)
        scope = fluid.Scope()
        rng = np.random.RandomState(1)
        with fluid.scope_guard(scope), tempfile.TemporaryDirectory() as d:
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            for i in range(5):
                x, y = _synth_mnist(rng, B)
                exe.run(main, feed={"img": x, "label": y}, fetch_list=[loss])
            x, y = _synth_mnist(rng, B)
            test_prog = main.clone(for_test=True)
            w_name = main.global_block().all_parameters[0].name
            w_before = np.asarray(scope.find_var(w_name).raw().array).copy()
            (ref,) = exe.run(test_prog, feed={"img": x, "label": y},
                             fetch_list=[pred])
            w_after = np.asarray(scope.find_var(w_name).raw().array)
            # for_test clone must not run backward/optimizer ops
            np.testing.assert_array_equal(w_before, w_after)
            fluid.io.save_inference_model(d, ["img"], [pred], exe,
                                          main_program=main)
            # fresh scope: the loaded model must be self-contained
            scope2 = fluid.Scope()
            with fluid.scope_guard(scope2):
                infer_prog, feed_names, fetch_targets = (
                    fluid.io.load_inference_model(d, exe))
                (out,) = exe.run(infer_prog, feed={feed_names[0]: x},
                                 fetch_list=fetch_targets)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)


class TestDygraphParity:
    def test_dygraph_lenet_trains(self):
        from paddle_tpu.dygraph import Conv2D, Linear, Pool2D, to_variable

        B = 32
        rng = np.random.RandomState(2)

        class LeNet(fluid.dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.c1 = Conv2D(1, 6, 5, act="relu")
                self.p1 = Pool2D(2, pool_type="max", pool_stride=2)
                self.c2 = Conv2D(6, 16, 5, act="relu")
                self.p2 = Pool2D(2, pool_type="max", pool_stride=2)
                self.f1 = Linear(256, 120, act="relu")
                self.f2 = Linear(120, 84, act="relu")
                self.f3 = Linear(84, 10, act="softmax")

            def forward(self, x):
                h = self.p2(self.c2(self.p1(self.c1(x))))
                h = fluid.layers.reshape(h, [h.shape[0], -1])
                return self.f3(self.f2(self.f1(h)))

        with fluid.dygraph.guard():
            model = LeNet()
            opt = fluid.optimizer.AdamOptimizer(
                1e-3, parameter_list=model.parameters())
            losses = []
            for i in range(30):
                x, y = _synth_mnist(rng, B)
                pred = model(to_variable(x))
                loss = fluid.layers.mean(
                    fluid.layers.cross_entropy(pred, to_variable(y)))
                loss.backward()
                opt.minimize(loss)
                model.clear_gradients()
                losses.append(float(np.asarray(loss.numpy()).ravel()[0]))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < 0.8 * losses[0], (losses[0], losses[-1])


class TestDataParallelParity:
    def test_8dev_loss_matches_single(self):
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 (virtual) devices")
        B = 32
        main, startup, pred, loss = _build_lenet_train(B, lr=0.05)
        rng = np.random.RandomState(3)
        x, y = _synth_mnist(rng, B)
        feed = {"img": x, "label": y}
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.TPUPlace())
            exe.run(startup)
            snap = _snapshot_persistables(main, scope)
            (l_single,) = exe.run(main, feed=feed, fetch_list=[loss])
            l_single = float(np.asarray(l_single).ravel()[0])
            _restore_persistables(scope, snap)
            compiled = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
            (l_dp,) = exe.run(compiled, feed=feed, fetch_list=[loss])
            l_dp = float(np.mean(np.asarray(l_dp)))
        assert abs(l_single - l_dp) < 1e-4, (l_single, l_dp)

    def test_multi_step_training_parity(self):
        """3 DP steps track 3 single-device steps from the SAME init —
        the test_dist_base loss-comparison contract (reference
        test_dist_base.py:506)."""
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 (virtual) devices")
        B = 32
        rng = np.random.RandomState(4)
        batches = [_synth_mnist(rng, B) for _ in range(3)]
        main, startup, pred, loss = _build_lenet_train(B, lr=0.01)

        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.TPUPlace())
            exe.run(startup)
            init = _snapshot_persistables(main, scope)
            single = []
            for x, y in batches:
                (l,) = exe.run(main, feed={"img": x, "label": y},
                               fetch_list=[loss])
                single.append(float(np.mean(np.asarray(l))))
            _restore_persistables(scope, init)
            compiled = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
            dp = []
            for x, y in batches:
                (l,) = exe.run(compiled, feed={"img": x, "label": y},
                               fetch_list=[loss])
                dp.append(float(np.mean(np.asarray(l))))
        np.testing.assert_allclose(single, dp, rtol=2e-3, atol=2e-4)


class TestTransformerModels:
    def test_tiny_bert_trains(self):
        from paddle_tpu import models

        B, T, M, V = 2, 16, 4, 50
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            src = fluid.data(name="src", shape=[B, T], dtype="int64")
            pos = fluid.data(name="pos", shape=[B, T], dtype="int64")
            mpos = fluid.data(name="mpos", shape=[B, M], dtype="int64")
            labels = fluid.data(name="labels", shape=[B, M, 1],
                                dtype="int64")
            logits = models.bert_base_pretrain(
                src, pos, mpos, vocab_size=V, max_len=T, num_layers=2,
                num_heads=4, d_model=32, d_ff=64)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(
                    fluid.layers.reshape(logits, [B * M, V]),
                    fluid.layers.reshape(labels, [B * M, 1])))
            fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
        rng = np.random.RandomState(0)
        feed = {"src": rng.randint(0, V, (B, T)).astype("int64"),
                "pos": np.tile(np.arange(T), (B, 1)).astype("int64"),
                "mpos": rng.randint(0, T, (B, M)).astype("int64"),
                "labels": rng.randint(0, V, (B, M, 1)).astype("int64")}
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            losses = []
            for i in range(10):
                (l,) = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_encoder_shapes(self):
        from paddle_tpu import models

        B, T = 2, 8
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            src = fluid.data(name="src", shape=[B, T], dtype="int64")
            pos = fluid.data(name="pos", shape=[B, T], dtype="int64")
            enc = models.transformer_encoder(
                src, pos, vocab_size=30, max_len=T, num_layers=1,
                num_heads=2, d_model=16, d_ff=32)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            (o,) = exe.run(main, feed={
                "src": np.zeros((B, T), "int64"),
                "pos": np.tile(np.arange(T), (B, 1)).astype("int64")},
                fetch_list=[enc])
        assert np.asarray(o).shape == (B, T, 16)


def test_transformer_wmt_seq2seq_trains():
    """North-star config 4: the encoder-decoder transformer (causal
    self-attention + cross attention) must train — loss decreases on a
    tiny copy task (reference dist_transformer.py contract)."""
    import paddle_tpu as fluid
    from paddle_tpu import models

    B, T, V = 4, 8, 20
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.data(name="src", shape=[B, T], dtype="int64")
        spos = fluid.data(name="spos", shape=[B, T], dtype="int64")
        tgt = fluid.data(name="tgt", shape=[B, T], dtype="int64")
        tpos = fluid.data(name="tpos", shape=[B, T], dtype="int64")
        lbl = fluid.data(name="lbl", shape=[B, T, 1], dtype="int64")
        logits = models.transformer_wmt(src, spos, tgt, tpos,
                                        vocab_size=V, max_len=T,
                                        num_layers=1, num_heads=2,
                                        d_model=16, d_ff=32)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(
                fluid.layers.reshape(logits, [B * T, V]),
                fluid.layers.reshape(lbl, [B * T, 1])))
        fluid.optimizer.AdamOptimizer(1e-2).minimize(loss)

    rng = np.random.RandomState(0)
    seq = rng.randint(0, V, (B, T)).astype("int64")
    pos = np.tile(np.arange(T), (B, 1)).astype("int64")
    # next-token labels (shifted by one): position t must predict
    # seq[t+1], which the causal decoder can only learn by READING it
    # from the encoder through cross attention — an unshifted copy
    # would collapse via the residual stream without exercising either
    lbl = np.roll(seq, -1, axis=1)
    feed = {"src": seq, "spos": pos, "tgt": seq, "tpos": pos,
            "lbl": lbl[..., None]}
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = []
        for _ in range(30):
            (l,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.ravel(l)[0]))
    assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_transformer_decoder_causality():
    """The decoder's self-attention must not see future positions: with
    identical src and two tgt sequences differing only at the LAST
    position, logits at earlier positions must match."""
    import paddle_tpu as fluid
    from paddle_tpu import models

    B, T, V = 1, 6, 12
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.data(name="src", shape=[B, T], dtype="int64")
        spos = fluid.data(name="spos", shape=[B, T], dtype="int64")
        tgt = fluid.data(name="tgt", shape=[B, T], dtype="int64")
        tpos = fluid.data(name="tpos", shape=[B, T], dtype="int64")
        logits = models.transformer_wmt(src, spos, tgt, tpos,
                                        vocab_size=V, max_len=T,
                                        num_layers=1, num_heads=2,
                                        d_model=16, d_ff=32,
                                        is_test=True)
    rng = np.random.RandomState(1)
    pos = np.tile(np.arange(T), (B, 1)).astype("int64")
    srcv = rng.randint(0, V, (B, T)).astype("int64")
    t1 = rng.randint(0, V, (B, T)).astype("int64")
    t2 = t1.copy()
    t2[0, -1] = (t1[0, -1] + 1) % V
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (a,) = exe.run(main, feed={"src": srcv, "spos": pos,
                                   "tgt": t1, "tpos": pos},
                       fetch_list=[logits])
        (b,) = exe.run(main, feed={"src": srcv, "spos": pos,
                                   "tgt": t2, "tpos": pos},
                       fetch_list=[logits])
    a, b = np.asarray(a), np.asarray(b)
    np.testing.assert_allclose(a[:, :-1], b[:, :-1], rtol=1e-5,
                               atol=1e-6)
    assert np.abs(a[:, -1] - b[:, -1]).max() > 1e-4


def test_resnet_nhwc_matches_nchw():
    """NHWC end-to-end (convs/pools/BN lower natively channels-last, no
    transposes) must match NCHW exactly: identical losses and updated
    params over two SGD steps from identical init."""
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import models

    B = 4
    rng = np.random.RandomState(0)
    img = rng.rand(B, 3, 16, 16).astype("float32")
    lab = rng.randint(0, 10, (B, 1)).astype("int64")

    out = {}
    for fmt in ("NCHW", "NHWC"):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            shape = [B, 3, 16, 16] if fmt == "NCHW" else [B, 16, 16, 3]
            x = fluid.data(name="x", shape=shape, dtype="float32")
            label = fluid.data(name="label", shape=[B, 1], dtype="int64")
            pred = models.resnet(x, class_dim=10, depth=18,
                                 data_format=fmt)
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, label))
            fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            # identical params positionally (layouts share OIHW filters)
            wr = np.random.RandomState(42)
            order = []
            for name, v in main.global_block().vars.items():
                if getattr(v, "persistable", False):
                    var = scope.find_var(name)
                    if var is not None and var.is_initialized():
                        a = np.asarray(var.raw().array)
                        if a.dtype.kind == "f":
                            scope.var(name).get_tensor()._array = \
                                jnp.asarray((wr.randn(*a.shape) * 0.05)
                                            .astype(a.dtype))
                        order.append(name)
            feed_img = img if fmt == "NCHW" else np.transpose(
                img, (0, 2, 3, 1))
            losses = []
            for _ in range(2):
                (l,) = exe.run(main, feed={"x": feed_img, "label": lab},
                               fetch_list=[loss])
                losses.append(float(np.ravel(l)[0]))
            params = [np.asarray(scope.find_var(n).raw().array)
                      for n in order]
        out[fmt] = (losses, params)

    np.testing.assert_allclose(out["NCHW"][0], out["NHWC"][0],
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(out["NCHW"][1], out["NHWC"][1]):
        if a.shape == b.shape:
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
