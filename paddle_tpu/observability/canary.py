"""Canary-gated plan rollout: apply to ONE replica, compare, decide.

The apply half of the self-driving runtime. A proposal (from the
steering daemon, or any ``report → plan`` steerer run by hand) never
reaches the fleet directly: it is applied to a single canary —
a serving fleet points one replica at the new bucket ladder, a
training job re-launches one config under the new placement plan —
measured, and compared against the incumbent with the SAME comparator
CI gates on (``observability/comparator.py``, the extracted
``bench_diff`` core). Then:

- PROMOTE: no watched metric regressed (and, when the caller demands
  it, the triggering metric actually improved) — the plan is
  installed as the fleet's active plan through the ``PlanStore``
  pointer (``PADDLE_TPU_PLACEMENT_PLAN`` for placement, the ladder
  for serving policies);
- ROLL BACK: any watched regression — the incumbent stays, the canary
  is reverted via ``rollback_fn``.

Every decision is flight-recorded (``canary.promoted`` /
``canary.rolled_back`` instants with the plan digest — they land in
the merged ``trace.json`` like every flight event) and appended to the
``steering_audit.json`` trail. The ``PlanStore`` is the ONLY writer of
the active-plan pointer and *refuses to install without an audit
entry*: a plan switch that skipped the audit trail is structurally
impossible, which is exactly what ``tools/steering_drill.py`` checks.

Audit entry schema (``steering_audit_v1``)::

    {"seq": n, "t": epoch_seconds, "decision": "promoted"|"rolled_back",
     "steerer": str|None, "plan_digest": sha1,
     "verdict": "ok"|"regression"|"no_overlap",
     "regressions": int, "regressed_metrics": [str, ...],
     "trigger": {...proposal trigger block or null...},
     "comparison": {...Comparison.to_dict()...}}

Interleaved A/B entries (``run_ab_canary``, ISSUE 20) carry
``"protocol": "ab_interleaved"`` plus ``pairs`` / ``ok_pairs`` /
``objective`` / ``objective_score`` / ``windows`` (every measurement
window with open/close stamps and its record) / ``pair_verdicts``
(every pairwise comparison) instead of the single ``comparison``.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from . import comparator, flight, steering
from . import inc as _inc
from . import set_gauge as _set_gauge

__all__ = ["AuditTrail", "PlanStore", "CanaryDecision", "run_canary",
           "run_ab_canary", "AUDIT_SCHEMA", "AUDIT_NAME",
           "AB_PROTOCOL", "AB_PAIRS_ENV", "DEFAULT_AB_PAIRS"]

AUDIT_SCHEMA = "steering_audit_v1"
AUDIT_NAME = "steering_audit.json"
# interleaved A/B protocol (ISSUE 20): tagged into every A/B audit
# entry so tooling (ft_timeline) can tell the two protocols apart
AB_PROTOCOL = "ab_interleaved"
AB_PAIRS_ENV = "PADDLE_TPU_AB_PAIRS"
DEFAULT_AB_PAIRS = 3


class AuditTrail:
    """Append-only JSON trail of steering decisions. The whole file is
    rewritten atomically per append (decisions are rare — human-scale
    events, not a hot path), so a reader never sees a torn trail and a
    crash between appends loses nothing already written."""

    def __init__(self, path: str):
        if os.path.isdir(path):
            path = os.path.join(path, AUDIT_NAME)
        self.path = path
        self._lock = threading.Lock()

    def entries(self) -> List[Dict]:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return []
        if isinstance(doc, dict) and isinstance(doc.get("entries"),
                                                list):
            return doc["entries"]
        return []

    def append(self, entry: Dict) -> Dict:
        from ..checkpoint import atomic_write_bytes

        with self._lock:
            entries = self.entries()
            entry = dict(entry)
            entry["seq"] = len(entries)
            entry.setdefault("t", time.time())
            entries.append(entry)
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            atomic_write_bytes(self.path, json.dumps(
                {"schema": AUDIT_SCHEMA, "entries": entries},
                indent=2, sort_keys=True, default=str).encode())
        return entry


class PlanStore:
    """The fleet's active-plan pointer for one steerer:
    ``active_plan-<steerer>.json``. The ONLY legal write path is
    ``install`` — and install demands the audit entry that justified
    the switch, so an un-audited plan switch cannot be expressed."""

    def __init__(self, dirname: str, steerer: str):
        self.dirname = dirname
        self.steerer = steerer
        self.path = os.path.join(dirname,
                                 "active_plan-%s.json" % steerer)
        self.installs = 0

    def read(self) -> Optional[Dict]:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def active_digest(self) -> Optional[str]:
        doc = self.read()
        if isinstance(doc, dict):
            d = doc.get("plan_digest") or doc.get("digest")
            if isinstance(d, str):
                return d
        return None

    def install(self, plan, audit_entry: Dict) -> str:
        """Atomically point the fleet at ``plan``. Refuses without the
        audit entry recording the promotion (and cross-checks its
        digest — the pointer and the trail can never disagree)."""
        from ..checkpoint import atomic_write_bytes

        if not isinstance(audit_entry, dict) \
                or audit_entry.get("decision") != "promoted":
            raise ValueError(
                "PlanStore.install requires the audit entry of a "
                "promotion — un-audited plan switches are not a thing")
        digest = steering.plan_digest(plan)
        if audit_entry.get("plan_digest") != digest:
            raise ValueError(
                "audit entry digest %r does not match plan %r"
                % (audit_entry.get("plan_digest"), digest))
        doc = {"schema": "active_plan_v1",
               "steerer": self.steerer,
               "plan": steering.plan_jsonable(plan),
               "plan_digest": digest,
               "audit_seq": audit_entry.get("seq"),
               "installed_at": time.time()}
        os.makedirs(self.dirname, exist_ok=True)
        atomic_write_bytes(self.path, json.dumps(
            doc, indent=2, sort_keys=True, default=str).encode())
        self.installs += 1
        return digest


class CanaryDecision:
    """What ``run_canary`` returns: the verdict plus everything needed
    to assert on it."""

    __slots__ = ("promoted", "reason", "plan", "plan_digest",
                 "comparison", "audit_entry")

    def __init__(self, promoted, reason, plan, plan_digest,
                 comparison, audit_entry):
        self.promoted = bool(promoted)
        self.reason = reason
        self.plan = plan
        self.plan_digest = plan_digest
        self.comparison = comparison
        self.audit_entry = audit_entry

    @property
    def decision(self) -> str:
        return "promoted" if self.promoted else "rolled_back"

    def __repr__(self):
        return "CanaryDecision(%s, %s, plan=%s)" % (
            self.decision, self.reason, self.plan_digest[:12])


def run_canary(proposal, incumbent, measure: Callable,
               *, steerer: Optional[str] = None,
               threshold: float = 0.10,
               counters_threshold: float = 0.25,
               apply_fn: Optional[Callable] = None,
               promote_fn: Optional[Callable] = None,
               rollback_fn: Optional[Callable] = None,
               plan_store: Optional[PlanStore] = None,
               audit: Optional[AuditTrail] = None,
               require_improvement: Optional[str] = None,
               min_improvement: float = 0.0,
               objective: Optional["comparator.Objective"] = None
               ) -> CanaryDecision:
    """One canary evaluation of ``proposal`` against ``incumbent``.

    - ``proposal``: a daemon proposal artifact (``{"plan": ...,
      "plan_digest": ...}``) or a bare plan;
    - ``incumbent``: the incumbent's measured record (any shape the
      comparator understands — bench record or merged metrics.json);
    - ``measure(plan) -> record``: run the canary replica/config under
      the plan and return its record. The caller owns HOW (one
      FleetRouter replica, one re-launched config) — this function
      owns the decision protocol;
    - ``apply_fn(plan)``: point the canary at the plan before
      measuring (optional when ``measure`` applies internally);
    - ``promote_fn(plan)`` / ``rollback_fn(plan)``: roll the plan out
      to the fleet / revert the canary. Called AFTER the audit entry
      exists — the trail records the decision before the world
      changes;
    - ``require_improvement``: a watched metric name that must have
      improved by more than ``min_improvement`` (direction-aware) for
      promotion — "no regression" alone keeps a pointless plan out of
      the fleet when set.

    Promotion requires verdict ``ok`` — a canary whose record shares
    NOTHING with the incumbent (``no_overlap``) rolls back: a blind
    promote is worse than a spurious rollback.
    """
    if isinstance(proposal, dict) and "plan" in proposal:
        plan = proposal["plan"]
        trigger = {k: proposal.get(k) for k in
                   ("steerer", "metric", "baseline", "observed",
                    "threshold", "created_at") if k in proposal}
        steerer = steerer or proposal.get("steerer")
        digest = proposal.get("plan_digest") \
            or steering.plan_digest(plan)
    else:
        plan = proposal
        trigger = None
        digest = steering.plan_digest(plan)

    if objective is None and isinstance(proposal, dict) \
            and isinstance(proposal.get("objective"), dict):
        objective = comparator.Objective.from_dict(
            proposal["objective"])

    if apply_fn is not None:
        apply_fn(plan)
    head = measure(plan)
    cmp = comparator.compare(incumbent, head, threshold,
                             counters_threshold, objective=objective)

    promoted = cmp.ok
    reason = cmp.verdict
    if promoted and require_improvement:
        gain = cmp.improvement(require_improvement)
        if gain is None or gain <= min_improvement:
            promoted = False
            reason = "no_improvement:%s" % require_improvement

    entry = {
        "schema": AUDIT_SCHEMA,
        "decision": "promoted" if promoted else "rolled_back",
        "reason": reason,
        "steerer": steerer,
        "plan_digest": digest,
        "verdict": cmp.verdict,
        "regressions": cmp.regressions,
        "regressed_metrics": cmp.regressed_metrics,
        "trigger": trigger,
        "comparison": cmp.to_dict(),
    }
    if objective is not None:
        entry["objective"] = objective.to_dict()
        entry["objective_score"] = cmp.objective_score
        if cmp.objective_score is not None:
            _set_gauge("steering.objective_score",
                       cmp.objective_score, steerer=steerer or "none")
    if audit is not None:
        entry = audit.append(entry)

    if promoted:
        if plan_store is not None:
            if audit is None:
                raise ValueError(
                    "a PlanStore promotion requires an AuditTrail — "
                    "every plan switch must be audited")
            plan_store.install(plan, entry)
        if promote_fn is not None:
            promote_fn(plan)
        _inc("canary.promoted", steerer=steerer or "none")
        flight.record("canary.promoted", steerer=steerer,
                      plan_digest=digest, verdict=cmp.verdict,
                      regressions=cmp.regressions)
    else:
        if rollback_fn is not None:
            rollback_fn(plan)
        _inc("canary.rolled_back", steerer=steerer or "none")
        flight.record("canary.rolled_back", steerer=steerer,
                      plan_digest=digest, verdict=cmp.verdict,
                      reason=reason,
                      regressions=cmp.regressions)

    return CanaryDecision(promoted, reason, plan, digest, cmp, entry)


def _ab_pairs_default() -> int:
    try:
        n = int(os.environ.get(AB_PAIRS_ENV, "") or DEFAULT_AB_PAIRS)
    except ValueError:
        n = DEFAULT_AB_PAIRS
    return max(1, n)


def run_ab_canary(proposal, measure: Callable,
                  *, steerer: Optional[str] = None,
                  pairs: Optional[int] = None,
                  objective: Optional["comparator.Objective"] = None,
                  threshold: float = 0.10,
                  counters_threshold: float = 0.25,
                  apply_fn: Optional[Callable] = None,
                  revert_fn: Optional[Callable] = None,
                  promote_fn: Optional[Callable] = None,
                  rollback_fn: Optional[Callable] = None,
                  plan_store: Optional[PlanStore] = None,
                  audit: Optional[AuditTrail] = None,
                  min_score: float = 0.0) -> CanaryDecision:
    """Interleaved A/B canary: alternate incumbent and candidate
    measurement windows N times (A-B-A-B-...), score each ADJACENT
    pair, and promote only on strict-majority pairwise agreement (plus
    net objective improvement when an objective is configured).

    Why interleaved: a single before/after comparison (``run_canary``
    against a stale incumbent record) confuses plan effect with load
    drift — under monotone drift everything measured later looks
    better (or worse) regardless of the plan. Adjacent A/B windows are
    at most one window apart in time, so the drift contribution to
    each pairwise delta is bounded by one window of drift and the same
    bias applies to every pair; a plan that only "wins" because of
    drift loses the pairwise vote. ``tools/steering_drill.py --drift``
    demonstrates exactly this divergence.

    - ``measure(plan_or_None) -> record``: one measurement window.
      ``None`` = measure the incumbent; a plan = measure the
      candidate. The caller owns window length.
    - ``revert_fn(plan)``: point the canary back at the incumbent
      before each A window (optional when ``measure(None)`` handles
      it); ``apply_fn(plan)`` points it at the candidate before each
      B window.
    - ``pairs``: A/B window pairs to run; default from the proposal's
      ``ab_pairs``, then ``PADDLE_TPU_AB_PAIRS``, then 3.
    - ``objective``: weighted scoring for every pairwise comparison;
      default from the proposal's ``objective`` block. With one, the
      MEAN pairwise score must exceed ``min_score`` on top of the
      majority vote; a hard-floor violation in ANY window vetoes
      unconditionally.

    The audit entry (appended BEFORE the world changes, like every
    canary decision) records every window, every pairwise verdict with
    its full comparison, and every objective term.
    """
    if isinstance(proposal, dict) and "plan" in proposal:
        plan = proposal["plan"]
        trigger = {k: proposal.get(k) for k in
                   ("steerer", "metric", "baseline", "observed",
                    "threshold", "created_at") if k in proposal}
        steerer = steerer or proposal.get("steerer")
        digest = proposal.get("plan_digest") \
            or steering.plan_digest(plan)
        if objective is None and \
                isinstance(proposal.get("objective"), dict):
            objective = comparator.Objective.from_dict(
                proposal["objective"])
        if pairs is None and proposal.get("ab_pairs"):
            pairs = int(proposal["ab_pairs"])
    else:
        plan = proposal
        trigger = None
        digest = steering.plan_digest(plan)
    pairs = max(1, int(pairs)) if pairs else _ab_pairs_default()

    windows: List[Dict] = []
    pair_docs: List[Dict] = []
    ok_pairs = 0
    hard_veto = False
    last_cmp = None

    def _window(phase: str, pair: int, plan_arg):
        flight.record("canary.window", phase=phase, pair=pair,
                      steerer=steerer, plan_digest=digest)
        _inc("canary.windows", phase=phase, steerer=steerer or "none")
        t_open = time.time()
        record = measure(plan_arg)
        windows.append({"seq": len(windows), "pair": pair,
                        "phase": phase, "t_open": t_open,
                        "t_close": time.time(), "record": record})
        return record

    for i in range(pairs):
        if revert_fn is not None:
            revert_fn(plan)
        rec_a = _window("incumbent", i, None)
        if apply_fn is not None:
            apply_fn(plan)
        rec_b = _window("candidate", i, plan)
        cmp = comparator.compare(rec_a, rec_b, threshold,
                                 counters_threshold,
                                 objective=objective)
        last_cmp = cmp
        if cmp.ok:
            ok_pairs += 1
        if cmp.verdict == "hard_floor":
            hard_veto = True
        pair_docs.append({"pair": i, "ok": cmp.ok,
                          "verdict": cmp.verdict,
                          "objective_score": cmp.objective_score,
                          "comparison": cmp.to_dict()})

    scores = [d["objective_score"] for d in pair_docs
              if d["objective_score"] is not None]
    mean_score = (sum(scores) / len(scores)) if scores else None

    promoted = ok_pairs * 2 > pairs
    reason = "ab_majority:%d/%d" % (ok_pairs, pairs)
    if hard_veto:
        # an SLO breach in any window vetoes regardless of the vote
        promoted = False
        reason = "ab_hard_floor"
    elif promoted and objective is not None and \
            (mean_score is None or mean_score <= min_score):
        promoted = False
        reason = "ab_no_objective_improvement"

    if mean_score is not None:
        _set_gauge("steering.objective_score", mean_score,
                   steerer=steerer or "none")

    entry = {
        "schema": AUDIT_SCHEMA,
        "protocol": AB_PROTOCOL,
        "decision": "promoted" if promoted else "rolled_back",
        "reason": reason,
        "steerer": steerer,
        "plan_digest": digest,
        "pairs": pairs,
        "ok_pairs": ok_pairs,
        "objective": objective.to_dict() if objective is not None
        else None,
        "objective_score": mean_score,
        "windows": windows,
        "pair_verdicts": pair_docs,
        "trigger": trigger,
    }
    if audit is not None:
        entry = audit.append(entry)

    if promoted:
        if plan_store is not None:
            if audit is None:
                raise ValueError(
                    "a PlanStore promotion requires an AuditTrail — "
                    "every plan switch must be audited")
            plan_store.install(plan, entry)
        if promote_fn is not None:
            promote_fn(plan)
        _inc("canary.promoted", steerer=steerer or "none")
        flight.record("canary.promoted", steerer=steerer,
                      plan_digest=digest, protocol=AB_PROTOCOL,
                      ok_pairs=ok_pairs, pairs=pairs,
                      objective_score=mean_score)
    else:
        if rollback_fn is not None:
            rollback_fn(plan)
        _inc("canary.rolled_back", steerer=steerer or "none")
        flight.record("canary.rolled_back", steerer=steerer,
                      plan_digest=digest, protocol=AB_PROTOCOL,
                      reason=reason, ok_pairs=ok_pairs, pairs=pairs,
                      objective_score=mean_score)

    return CanaryDecision(promoted, reason, plan, digest, last_cmp,
                          entry)
