"""Supervised streaming-decode replica for the fleet chaos drill.

Run under ``paddle_tpu.distributed.launch --serving_script=<this>``:
builds a ``DecodeEngine`` over the fixed-seed ``TinyDecodeLM`` (every
replica serves the IDENTICAL next-token function — and regeneration is
bit-deterministic regardless of batch composition or chunk boundaries,
so a failed-over stream re-prefixed on a different replica continues
with exactly the tokens the dead replica would have emitted) and
serves it with the streaming HTTP front (``/generate`` chunked ndjson,
``/healthz`` with ``engine_kind=decode``) on
``$PADDLE_SERVING_ENDPOINT``.

Drill hooks (env):

- ``SERVING_DIE_REPLICA`` / ``DECODE_DIE_AFTER_TOKENS`` — the named
  replica index SIGKILLs ITSELF (no cleanup, no drain, streams
  mid-token) once it has emitted that many decode tokens, but only on
  its first incarnation (``PADDLE_RESTART_COUNT=0``): the supervisor
  relaunches it and the relaunched incarnation must rejoin the fleet
  and serve streams again.

The driver side of the drill builds the SAME engine config locally
(``ENGINE_KW``) and verifies every delivered token value-for-value
against local regeneration — a resumed stream that re-emitted,
skipped, or diverged after failover cannot hide.
"""
from __future__ import annotations

import os
import signal
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# one engine config, shared verbatim by every replica AND the driver's
# local reference engine: determinism across processes is the drill's
# foundation, so the config must never be able to drift between them
ENGINE_KW = dict(
    kv_blocks=96, kv_block_tokens=16, num_layers=2, num_heads=2,
    head_dim=8, max_batch_size=8, max_waiting=64, max_tokens_cap=512,
    prefill_chunk_tokens=16, eos_token=None)


def build_engine():
    from paddle_tpu.serving.decode import DecodeConfig, DecodeEngine
    return DecodeEngine(DecodeConfig(**ENGINE_KW))


def main() -> int:
    from paddle_tpu import observability as obs
    from paddle_tpu import serving
    from paddle_tpu.serving import metrics as sm

    endpoint = os.environ.get("PADDLE_SERVING_ENDPOINT",
                              "127.0.0.1:8300")
    index = int(os.environ.get("PADDLE_SERVING_REPLICA_INDEX", "0") or 0)
    restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0") or 0)
    die_replica = int(os.environ.get("SERVING_DIE_REPLICA", "-1") or -1)
    die_after = int(os.environ.get("DECODE_DIE_AFTER_TOKENS", "0") or 0)
    if index != die_replica or restart > 0:
        die_after = 0  # only the named replica's FIRST incarnation dies

    host, _, port = endpoint.rpartition(":")
    engine = build_engine().start()
    server, _thread = serving.start_http_server(
        engine, host or "127.0.0.1", int(port))

    if die_after:
        # the drill's replica death: SIGKILL once the engine has
        # emitted `die_after` tokens — streams half-delivered, KV
        # blocks held, the HTTP chunks mid-flight. Watching the token
        # counter (~1 token/ms on CPU) lands the kill mid-stream
        # without reaching into the engine's step loop.
        def watchdog():
            while True:
                if obs.counter_value(sm.TOKENS) >= die_after:
                    os.kill(os.getpid(), signal.SIGKILL)
                time.sleep(0.001)

        threading.Thread(target=watchdog, name="die-watchdog",
                         daemon=True).start()

    print("[decode replica %d r%d] serving %s (die_after_tokens=%d)"
          % (index, restart, endpoint, die_after), flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    try:
        while not stop.wait(0.2):
            pass
    finally:
        engine.stop()
        server.shutdown()
        server.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
