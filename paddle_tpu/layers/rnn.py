"""RNN layer APIs.

Parity: /root/reference/python/paddle/fluid/layers/rnn.py
(dynamic_lstm :1860, lstm :2017, dynamic_gru :2395, gru_unit :2548,
lstm_unit :2921). The LoD variants keep the reference's pre-projected
input contract ([T, 4*size] / [T, 3*size]); the dense ``lstm`` packs
per-(layer, direction) weights into one flat parameter consumed by the
scan-stack op (gate order candidate/input/forget/output, matching
operators/math/detail/lstm_cpu_kernel.h).
"""
from __future__ import annotations

from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer

__all__ = ["dynamic_lstm", "dynamic_gru", "lstm", "StaticRNN"]


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """LoD LSTM; ``input`` is the pre-projected [T, 4*size//4] sequence.
    Returns (hidden, cell), both LoD-preserving."""
    helper = LayerHelper("lstm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d = size // 4
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[d, 4 * d], dtype=dtype)
    bias_size = [1, 7 * d] if use_peepholes else [1, 4 * d]
    bias = helper.create_parameter(attr=helper.bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(
        "lstm", inputs=inputs,
        outputs={"Hidden": [hidden], "Cell": [cell]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation},
        infer_shape=False)
    hidden.shape = input.shape[:-1] + (d,)
    cell.shape = input.shape[:-1] + (d,)
    hidden.lod_level = input.lod_level
    cell.lod_level = input.lod_level
    return hidden, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False,
                name=None):
    """LoD GRU; ``input`` is the pre-projected [T, 3*size] sequence."""
    helper = LayerHelper("gru", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = helper.input_dtype()
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(attr=helper.bias_attr,
                                   shape=[1, 3 * size], dtype=dtype,
                                   is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(
        "gru", inputs=inputs, outputs={"Hidden": [hidden]},
        attrs={"activation": candidate_activation,
               "gate_activation": gate_activation,
               "is_reverse": is_reverse, "origin_mode": origin_mode},
        infer_shape=False)
    hidden.shape = input.shape[:-1] + (size,)
    hidden.lod_level = input.lod_level
    return hidden


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Dense multi-layer (bi)LSTM over [T, N, D] (reference layers.lstm,
    cudnn-backed there). Returns (out, last_h, last_c)."""
    helper = LayerHelper("cudnn_lstm", input=input, name=name)
    dtype = helper.input_dtype()
    ndir = 2 if is_bidirec else 1
    in_size = input.shape[-1]
    n_weight = 0
    din = in_size
    for layer in range(num_layers):
        for _ in range(ndir):
            n_weight += din * 4 * hidden_size + hidden_size * 4 * hidden_size
            n_weight += 4 * hidden_size
        din = hidden_size * ndir
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[n_weight], dtype=dtype,
        default_initializer=default_initializer)
    out = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "cudnn_lstm",
        inputs={"Input": [input], "InitH": [init_h], "InitC": [init_c],
                "W": [weight]},
        outputs={"Out": [out], "LastH": [last_h], "LastC": [last_c]},
        attrs={"max_len": max_len, "hidden_size": hidden_size,
               "num_layers": num_layers, "is_bidirec": is_bidirec,
               "dropout_prob": dropout_prob, "is_test": is_test,
               "input_size": in_size, "seed": seed},
        infer_shape=False)
    t, n = input.shape[0], input.shape[1]
    out.shape = (t, n, hidden_size * ndir)
    last_h.shape = (num_layers * ndir, n, hidden_size)
    last_c.shape = (num_layers * ndir, n, hidden_size)
    return out, last_h, last_c


class StaticRNN:
    """Fixed-length RNN builder (reference layers/control_flow.py
    StaticRNN / operators/recurrent_op.cc).

    The user's step body is captured into a sub-block once; on exit it is
    UNROLLED: copied T times into the parent block with per-step variable
    renaming — step inputs become time slices, memories thread from step
    to step, step outputs stack back along time. Every unrolled op is an
    ordinary pure op, so the program still whole-compiles (XLA dedups the
    repeated computation structure).

    Usage (reference contract)::

        rnn = StaticRNN()
        with rnn.step():
            w = rnn.step_input(x_tbd)          # x [T, B, D] -> w [B, D]
            prev = rnn.memory(shape=[-1, H], batch_ref=w)
            h = layers.fc([w, prev], size=H)
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        out = rnn()                             # [T, B, H]
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._step_inputs = []   # (sub_var, source_var)
        self._mems = []          # (sub_var, init_var); _next set later
        self._mem_next = {}      # sub_var.name -> sub-block var
        self._step_outputs = []  # sub-block vars
        self._seq_len = None
        self._sub = None
        self._result = None

    def step(self):
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            main = self.helper.main_program
            self._parent_block = main.current_block()
            self._sub = main._create_block()
            try:
                yield
            finally:
                main._rollback()
                self._unroll()

        return _ctx()

    def _require_step(self):
        if self._sub is None:
            raise RuntimeError("call inside `with rnn.step():`")

    def step_input(self, x):
        self._require_step()
        if self._seq_len is None:
            self._seq_len = int(x.shape[0])
        elif int(x.shape[0]) != self._seq_len:
            raise ValueError("step inputs disagree on seq_len")
        v = self._sub.create_var(
            name=self.helper.unique_var_name("step_in"),
            shape=tuple(x.shape[1:]), dtype=x.dtype)
        self._step_inputs.append((v, x))
        return v

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1,
               value=None, dtype="float32"):
        """Reference signature (control_flow.py StaticRNN.memory):
        ``init_value`` is the canonical kwarg; ``value`` kept as an
        alias. The batch-dim indices are accepted for compatibility
        (batch_ref's dim 0 is used as the batch here)."""
        self._require_step()
        if value is not None:
            init_value = value
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init or (shape, batch_ref)")
            from .tensor import fill_constant

            dims = [int(batch_ref.shape[0])] + [int(s) for s in shape
                                                if int(s) != -1]
            # init belongs to the parent block, before the unroll
            cur = self.helper.main_program.current_block()
            self.helper.main_program._current_block_idx = \
                self._parent_block.idx
            try:
                init = fill_constant(shape=dims, dtype=dtype,
                                     value=init_value)
            finally:
                self.helper.main_program._current_block_idx = cur.idx
        v = self._sub.create_var(
            name=self.helper.unique_var_name("mem"),
            shape=tuple(init.shape), dtype=init.dtype)
        self._mems.append((v, init))
        return v

    def update_memory(self, mem, new_val):
        self._require_step()
        self._mem_next[mem.name] = new_val

    def step_output(self, o):
        self._require_step()
        self._step_outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _unroll(self):
        if self._seq_len is None:
            raise ValueError("StaticRNN needs at least one step_input")
        parent = self._parent_block
        state = {}  # sub mem name -> parent var name (current value)
        for mem, init in self._mems:
            state[mem.name] = init.name
        per_step_outs = {o.name: [] for o in self._step_outputs}

        for t in range(self._seq_len):
            mapping = dict(state)
            for v, src in self._step_inputs:
                mapping[v.name] = self._slice_t(parent, src, t).name
            for op in self._sub.ops:
                new_ins = {
                    slot: [mapping.get(n, n) for n in names]
                    for slot, names in op.inputs.items()}
                new_outs = {}
                for slot, names in op.outputs.items():
                    outs = []
                    for n in names:
                        sv = self._sub.vars.get(n)
                        nn = "%s@t%d" % (n, t)
                        if sv is not None and nn not in parent.vars:
                            parent.create_var(name=nn, shape=sv.shape,
                                              dtype=sv.dtype)
                        mapping[n] = nn
                        outs.append(nn)
                    new_outs[slot] = outs
                parent.append_op(op.type, inputs=new_ins, outputs=new_outs,
                                 attrs=dict(op.attrs), infer_shape=False)
            for mem, _init in self._mems:
                nxt = self._mem_next.get(mem.name)
                if nxt is not None:
                    state[mem.name] = mapping[nxt.name]
            for o in self._step_outputs:
                per_step_outs[o.name].append(parent.vars[mapping[o.name]])

        results = []
        cur = self.helper.main_program._current_block_idx
        self.helper.main_program._current_block_idx = parent.idx
        try:
            from .nn import stack

            for o in self._step_outputs:
                results.append(stack(per_step_outs[o.name], axis=0))
        finally:
            self.helper.main_program._current_block_idx = cur
        self._result = results

    def _slice_t(self, parent, src, t):
        from .nn import slice as nn_slice

        cur = self.helper.main_program._current_block_idx
        self.helper.main_program._current_block_idx = parent.idx
        try:
            s = nn_slice(src, axes=[0], starts=[t], ends=[t + 1])
            from .nn import squeeze

            return squeeze(s, axes=[0])
        finally:
            self.helper.main_program._current_block_idx = cur

    def __call__(self):
        if self._result is None:
            raise RuntimeError("StaticRNN not built — use `with rnn.step()`")
        return self._result[0] if len(self._result) == 1 else self._result


class RNNCell:
    """Base cell (reference layers/rnn.py RNNCell): call(inputs, states)
    -> (outputs, new_states)."""

    def call(self, inputs, states):
        raise NotImplementedError

    def __call__(self, inputs, states):
        return self.call(inputs, states)

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0):
        from .tensor import fill_constant

        b = int(batch_ref.shape[0])
        shapes = shape if isinstance(shape, (list, tuple)) and shape and \
            isinstance(shape[0], (list, tuple)) else [shape]
        outs = [fill_constant([b] + [int(s) for s in sh], dtype,
                              init_value) for sh in shapes]
        return outs if len(outs) > 1 else outs[0]


class LSTMCell(RNNCell):
    """(reference layers/rnn.py LSTMCell): one LSTM step built from fc +
    the lstm_unit op; state = [hidden, cell]. Parameters are NAMED once
    per cell instance so every time step of an unroll shares the same
    recurrent weights (LayerHelper reuses parameters by name)."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 name="LSTMCell"):
        from .. import framework
        from ..param_attr import ParamAttr

        self.hidden_size = hidden_size
        base = framework.unique_name.generate(name)
        self._param_attr = param_attr if param_attr is not None else             ParamAttr(name=base + "_w")
        self._bias_attr = bias_attr if bias_attr is not None else             ParamAttr(name=base + "_b")

    def call(self, inputs, states):
        from .extras import lstm_unit

        h_prev, c_prev = states
        h, c = lstm_unit(inputs, h_prev, c_prev,
                         param_attr=self._param_attr,
                         bias_attr=self._bias_attr)
        return h, [h, c]

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]


class GRUCell(RNNCell):
    """(reference layers/rnn.py GRUCell): fc projection + gru_unit op;
    state = hidden. The projection and recurrent weights get DISTINCT
    per-instance names (shared across steps, never across the two ops —
    they have different shapes)."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 name="GRUCell"):
        from .. import framework
        from ..param_attr import ParamAttr

        self.hidden_size = hidden_size
        base = framework.unique_name.generate(name)
        # a user-supplied NAMED param_attr cannot serve both ops (their
        # shapes differ); derive distinct names from it
        user_name = getattr(param_attr, "name", None) if param_attr else             None
        prefix = user_name or base
        self._proj_attr = ParamAttr(name=prefix + "_proj_w")
        self._rec_attr = ParamAttr(name=prefix + "_rec_w")
        self._bias_attr = bias_attr if bias_attr is not None else             ParamAttr(name=prefix + "_b")

    def call(self, inputs, states):
        from .extras import gru_unit
        from .nn import fc

        h_prev = states[0] if isinstance(states, (list, tuple)) else states
        x = fc(inputs, size=3 * self.hidden_size,
               param_attr=self._proj_attr, bias_attr=False)
        h, _, _ = gru_unit(x, h_prev, 3 * self.hidden_size,
                           param_attr=self._rec_attr,
                           bias_attr=self._bias_attr)
        return h, [h]

    @property
    def state_shape(self):
        return [[self.hidden_size]]


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Run `cell` over the time axis of dense inputs (reference
    layers/rnn.py rnn): unrolled via StaticRNN-style slicing, so the
    whole program still compiles. Returns (outputs, final_states)."""
    from .nn import slice as nn_slice
    from .nn import squeeze, stack
    from .tensor import cast, fill_constant

    time_axis = 0 if time_major else 1
    batch_axis = 1 if time_major else 0
    T = int(inputs.shape[time_axis])
    B = int(inputs.shape[batch_axis])
    states = initial_states
    if states is None:
        shapes = cell.state_shape
        states = [fill_constant([B] + [int(d) for d in sh], "float32",
                                0.0) for sh in shapes]
    if not isinstance(states, (list, tuple)):
        states = [states]
    states = list(states)
    len_mask = None
    if sequence_length is not None:
        # [T, B] step-validity mask; padded steps carry the old state
        from .sequence_lod import sequence_mask

        m = sequence_mask(sequence_length, maxlen=T)  # [B, T]
        len_mask = cast(m, inputs.dtype)
    outs = []
    steps = range(T - 1, -1, -1) if is_reverse else range(T)
    for i in steps:
        x_t = squeeze(nn_slice(inputs, axes=[time_axis], starts=[i],
                               ends=[i + 1]), axes=[time_axis])
        o, new_states = cell.call(x_t, list(states))
        if len_mask is not None:
            from .nn import elementwise_add, elementwise_mul
            from .ops import scale as _scale_op

            m_t = nn_slice(len_mask, axes=[1], starts=[i], ends=[i + 1])
            inv_m = _scale_op(m_t, scale=-1.0, bias=1.0)
            new_states = [
                elementwise_add(elementwise_mul(n, m_t),
                                elementwise_mul(s, inv_m))
                for n, s in zip(new_states, states)]
            o = elementwise_mul(o, m_t)
        states = new_states
        outs.append(o)
    if is_reverse:
        outs = outs[::-1]
    outputs = stack(outs, axis=time_axis)
    return outputs, states


__all__ += ["RNNCell", "LSTMCell", "GRUCell", "rnn"]
