"""Minimal socket RPC for the parameter-server runtime.

The reference's PS dataplane is gRPC/BRPC (operators/distributed/grpc/
grpc_client.cc, grpc_server.cc) with a sync round protocol
(listen_and_serv_op.cc:110 RunSyncLoop: wait for every trainer's grads,
run the optimize blocks, serve param reads until all trainers fetched)
and liveness tracking (heart_beat_monitor.h:54). This module provides
the same contract over plain TCP sockets — enough transport for real
multi-process PS training and its tests, without a gRPC dependency.

Wire format (no pickle — frames from the network must not be able to
execute code): 8-byte LE json-header length, json header, 8-byte LE raw
length, raw array bytes. The header carries only json-safe scalars;
arrays travel as dtype/shape in the header plus the raw section.

Round protocol (sync mode): send_grad buffers; the fanin-th
send_barrier sums each grad, runs its optimize block, and opens the
params; get_param waits for the open round; the fanin-th fetch_barrier
closes it. A send_barrier for round N+1 blocks until round N is fully
fetched — without that gate, a fast trainer's next round would flip
the round incomplete while a slow trainer is still mid-fetch and both
would deadlock.

Fault tolerance (reference grpc_client.cc deadline/retry +
heart_beat_monitor.h semantics):

- every frame passes through ``distributed/fault.py`` — the
  env-configured injector (``PADDLE_TPU_FAULTS``) that makes each
  recovery path below testable on one host;
- the client retries EVERY rpc with bounded exponential backoff +
  jitter after a timeout, EOF, or connection loss. Requests carry a
  ``(cid, round, seq)`` dedup token (``cid`` is a per-incarnation
  random nonce standing in for the trainer id, so a restarted
  trainer's fresh ``seq`` can never match its previous life's cache);
  the server executes each token exactly once — a retried
  ``send_grad``/barrier is summed/counted once no matter how many
  copies of the frame arrive. Responses echo ``seq`` so the client
  discards stale replies left in the stream by duplicated frames;
- the server evicts trainers whose heartbeats go silent past
  ``PADDLE_PS_EVICT_AFTER`` seconds: the effective fanin shrinks so
  surviving trainers' barriers complete instead of deadlocking, and
  the heartbeat response names the evicted so survivors
  log-and-continue. A relaunched trainer that sends again is
  re-admitted and the fanin grows back;
- ``rpc.retries`` / ``rpc.timeouts`` (labeled by rpc ``method``) /
  ``ps.evictions`` / ``ps.readmissions`` are recorded unconditionally
  in the observability registry (rare events, and CI asserts on them).

Replication + failover (ISSUE 4 — the reference's brpc failover /
checkpoint_notify availability tier, made survivable end to end):

- ``PADDLE_PSERVER_ENDPOINTS`` names an ordered primary + N backups.
  In sync mode the primary streams every applied round — round number,
  post-round scope blobs, and the per-client ``(cid -> seq)`` dedup
  watermark — to each live backup and waits for the acks BEFORE
  marking the round complete, so no trainer can observe (get_param) an
  update a promoted backup would not have;
- ``PSClient`` accepts a comma-separated endpoint list. When the
  bounded retry budget on the current endpoint is exhausted by
  transport failures (conn loss / timeout — never app errors), it
  advances to the next endpoint, replays its per-round log of
  non-idempotent rpcs (send_grad / send_barrier / push_sparse, with
  their ORIGINAL dedup tokens), and reissues the in-flight rpc. The
  replicated watermark makes replays of already-folded rpcs no-ops,
  so the replay is exactly-once on the new primary;
- promotion is deterministic: the lowest-index live endpoint. A backup
  only accepts the dataplane from a client that actually failed over
  (its rpcs carry a failover epoch ``fo >= 1``); fresh clients are
  redirected (``not_primary``) so a relaunched server can never steal
  traffic from the live primary (no split brain);
- a relaunched server (``PADDLE_PS_REJOIN=1``, set by the launch
  supervisor) rejoins as a backup: it refuses the dataplane until it
  has caught up from the active server's manifest-verified snapshot
  (``join_backup`` rpc -> ``snapshot_scope_to_dir`` ->
  ``checkpoint.load_scope_snapshot``), then receives the stream;
- counters: ``ps.failovers{cause=}``, ``ps.promotions``,
  ``ps.catchup_ms``, and the per-backup gauge
  ``ps.replication_lag_rounds{backup=}`` (0 after every ack; a backup
  that stops acking is dropped from the stream and the gauge freezes
  at its lag).

Distributed observability (ISSUE 5 — Dapper-style context riding the
existing frame):

- the client stamps ``trace_id`` / ``parent_span`` onto every rpc
  header (one trace per sync round, or the ambient context when one is
  installed — e.g. a serving request). The server opens a child span
  per rpc under the propagated context, and because ``child_span``
  installs itself thread-locally, the optimize apply and the
  replication rpcs it issues join the SAME trace — one round is one
  timeline across client, primary, and backups, retries/failovers/
  injected faults included. Old-frame peers ignore the extra fields;
- ``rpc.latency_ms{method=}`` observes every attempt's reply latency
  (retries observe separately) — the axis retry-policy tuning needs
  next to ``rpc.retries`` counts;
- every rpc token, retry, failover, replay, promotion, eviction, and
  round apply/applied pair is recorded in the crash flight recorder
  (``observability.flight``; heartbeat/status polls excluded so the
  bounded ring holds decisions, not noise) — dumped per-process into
  ``$PADDLE_TPU_METRICS_DIR`` and merged by ``tools/ft_timeline.py``
  into the cross-process postmortem.
"""
from __future__ import annotations

import json
import os
import random
import socket
import struct
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..observability import distributed as _dtrace
from ..observability import flight as _flight
from . import fault as _fault

_ROUND_TIMEOUT = float(os.environ.get("PADDLE_PS_ROUND_TIMEOUT", "120"))

# kinds whose per-frame flight events would flood the bounded ring
# (a heartbeater ticks every few hundred ms for the whole job) — they
# still get latency histograms and trace spans, just no black-box line
_FLIGHT_QUIET = ("heartbeat", "repl_status")


def _counter(name: str, **labels):
    from .. import observability as _obs

    return _obs.counter(name, **labels)


def _gauge(name: str, **labels):
    from .. import observability as _obs

    return _obs.gauge(name, **labels)


def _histogram(name: str, **labels):
    from .. import observability as _obs

    return _obs.histogram(name, **labels)


def _endpoints_from_env() -> List[str]:
    raw = os.environ.get("PADDLE_PSERVER_ENDPOINTS", "")
    return [e.strip() for e in raw.split(",") if e.strip()]


def _send_msg(sock: socket.socket, msg: dict,
              raw: bytes = b"") -> None:
    header = json.dumps(msg).encode("utf-8")
    frame = (struct.pack("<Q", len(header)) + header
             + struct.pack("<Q", len(raw)) + raw)
    inj = _fault.get_injector()
    if inj is not None:
        inj.on_send(sock, frame)  # may drop/dup/sever per the plan
    else:
        sock.sendall(frame)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    """Returns (msg_dict, raw_bytes) or None on EOF."""
    while True:
        inj = _fault.get_injector()
        action = inj.on_recv(sock) if inj is not None else "pass"
        h = _recv_exact(sock, 8)
        if h is None:
            return None
        (hlen,) = struct.unpack("<Q", h)
        header = _recv_exact(sock, hlen)
        if header is None:
            return None
        r = _recv_exact(sock, 8)
        if r is None:
            return None
        (rlen,) = struct.unpack("<Q", r)
        raw = _recv_exact(sock, rlen) if rlen else b""
        if raw is None:
            return None
        if action == "drop":
            continue  # injected: the frame evaporates in flight
        return json.loads(header.decode("utf-8")), raw


def _array_header(arr: np.ndarray) -> dict:
    return {"dtype": str(arr.dtype), "shape": list(arr.shape)}


def _array_from(header: dict, raw: bytes) -> np.ndarray:
    return np.frombuffer(raw, dtype=header["dtype"]).reshape(
        header["shape"]).copy()


def snapshot_scope_to_dir(executor, scope, dirname: str,
                          names_map: bool = False) -> None:
    """Serialize every tensor var in ``scope`` into ``dirname`` in the
    reference tensor-stream format (shared by the server-side
    'checkpoint' RPC kind and the emulated checkpoint_notify path).

    checkpoint_notify fans out over SEVERAL pservers that share one
    dir — each contributes its shard's vars concurrently — so the
    write is a MERGE: every file lands via tmp+fsync+rename (never a
    torn file) and the sha256 manifest is rewritten over the whole dir
    after this server's files. A whole-dir rename would let racing
    shards clobber each other. Scope of the guarantee: the manifest
    certifies integrity of the files PRESENT (no torn/corrupt file
    loads as garbage); whether every EXPECTED server contributed is
    the notifier's concern — it fans out the RPCs and sees each
    server's ack or error.

    ``names_map=True`` additionally writes ``__vars__.json``
    (file name -> original var name) so a DEDICATED snapshot — the
    ``join_backup`` catch-up path — can restore vars whose names were
    munged for the filesystem. Never set it for SHARED multi-server
    dirs: concurrent shards would clobber each other's map."""
    import os

    from ..checkpoint import SCOPE_VARS_NAME, atomic_write_bytes, \
        write_manifest
    from ..core import proto_format

    os.makedirs(dirname, exist_ok=True)
    names: Dict[str, str] = {}
    for name in list(scope.local_var_names()):
        val = executor._read_var(scope, name)
        if val is None or not hasattr(val, "shape"):
            continue
        fn = name.replace("/", "_")
        names[fn] = name
        atomic_write_bytes(
            os.path.join(dirname, fn),
            proto_format.serialize_lod_tensor(np.asarray(val)))
    if names_map:
        atomic_write_bytes(
            os.path.join(dirname, SCOPE_VARS_NAME),
            json.dumps(names, indent=1, sort_keys=True).encode())
    write_manifest(dirname)


class HeartBeatMonitor:
    """Per-trainer last-ping tracking (heart_beat_monitor.h:54)."""

    def __init__(self, stale_seconds: float = 60.0):
        self._last: Dict[int, float] = {}
        self._stale = stale_seconds
        self._lock = threading.Lock()

    def ping(self, trainer_id: int) -> None:
        with self._lock:
            self._last[int(trainer_id)] = time.time()

    def register(self, trainer_ids) -> None:
        """Start the staleness clock for expected trainers that have
        not pinged yet — a rank that dies BEFORE its first rpc must
        still become evictable, or survivors would wait out the full
        round timeout on a trainer the monitor never heard of."""
        now = time.time()
        with self._lock:
            for t in trainer_ids:
                self._last.setdefault(int(t), now)

    def forget(self, trainer_id: int) -> None:
        """Drop a trainer's entry (post-eviction: a stale entry would
        re-report the same trainer forever; re-admission re-pings)."""
        with self._lock:
            self._last.pop(int(trainer_id), None)

    def status(self) -> Dict[int, float]:
        """trainer_id -> seconds since last ping."""
        now = time.time()
        with self._lock:
            return {t: now - ts for t, ts in self._last.items()}

    def stale_trainers(self) -> List[int]:
        return [t for t, age in self.status().items()
                if age > self._stale]


class PSServer:
    """Sync-mode PS endpoint implementing the RunSyncLoop round
    protocol; async mode applies each grad immediately (RunAsyncLoop).

    ``evict_after`` (seconds; env ``PADDLE_PS_EVICT_AFTER``, 0 =
    disabled) arms the heartbeat monitor: a trainer silent that long is
    evicted — its slot leaves the effective fanin so the surviving
    trainers' barriers complete, and the heartbeat response carries the
    eviction so survivors can log-and-continue.

    ``endpoints`` (env ``PADDLE_PSERVER_ENDPOINTS``) is the ordered
    primary + backups list this server belongs to; index 0 starts as
    the active primary, the rest as replication backups that refuse
    the trainer dataplane until a genuinely failed-over client
    promotes them. ``rejoin=True`` (env ``PADDLE_PS_REJOIN``, set by
    the launch supervisor on a server relaunch) starts the server as
    an un-caught-up backup that first pulls a manifest-verified
    snapshot from the active server."""

    _DEDUPE_CAP = 512  # distinct live client nonces remembered

    # rpcs that belong to trainers (gated on primary role); everything
    # else — heartbeat, replication, catch-up, shutdown — any role
    # answers
    _DATAPLANE = ("send_grad", "send_barrier", "get_param",
                  "fetch_barrier", "pull_sparse", "push_sparse")

    def __init__(self, endpoint: str, executor, scope, grad_to_block,
                 fanin: int = 1, sync_mode: bool = True,
                 evict_after: Optional[float] = None,
                 endpoints: Optional[List[str]] = None,
                 rejoin: Optional[bool] = None):
        host, port = endpoint.rsplit(":", 1)
        self._executor = executor
        self._scope = scope
        self._grad_to_block = grad_to_block
        self._fanin = max(int(fanin), 1)
        self._sync = bool(sync_mode)
        # -- replication topology -----------------------------------------
        if endpoints is None:
            endpoints = _endpoints_from_env()
        self._endpoints = [e.strip() for e in (endpoints or [])
                           if e.strip()]
        self._own_endpoint = endpoint
        try:
            self._index = self._endpoints.index(endpoint)
        except ValueError:
            self._index = 0
            self._endpoints = [endpoint]
        if rejoin is None:
            rejoin = os.environ.get("PADDLE_PS_REJOIN") == "1"
        self._rejoin = bool(rejoin)
        self._active = (self._index == 0 and not self._rejoin)
        self._promoted = False
        self._caught_up = not self._rejoin
        self._applied_round = 0
        # cid -> highest seq whose effect is folded into the replicated
        # state this server holds: a failover replay at-or-below it is
        # acknowledged without re-executing (exactly-once across the
        # promotion)
        self._repl_watermark: Dict[str, int] = {}
        # the watermark AS OF THE LAST APPLIED ROUND — the only thing
        # ever shipped to backups. The live ``_last_seq`` also covers
        # rpcs buffered in the CURRENT unapplied round (a join_backup
        # can land mid-round); shipping those would make a promoted
        # backup falsely skip their replay and lose the round.
        self._applied_watermark: Dict[str, int] = {}
        self._repl_clients: Dict[str, "PSClient"] = {}
        self._repl_dead: set = set()
        self._repl_deadline = float(
            os.environ.get("PADDLE_PS_REPL_DEADLINE", "10"))
        self._repl_connect = float(
            os.environ.get("PADDLE_PS_REPL_CONNECT_TIMEOUT", "3"))
        if evict_after is None:
            evict_after = float(os.environ.get("PADDLE_PS_EVICT_AFTER",
                                               "0"))
        self._evict_after = float(evict_after)
        self.monitor = HeartBeatMonitor(
            stale_seconds=self._evict_after if self._evict_after > 0
            else 60.0)
        self._evicted: set = set()
        self._clock_started = False
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # var name -> {trainer_id: grad}: keyed (not appended) so a
        # relaunched trainer RE-SENDING the round it died in REPLACES
        # its dead incarnation's contribution instead of double
        # counting it, and summed in sorted-tid order so the applied
        # total is bit-deterministic regardless of arrival order
        self._pending: Dict[str, Dict[int, np.ndarray]] = {}
        self._send_barriers = 0
        self._fetch_barriers = 0
        self._round_complete = True   # params servable before round 1
        self._fetches_pending = False  # True between apply and last fetch
        # per-client (token, response) cache: the client resends after a
        # reconnect; without dedupe a response lost AFTER server-side
        # processing would double-apply a grad/barrier in the round.
        # Keyed by the client's random nonce (NOT trainer_id: the
        # background heartbeater is a second connection with the same
        # trainer_id, and sharing one slot would let its traffic evict
        # the main client's in-flight entry mid-retry).
        self._dedupe: Dict[str, list] = {}   # cid -> [key, ev, resp, raw, ts]
        self._last_seq: Dict[str, int] = {}  # cid -> highest seq admitted
        self._dedupe_lock = threading.Lock()
        self._shutdown = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host or "127.0.0.1", int(port)))
        self._sock.listen(16)
        self._threads: List[threading.Thread] = []
        self._conns: set = set()
        self._conn_lock = threading.Lock()
        if self._evict_after > 0:
            t = threading.Thread(target=self._evict_loop,
                                 name="ps-evict-monitor", daemon=True)
            t.start()
            self._threads.append(t)
        if self._rejoin:
            t = threading.Thread(target=self._catchup_loop,
                                 name="ps-catchup", daemon=True)
            t.start()
            self._threads.append(t)

    # -- round protocol ---------------------------------------------------

    def _effective_fanin(self) -> int:
        return max(1, self._fanin - len(self._evicted))

    def _apply_round(self):
        """All trainers' grads in (locked by caller): sum per var, run
        its optimize block, replicate the applied round to every live
        backup (acks REQUIRED before the round reads as complete — a
        promoted backup must never be behind a state any trainer has
        observed), then open params for reading."""
        nxt = self._applied_round + 1
        # begin/applied flight pair: a primary SIGKILLed mid-apply
        # leaves "ps.round_apply" with no matching "ps.round_applied"
        # in its last periodic dump — the postmortem's smoking gun
        _flight.record("ps.round_apply", round=nxt,
                       vars=len(self._pending))
        with _dtrace.child_span("ps.apply_round", cat="ps", round=nxt):
            for name in sorted(self._pending):
                by_tid = self._pending[name]
                tids = sorted(by_tid)
                total = by_tid[tids[0]]
                for t in tids[1:]:
                    total = total + by_tid[t]
                self._executor._write_var(self._scope, name, total)
                sub = self._grad_to_block.get(name)
                if sub is not None:
                    self._executor.run_block(sub, self._scope)
            self._pending.clear()
            self._send_barriers = 0
            self._applied_round += 1
            # safe point for a watermark snapshot: every processed
            # send-kind seq is now folded into the scope (trainers
            # cannot have sent next-round traffic — their barriers
            # haven't returned yet)
            self._applied_watermark = self._watermark_locked()
            self._replicate_locked()
        _flight.record("ps.round_applied", round=self._applied_round)
        self._round_complete = True
        self._fetches_pending = True
        self._cond.notify_all()

    # -- replication (primary -> backups) ---------------------------------

    def _repl_targets(self) -> List[str]:
        return [ep for ep in self._endpoints
                if ep != self._own_endpoint and ep not in self._repl_dead]

    def _repl_client(self, ep: str) -> "PSClient":
        c = self._repl_clients.get(ep)
        if c is None:
            c = PSClient(ep, trainer_id=None, auto_heartbeat=False,
                         timeout=self._repl_connect,
                         rpc_deadline=self._repl_deadline,
                         max_retries=int(os.environ.get(
                             "PADDLE_PS_REPL_RETRIES", "3")))
            self._repl_clients[ep] = c
        return c

    def _scope_blobs(self):
        """(headers, raw) for every tensor var in the scope — the
        post-round replication payload (full blobs, bit-exact by
        construction; delta streaming is a named ROADMAP follow-up)."""
        headers, chunks = [], []
        for name in list(self._scope.local_var_names()):
            val = self._executor._read_var(self._scope, name)
            if val is None or not hasattr(val, "shape"):
                continue
            arr = np.ascontiguousarray(np.asarray(val))
            h = _array_header(arr)
            h["name"] = name
            headers.append(h)
            chunks.append(arr.tobytes())
        return headers, b"".join(chunks)

    def _watermark_locked(self) -> Dict[str, int]:
        """Per-cid seq watermark covering every rpc folded into the
        state being replicated (own processed seqs plus any watermark
        this server itself inherited through a promotion)."""
        with self._dedupe_lock:
            wm = dict(self._last_seq)
        for cid, s in self._repl_watermark.items():
            if int(wm.get(cid, 0)) < int(s):
                wm[cid] = int(s)
        return wm

    def _replicate_locked(self) -> None:
        """Stream the just-applied round to every live backup and wait
        for each ack (locked by caller — the round stays incomplete,
        and unfetchable, until the backups hold it). A backup that
        fails the short replication deadline is dropped from the
        stream (its lag gauge freezes; a relaunch re-enters via
        join_backup)."""
        if not self._sync or not self._active_role():
            return
        targets = self._repl_targets()
        if not targets:
            return
        headers, raw = self._scope_blobs()
        wm = self._applied_watermark
        for ep in targets:
            _gauge("ps.replication_lag_rounds", backup=ep).set(1)
            try:
                self._repl_client(ep).replicate(
                    self._applied_round, headers, raw, wm)
                _gauge("ps.replication_lag_rounds", backup=ep).set(0)
            except (RuntimeError, OSError) as e:
                self._repl_dead.add(ep)
                _flight.record("ps.backup_dropped", backup=ep,
                               round=self._applied_round)
                try:
                    self._repl_clients.pop(ep).close()
                except (KeyError, OSError):
                    pass
                print("[ps_rpc] dropping backup %s from the replication"
                      " stream at round %d: %s"
                      % (ep, self._applied_round, e),
                      file=sys.stderr, flush=True)

    def _active_role(self) -> bool:
        return self._active or self._promoted

    def _promote_locked(self, kind: str) -> None:
        """A genuinely failed-over client reached this backup: become
        the primary (deterministic — clients walk the endpoint list in
        order, so the lowest-index live endpoint wins) and start
        streaming to the remaining backups."""
        self._promoted = True
        self._repl_dead.discard(self._own_endpoint)
        # the state this server holds = the replicated rounds; its
        # folded-seq watermark is exactly the inherited one
        self._applied_watermark = dict(self._repl_watermark)
        _counter("ps.promotions").inc()
        _flight.record("ps.promotion", round=self._applied_round,
                       index=self._index, endpoint=self._own_endpoint,
                       rpc=kind)
        print("[ps_rpc] endpoint %s (index %d) promoted to primary at "
              "round %d (first failover rpc: %s)"
              % (self._own_endpoint, self._index, self._applied_round,
                 kind), file=sys.stderr, flush=True)

    # -- rejoin catch-up (relaunched server -> backup) --------------------

    def _catchup_loop(self) -> None:
        """Probe the endpoint list for the active server, pull a
        manifest-verified snapshot (join_backup also splices this
        server back into the replication stream, atomically with the
        snapshot), load it, and open for replication traffic."""
        import shutil
        import tempfile

        t0 = time.monotonic()
        while not self._shutdown.is_set():
            for ep in self._endpoints:
                if ep == self._own_endpoint or self._shutdown.is_set():
                    continue
                probe = None
                d = None
                try:
                    probe = PSClient(ep, trainer_id=None,
                                     auto_heartbeat=False, timeout=2.0,
                                     rpc_deadline=30.0, max_retries=0)
                    st, _ = probe._call({"kind": "repl_status"})
                    if not st.get("active"):
                        continue
                    d = tempfile.mkdtemp(prefix="ps_catchup_")
                    resp, _ = probe._call({
                        "kind": "join_backup", "dir": d,
                        "endpoint": self._own_endpoint})
                    from ..checkpoint import load_scope_snapshot

                    with self._lock:
                        # replication may already have raced past the
                        # snapshot (we were spliced into the stream the
                        # instant it was taken) — newer full blobs win
                        if self._applied_round <= int(resp["round"]):
                            load_scope_snapshot(self._executor,
                                                self._scope, d)
                            self._applied_round = int(resp["round"])
                        for cid, s in (resp.get("watermark")
                                       or {}).items():
                            if int(self._repl_watermark.get(cid, 0)) \
                                    < int(s):
                                self._repl_watermark[cid] = int(s)
                        self._pending.clear()
                        self._send_barriers = 0
                        self._fetch_barriers = 0
                        self._round_complete = True
                        self._fetches_pending = False
                        self._caught_up = True
                    _histogram("ps.catchup_ms").observe(
                        (time.monotonic() - t0) * 1e3)
                    _flight.record("ps.rejoin",
                                   round=self._applied_round, via=ep)
                    print("[ps_rpc] endpoint %s rejoined as backup at "
                          "round %d (caught up from %s in %.0f ms)"
                          % (self._own_endpoint, self._applied_round,
                             ep, (time.monotonic() - t0) * 1e3),
                          file=sys.stderr, flush=True)
                    return
                except (RuntimeError, OSError, KeyError, ValueError) \
                        as e:
                    print("[ps_rpc] rejoin catch-up attempt via %s "
                          "failed (will retry): %s" % (ep, e),
                          file=sys.stderr, flush=True)
                    continue
                finally:
                    if probe is not None:
                        probe.close()
                    if d is not None:
                        # failed attempts must not leave a snapshot
                        # dir per 0.5s retry during a long outage
                        shutil.rmtree(d, ignore_errors=True)
            self._shutdown.wait(0.5)

    def _wait_for(self, predicate, what: str):
        """Bounded condition wait (locked by caller); surfaces stale
        trainers instead of hanging forever when a rank died."""
        deadline = time.time() + _ROUND_TIMEOUT
        while not predicate():
            if self._shutdown.is_set():
                raise RuntimeError("pserver shut down mid-round")
            if time.time() > deadline:
                raise RuntimeError(
                    "PS round stalled waiting for %s (fanin=%d); stale "
                    "trainers by heartbeat: %s"
                    % (what, self._fanin, self.monitor.stale_trainers()))
            self._cond.wait(timeout=1.0)

    # -- eviction (heart_beat_monitor.h semantics) ------------------------

    def _evict_loop(self):
        period = max(self._evict_after / 4.0, 0.05)
        while not self._shutdown.wait(period):
            stale = self.monitor.stale_trainers()
            if not stale:
                continue
            with self._lock:
                for t in stale:
                    if t not in self._evicted:
                        self._evict_locked(t)

    def _evict_locked(self, trainer_id: int) -> None:
        """Remove a dead trainer from the round math (locked by
        caller): shrink the effective fanin and re-check both barriers
        — the survivors may already have everyone-still-alive's
        contributions in, in which case the round completes NOW."""
        self._evicted.add(trainer_id)
        self.monitor.forget(trainer_id)
        _counter("ps.evictions").inc()
        _flight.record("ps.eviction", trainer=trainer_id,
                       effective_fanin=self._effective_fanin())
        print("[ps_rpc] evicting trainer %d (silent > %.1fs); "
              "effective fanin now %d"
              % (trainer_id, self._evict_after, self._effective_fanin()),
              file=sys.stderr, flush=True)
        eff = self._effective_fanin()
        if not self._round_complete and self._send_barriers >= eff:
            self._apply_round()
        if self._fetches_pending and self._fetch_barriers >= eff:
            self._fetch_barriers = 0
            self._fetches_pending = False
        self._cond.notify_all()

    def _readmit(self, trainer_id: int) -> None:
        with self._lock:
            if trainer_id in self._evicted:
                self._evicted.discard(trainer_id)
                _counter("ps.readmissions").inc()
                _flight.record("ps.readmission", trainer=trainer_id)
                print("[ps_rpc] re-admitting trainer %d; effective "
                      "fanin now %d"
                      % (trainer_id, self._effective_fanin()),
                      file=sys.stderr, flush=True)

    def _handle(self, msg: dict, raw: bytes):
        """Returns (response_dict, response_raw)."""
        kind = msg["kind"]
        if kind in self._DATAPLANE and not self._active_role():
            # backup role: only a client that genuinely failed over
            # (fo >= 1 — it watched the previous endpoint die) may
            # promote this server; a FRESH client (a relaunched
            # trainer walking the list from index 0) is redirected so
            # a rejoined server can never split the brain with the
            # live primary. An un-caught-up rejoiner redirects
            # unconditionally — serving stale params is worse than a
            # redirect hop.
            with self._lock:
                if (not self._caught_up
                        or int(msg.get("fo", 0)) < 1
                        # a backup that fell off the stream must never
                        # be promoted by a client that has OBSERVED a
                        # newer round than it holds — better no
                        # primary (loud failure) than a stale one
                        # (silent param regression)
                        or int(msg.get("round", 0))
                        > self._applied_round):
                    return {"ok": False, "not_primary": True,
                            "error": "endpoint %s is a backup (index "
                            "%d, caught_up=%s, round %d vs client "
                            "round %s), not the primary"
                            % (self._own_endpoint, self._index,
                               self._caught_up, self._applied_round,
                               msg.get("round"))}, b""
                if not self._active_role():
                    self._promote_locked(kind)
        if "trainer_id" in msg:
            tid = int(msg["trainer_id"])
            if self._evict_after > 0 and not self._clock_started:
                # first sign of life from ANY trainer arms the clock
                # for every expected rank (0..fanin-1) — not at server
                # construction, or slow worker startup (interpreter +
                # jax import) would read as death before round 1
                self._clock_started = True
                self.monitor.register(range(self._fanin))
            self.monitor.ping(tid)
            # an evicted trainer that TRAINS again (a supervised
            # relaunch) rejoins the round math; a mere heartbeat from a
            # zombie must not grow the fanin back
            if tid in self._evicted and kind in (
                    "send_grad", "send_barrier", "get_param",
                    "fetch_barrier", "pull_sparse", "push_sparse"):
                self._readmit(tid)
        if kind == "send_grad":
            arr = _array_from(msg["array"], raw)
            with self._lock:
                if self._sync:
                    self._pending.setdefault(
                        msg["name"], {})[int(msg.get("trainer_id",
                                                     0))] = arr
                else:  # async: apply immediately (RunAsyncLoop)
                    self._executor._write_var(self._scope, msg["name"],
                                              arr)
                    sub = self._grad_to_block.get(msg["name"])
                    if sub is not None:
                        self._executor.run_block(sub, self._scope)
            return {"ok": True}, b""
        if kind == "send_barrier":
            with self._lock:
                # gate round N+1 on round N being fully fetched
                self._wait_for(lambda: not self._fetches_pending,
                               "previous round's fetch barriers")
                self._send_barriers += 1
                self._round_complete = False
                if self._send_barriers >= self._effective_fanin():
                    self._apply_round()
                else:
                    self._wait_for(lambda: self._round_complete,
                                   "all trainers' send barriers")
            return {"ok": True}, b""
        if kind == "get_param":
            with self._lock:
                if self._sync:
                    self._wait_for(lambda: self._round_complete,
                                   "the optimize round")
                val = self._executor._read_var(self._scope, msg["name"])
            if val is None:
                return {"ok": False,
                        "error": "no var %r" % msg["name"]}, b""
            arr = np.ascontiguousarray(np.asarray(val))
            return {"ok": True, "array": _array_header(arr)}, \
                arr.tobytes()
        if kind == "fetch_barrier":
            with self._lock:
                # only count toward an OPEN fetch window: a failover
                # replay of an already-satisfied barrier (the round it
                # closed arrived here via replication) must not
                # pre-pay the NEXT round's fetch count, or a later
                # round would unlatch with a trainer still mid-fetch
                if self._fetches_pending:
                    self._fetch_barriers += 1
                    if self._fetch_barriers >= self._effective_fanin():
                        self._fetch_barriers = 0
                        self._fetches_pending = False
                        self._cond.notify_all()
            return {"ok": True}, b""
        if kind == "pull_sparse":
            # sparse table pull (pslib PullSparseVarsSync,
            # fleet_wrapper.h:84): LOCAL row ids in, value rows out.
            # Deliberately NOT gated on the dense sync round: a pull
            # happens at FORWARD time, and waiting for _round_complete
            # here would deadlock two sync trainers (A's barrier waits
            # for B while B's pull waits for the round A opened) —
            # sparse tables are round-free in pslib, like the push.
            ids = _array_from(msg["array"], raw).reshape(-1)
            with self._lock:
                tbl = self._executor._read_var(self._scope, msg["name"])
            if tbl is None:
                return {"ok": False,
                        "error": "no table %r" % msg["name"]}, b""
            vals = np.ascontiguousarray(np.asarray(tbl)[ids])
            return {"ok": True, "array": _array_header(vals)}, \
                vals.tobytes()
        if kind == "push_sparse":
            # sparse grad push applied IMMEDIATELY (pslib
            # PushSparseVarsAsync semantics — downpour workers don't
            # gate sparse updates on the dense sync round). raw =
            # rows bytes + values bytes; rows are LOCAL to this shard.
            rh, vh = msg["rows"], msg["array"]
            nrows_bytes = int(np.dtype(rh["dtype"]).itemsize
                              * int(np.prod(rh["shape"])))
            rows = np.frombuffer(raw[:nrows_bytes],
                                 dtype=rh["dtype"]).reshape(-1)
            vals = _array_from(vh, raw[nrows_bytes:])
            from ..core.tensor import LoDTensor, SelectedRows

            with self._lock:
                tbl = self._executor._read_var(self._scope,
                                               msg.get("param", ""))
                height = (int(np.asarray(tbl).shape[0])
                          if tbl is not None else int(rows.max()) + 1)
                sr = SelectedRows(rows=rows.tolist(), height=height)
                sr._value = LoDTensor(vals)
                self._executor._write_var(self._scope, msg["name"], sr)
                sub = self._grad_to_block.get(msg["name"])
                if sub is not None:
                    self._executor.run_block(sub, self._scope)
            return {"ok": True}, b""
        if kind == "checkpoint":
            # checkpoint_notify_op.cc: snapshot every servable var into
            # the requested directory (reference tensor-stream format)
            with self._lock:
                snapshot_scope_to_dir(self._executor, self._scope,
                                      msg.get("dir", ""))
            return {"ok": True}, b""
        if kind == "replicate":
            # primary -> backup round stream: post-round blobs + the
            # dedup watermark, applied atomically with a round-state
            # reset so a promotion right after is a clean round start
            if self._active_role():
                return {"ok": False, "error":
                        "replicate sent to the active primary %s"
                        % self._own_endpoint}, b""
            off = 0
            with self._lock:
                for h in msg.get("vars", []):
                    n = int(np.dtype(h["dtype"]).itemsize
                            * int(np.prod(h["shape"]) if h["shape"]
                                  else 1))
                    self._executor._write_var(
                        self._scope, h["name"],
                        _array_from(h, raw[off:off + n]))
                    off += n
                # NB "round" is the dedup-token key _call stamps on
                # every message — the payload round travels separately
                self._applied_round = int(msg["repl_round"])
                for cid, s in (msg.get("watermark") or {}).items():
                    if int(self._repl_watermark.get(cid, 0)) < int(s):
                        self._repl_watermark[cid] = int(s)
                self._pending.clear()
                self._send_barriers = 0
                self._fetch_barriers = 0
                self._round_complete = True
                self._fetches_pending = False
                self._caught_up = True
            _flight.record("ps.replicated", round=self._applied_round)
            return {"ok": True, "round": self._applied_round}, b""
        if kind == "repl_status":
            return {"ok": True, "active": self._active_role(),
                    "caught_up": self._caught_up,
                    "round": self._applied_round,
                    "index": self._index}, b""
        if kind == "join_backup":
            # a relaunched server catching up: snapshot the scope into
            # its directory AND splice it back into the replication
            # stream in the same locked step, so every round applied
            # after the snapshot reaches it
            if not self._active_role():
                return {"ok": False, "error":
                        "join_backup sent to non-active endpoint %s"
                        % self._own_endpoint}, b""
            ep = msg.get("endpoint", "")
            with self._lock:
                snapshot_scope_to_dir(self._executor, self._scope,
                                      msg.get("dir", ""),
                                      names_map=True)
                # NOT the live _last_seq: a mid-round join must ship
                # the watermark of the state in the snapshot, or the
                # pending round's replays would be falsely skipped
                wm = dict(self._applied_watermark)
                if ep:
                    self._repl_dead.discard(ep)
                return {"ok": True, "round": self._applied_round,
                        "watermark": wm}, b""
        if kind == "heartbeat":
            with self._lock:
                evicted = sorted(self._evicted)
                eff = self._effective_fanin()
            return {"ok": True,
                    "status": {str(k): v
                               for k, v in
                               self.monitor.status().items()},
                    "evicted": evicted,
                    "fanin": self._fanin,
                    "effective_fanin": eff,
                    "active": self._active_role(),
                    "round": self._applied_round,
                    # process-wide counters, surfaced so an external
                    # probe (tests, the CI smoke) can assert on
                    # recovery without reaching into this process
                    "evictions": _counter("ps.evictions").value,
                    "readmissions": _counter("ps.readmissions").value,
                    "promotions": _counter("ps.promotions").value,
                    }, b""
        if kind == "shutdown":
            self._shutdown.set()
            with self._lock:
                self._cond.notify_all()
            return {"ok": True}, b""
        return {"ok": False, "error": "unknown kind %r" % kind}, b""

    def _traced_handle(self, msg: dict, raw: bytes):
        """Flight-record the incoming rpc token and run the handler
        under the client's propagated trace context (when the header
        carries one): the server span parents to the client's round /
        request span, and anything the handler does downstream — the
        optimize apply, a replication rpc to a backup — joins the same
        cross-process trace via the thread-local current context."""
        kind = msg.get("kind", "?")
        if kind not in _FLIGHT_QUIET:
            _flight.record("ps.rpc", kind=kind, cid=msg.get("cid"),
                           seq=msg.get("seq"), round=msg.get("round"),
                           fo=msg.get("fo"))
        tid, pspan = _dtrace.extract(msg)
        if tid is None:
            return self._handle(msg, raw)
        with _dtrace.child_span("rpc.server." + kind, trace_id=tid,
                                parent_span=pspan, cid=msg.get("cid"),
                                seq=msg.get("seq")):
            return self._handle(msg, raw)

    # -- socket plumbing --------------------------------------------------

    def _dispatch(self, msg: dict, raw: bytes):
        """Dedupe + handle one request. The client resends after a
        reconnect; a resend may arrive (a) after the original completed
        — return the cached response — or (b) while the original is
        STILL EXECUTING (it blocked in a barrier wait): wait on its
        completion event instead of running the handler twice, which
        would double-count a barrier / double-apply a grad. A resend of
        a request OLDER than the client's latest (a duplicated frame
        surfacing late) is answered with a stale marker and NEVER
        re-executed — the client discards the reply by seq anyway."""
        seq = msg.get("seq") if isinstance(msg, dict) else None
        cid = msg.get("cid") if isinstance(msg, dict) else None
        if seq is None or cid is None:
            return self._traced_handle(msg, raw)
        if (msg.get("kind") in ("send_grad", "send_barrier",
                                "push_sparse")
                and seq <= int(self._repl_watermark.get(cid, 0))):
            # failover replay of an rpc whose effect is already folded
            # into the replicated state this server holds (the
            # watermark travelled with the round stream / snapshot):
            # acknowledge without re-executing — exactly-once across
            # the promotion
            return {"ok": True, "replayed": True}, b""
        # the dedup token: the client's per-incarnation random nonce
        # (its trainer_id stand-in that survives nothing), the sync
        # round it believes it is in, and its per-connection sequence
        key = (msg.get("round", 0), seq)
        with self._dedupe_lock:
            cached = self._dedupe.get(cid)
            if cached is not None and cached[0] == key:
                ev = cached[1]
            elif seq <= self._last_seq.get(cid, 0):
                # duplicate of an ALREADY-SUPERSEDED request (a dup'd
                # frame surfacing after newer traffic): executing it
                # again would double-apply; its original response is
                # gone, so answer with a stale marker. (A legitimate
                # retry whose completed entry was LRU-pruned — >512
                # live cids between response loss and resend — also
                # lands here and fails loudly: exactly-once is kept at
                # the price of that narrow hard-fail; raise _DEDUPE_CAP
                # if a deployment actually churns that many clients.)
                return {"ok": False, "stale": True,
                        "error": "stale duplicate (seq %s <= %s)"
                        % (seq, self._last_seq.get(cid, 0))}, b""
            else:
                # dict insertion order doubles as the LRU order:
                # re-insert on every update so the oldest entry is
                # the longest-idle client
                self._last_seq.pop(cid, None)
                self._last_seq[cid] = int(seq)
                ev = threading.Event()
                self._dedupe[cid] = [key, ev, None, b"", time.time()]
                if len(self._dedupe) > self._DEDUPE_CAP:
                    self._prune_dedupe_locked()
                cached = None
        if cached is not None:  # duplicate: original owns the handler
            if not ev.wait(timeout=_ROUND_TIMEOUT):
                return {"ok": False,
                        "error": "duplicate request (cid %s seq %s) "
                        "still in flight" % (cid, seq)}, b""
            with self._dedupe_lock:
                c2 = self._dedupe.get(cid)
            if c2 is not None and c2[0] == key:
                return c2[2], c2[3]
            return {"ok": False, "stale": True,
                    "error": "dedupe entry superseded"}, b""
        try:
            resp, rraw = self._traced_handle(msg, raw)
        except Exception as e:
            resp, rraw = {"ok": False, "error": "%s: %s"
                          % (type(e).__name__, e)}, b""
        with self._dedupe_lock:
            ent = self._dedupe.get(cid)
            if ent is not None and ent[0] == key:
                ent[2], ent[3], ent[4] = resp, rraw, time.time()
        ev.set()
        return resp, rraw

    def _prune_dedupe_locked(self):
        """Cap the per-client caches: drop the least-recently-used
        completed RESPONSE entries (heartbeater clients come and go; an
        unbounded dict would grow with every incarnation). The tiny
        ``_last_seq`` watermark is kept much longer — pruning it with
        the response would re-open the stale-duplicate double-apply
        window for a still-live client — and is itself LRU-capped far
        above the response cache, where only long-dead clients fall
        off the end."""
        done = sorted(
            (cid for cid, e in self._dedupe.items() if e[1].is_set()),
            key=lambda c: self._dedupe[c][4])
        for cid in done[:max(0, len(self._dedupe) - self._DEDUPE_CAP)]:
            del self._dedupe[cid]
        while len(self._last_seq) > 16 * self._DEDUPE_CAP:
            self._last_seq.pop(next(iter(self._last_seq)))

    def _serve_conn(self, conn: socket.socket):
        with self._conn_lock:
            self._conns.add(conn)
        try:
            while not self._shutdown.is_set():
                got = _recv_msg(conn)
                if got is None:
                    return
                msg, raw = got
                # catch ANY handler error (malformed message, bad dtype,
                # missing keys) and reply — a dead connection thread
                # would leave the client blocked until its own timeout
                try:
                    resp, rraw = self._dispatch(msg, raw)
                except Exception as e:
                    resp, rraw = {"ok": False, "error": "%s: %s"
                                  % (type(e).__name__, e)}, b""
                if isinstance(msg, dict) and msg.get("seq") is not None:
                    # echo the token: the retrying client matches
                    # responses by seq and discards strays from dup'd
                    # frames
                    resp.setdefault("seq", msg.get("seq"))
                    resp.setdefault("cid", msg.get("cid"))
                if self._evict_after > 0:
                    # advertise the eviction deadline: clients of an
                    # eviction-armed server MUST heartbeat while their
                    # main socket is blocked in a barrier, or a healthy
                    # straggler round would read as death — the client
                    # auto-arms its heartbeater off this field
                    resp.setdefault("evict_after", self._evict_after)
                _send_msg(conn, resp, rraw)
        except OSError:
            pass
        finally:
            with self._conn_lock:
                self._conns.discard(conn)
            conn.close()

    def serve_forever(self) -> None:
        """Accept loop; returns after a shutdown message (the reference
        blocks inside the listen_and_serv op the same way)."""
        self._sock.settimeout(0.2)
        try:
            while not self._shutdown.is_set():
                try:
                    conn, _ = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break  # listening socket closed by stop()
                t = threading.Thread(target=self._serve_conn,
                                     args=(conn,), daemon=True)
                t.start()
                if len(self._threads) > 64:
                    # churning heartbeat clients reconnect forever;
                    # finished handler threads must not pile up
                    self._threads = [x for x in self._threads
                                     if x.is_alive()]
                self._threads.append(t)
        finally:
            try:
                self._sock.close()
            except OSError:
                pass

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever,
                             name="ps-accept", daemon=True)
        t.start()
        self._threads.append(t)
        return t

    def stop(self, join_timeout: float = 5.0) -> None:
        """Tear the server down NOW: wake blocked rounds, close the
        listening socket (the bound port is released even while a
        client is mid-frame), sever live connections, and join the
        worker threads. Idempotent; safe from any thread."""
        self._shutdown.set()
        with self._lock:
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass
        for c in list(self._repl_clients.values()):
            try:
                c.close()
            except OSError:
                pass
        self._repl_clients.clear()
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        me = threading.current_thread()
        deadline = time.time() + join_timeout
        for t in list(self._threads):
            if t is me or not t.is_alive():
                continue
            t.join(timeout=max(0.0, deadline - time.time()))


class _RetryableRPC(Exception):
    """Transport-level failure worth a reconnect-and-reissue."""


class _RPCTimeout(_RetryableRPC):
    pass


class _RPCConnLost(_RetryableRPC):
    pass


class _NotPrimary(_RetryableRPC):
    """The endpoint answered ``not_primary`` — advance along the
    endpoint list instead of burning the retry budget."""


class PSClient:
    """One persistent connection per (endpoint, trainer) —
    grpc_client.cc keeps channels the same way. Every call retries
    with bounded exponential backoff + jitter on timeout/EOF/conn loss
    (``PADDLE_PS_RPC_RETRIES``, default 3); the ``(cid, round, seq)``
    dedup token makes the resend of a non-idempotent rpc
    (send_grad/barriers) safe — the server executes it exactly once.

    ``endpoint`` may be a comma-separated primary + backups list
    (``PADDLE_PSERVER_ENDPOINTS``): when the retry budget on the
    current endpoint is exhausted by TRANSPORT failures, the client
    fails over to the next endpoint, replays its round log of
    non-idempotent rpcs with their original dedup tokens, and reissues
    the in-flight rpc (see the module docstring)."""

    _clients: Dict[tuple, "PSClient"] = {}
    _lock = threading.Lock()

    def __init__(self, endpoint: str, trainer_id: Optional[int] = 0,
                 timeout: Optional[float] = None,
                 auto_heartbeat: bool = True,
                 rpc_deadline: Optional[float] = None,
                 max_retries: Optional[int] = None):
        self._endpoints = [e.strip() for e in str(endpoint).split(",")
                           if e.strip()]
        if not self._endpoints:
            raise ValueError("PSClient needs at least one endpoint")
        self._ep_idx = 0
        self._trainer_id = trainer_id
        # auto-arm the background heartbeater when the server turns
        # out to be eviction-armed (its responses advertise
        # evict_after). Off for the heartbeater's own inner client.
        self._auto_heartbeat = bool(auto_heartbeat)
        self._timeout = timeout if timeout is not None else float(
            os.environ.get("PADDLE_PS_CONNECT_TIMEOUT", "15"))
        # per-ATTEMPT read deadline: must exceed the server round
        # timeout so only a dead/hung server trips it
        self._rpc_deadline = rpc_deadline if rpc_deadline is not None \
            else float(os.environ.get("PADDLE_PS_RPC_DEADLINE",
                                      str(_ROUND_TIMEOUT + 30.0)))
        self._max_retries = max_retries if max_retries is not None \
            else int(os.environ.get("PADDLE_PS_RPC_RETRIES", "3"))
        # failover budget: total endpoint advances per CALL (0 when
        # there is nowhere to go)
        self._max_failovers = int(os.environ.get(
            "PADDLE_PS_FAILOVER_MAX",
            str(2 * max(0, len(self._endpoints) - 1))))
        self._failover_count = 0  # the "fo" epoch carried on every rpc
        # non-idempotent rpcs of the round in flight, with their
        # stamped dedup tokens — replayed verbatim on a failover;
        # cleared when a send_barrier succeeds (the round is then
        # applied AND replicated, so its effects survive the primary).
        # Bounded: ASYNC mode never sends barriers, so without a cap
        # the log would grow with every gradient of the job — async
        # failover is best-effort (a documented gap), and the oldest
        # entries age out instead of leaking memory
        self._replay_log: List[tuple] = []
        self._replay_cap = int(
            os.environ.get("PADDLE_PS_REPLAY_LOG_CAP", "1024"))
        self._replay_overflowed = False
        self._backoff_base = float(
            os.environ.get("PADDLE_PS_RPC_BACKOFF_MS", "50")) / 1e3
        self._backoff_cap = float(
            os.environ.get("PADDLE_PS_RPC_BACKOFF_CAP_MS", "2000")) / 1e3
        # a failover probes endpoints that may be dead: use a short
        # connect window, not the boot-tolerant default
        self._failover_connect = float(os.environ.get(
            "PADDLE_PS_FAILOVER_CONNECT_TIMEOUT",
            str(min(self._timeout, 5.0))))
        self._io_lock = threading.Lock()
        self._seq = 0  # per-client sequence: lets the server dedupe the
        # reconnect-resend in _call (send_grad/barriers are not
        # idempotent without it). The random client nonce scopes seq so
        # a RESTARTED trainer's fresh seq=1 never matches a stale cache
        # entry from its previous incarnation.
        self._round = 0  # completed send_barriers (the dedup token's
        # round component: (cid, round, seq))
        self._cid = os.urandom(8).hex()
        # one TraceContext per sync round (regenerated when _round
        # advances): every rpc/retry/failover of the round rides one
        # cross-process trace. Only populated while spans are armed.
        self._trace_ctx = None
        self._trace_round = -1
        self._jitter = random.Random(int.from_bytes(os.urandom(4),
                                                    "little"))
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self.evicted_peers: set = set()
        try:
            self._sock = self._connect()
        except RuntimeError:
            if len(self._endpoints) == 1:
                raise
            # the primary may be down with a backup alive (a trainer
            # relaunched mid-failover): defer to the first _call,
            # whose failover path walks the rest of the list
            self._sock = None

    @property
    def _endpoint(self) -> str:
        return self._endpoints[self._ep_idx]

    @property
    def endpoint(self) -> str:
        """The endpoint currently in use (moves on failover)."""
        return self._endpoint

    def _connect(self, timeout: Optional[float] = None) -> socket.socket:
        host, port = self._endpoint.rsplit(":", 1)
        if timeout is None:
            timeout = self._timeout
        deadline = time.time() + timeout
        last: Optional[OSError] = None
        while True:  # the pserver process may still be booting
            try:
                sock = socket.create_connection(
                    (host or "127.0.0.1", int(port)),
                    timeout=max(timeout, 1.0))
                # reads get a DEADLINE above the server's round bound:
                # a functioning server always replies within
                # _ROUND_TIMEOUT (slow barriers get an error reply), so
                # a longer client deadline only fires when the server
                # is dead/hung mid-round — failing fast (then retrying
                # boundedly) instead of hanging the trainer's sync send
                # loop forever (grpc_client.cc deadline+retry).
                sock.settimeout(self._rpc_deadline)
                return sock
            except OSError as e:
                last = e
                if time.time() > deadline:
                    raise RuntimeError(
                        "cannot reach pserver %s within %.0fs (%r) — is "
                        "the pserver program (listen_and_serv) running, "
                        "with PADDLE_PSERVER_RPC=1 for cross-process "
                        "mode?" % (self._endpoint, timeout, last))
                time.sleep(0.2)

    @classmethod
    def for_endpoint(cls, endpoint: str, trainer_id: int = 0):
        with cls._lock:
            key = (endpoint, trainer_id)
            c = cls._clients.get(key)
            if c is None:
                c = cls(endpoint, trainer_id)
                cls._clients[key] = c
                hb_ms = os.environ.get("PADDLE_PS_HEARTBEAT_MS")
                if hb_ms:
                    c.start_heartbeat(float(hb_ms) / 1e3)
            return c

    @classmethod
    def reset(cls):
        with cls._lock:
            for c in cls._clients.values():
                c.close()
            cls._clients.clear()

    def close(self) -> None:
        self.stop_heartbeat()
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None

    # -- background heartbeat (keeps this trainer alive in the server's
    # monitor while the MAIN connection is blocked in a barrier) ---------

    def start_heartbeat(self, interval_s: float = 1.0) -> None:
        """Ping the server every ``interval_s`` from a dedicated
        connection; surfaces peer evictions (``evicted_peers``) with a
        log line so a surviving trainer knows why its barrier suddenly
        completed. Env ``PADDLE_PS_HEARTBEAT_MS`` auto-arms this for
        ``for_endpoint`` clients."""
        if self._hb_thread is not None and self._hb_thread.is_alive():
            return
        self._hb_stop.clear()

        def loop():
            hb = None
            hb_ep = None
            while not self._hb_stop.wait(interval_s):
                try:
                    if hb is not None and hb_ep != self._endpoint:
                        # the main client failed over: heartbeats must
                        # follow it — pinging the abandoned endpoint
                        # keeps nobody alive anywhere
                        hb.close()
                        hb = None
                    if hb is None:
                        hb_ep = self._endpoint
                        hb = PSClient(hb_ep,
                                      trainer_id=self._trainer_id,
                                      auto_heartbeat=False)
                    resp = hb.heartbeat_full()
                    evicted = {int(t) for t in resp.get("evicted", [])}
                    new = evicted - self.evicted_peers
                    self.evicted_peers |= evicted
                    for t in sorted(new):
                        print("[ps_rpc] pserver %s evicted trainer %d; "
                              "continuing with effective fanin %s"
                              % (self._endpoint, t,
                                 resp.get("effective_fanin")),
                              file=sys.stderr, flush=True)
                except Exception:
                    # best-effort: a failed ping must never kill the
                    # trainer; the next tick retries (fresh connection)
                    if hb is not None:
                        hb.close()
                    hb = None
            if hb is not None:
                hb.close()

        self._hb_thread = threading.Thread(
            target=loop, name="ps-heartbeat-%d" % self._trainer_id,
            daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        self._hb_stop.set()

    # -- request path -----------------------------------------------------

    def _attempt(self, msg: dict, raw: bytes):
        """One send + seq-matched receive on the cached socket; raises
        a _RetryableRPC on timeout/EOF/conn loss after dropping the
        socket (it may hold a late/partial reply — reusing it would
        desync framing or hand the NEXT call the OLD response)."""
        if self._sock is None:
            self._sock = self._connect()
        kind = msg.get("kind", "?")
        quiet = kind in _FLIGHT_QUIET
        t0 = time.perf_counter()
        if not quiet:
            _flight.record("rpc.send", kind=kind, seq=msg.get("seq"),
                           cid=msg.get("cid"), round=msg.get("round"),
                           fo=msg.get("fo"), ep=self._endpoint)
        deadline = time.time() + self._rpc_deadline
        try:
            _send_msg(self._sock, msg, raw)
            while True:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise socket.timeout("rpc deadline")
                self._sock.settimeout(remaining)
                got = _recv_msg(self._sock)
                if got is None:
                    raise _RPCConnLost(
                        "pserver %s closed the connection"
                        % self._endpoint)
                resp, resp_raw = got
                rseq = resp.get("seq") if isinstance(resp, dict) else None
                if rseq is not None and rseq != msg["seq"]:
                    continue  # stale reply from a dup'd earlier frame
                # per-ATTEMPT reply latency (retries observe
                # separately): the axis rpc.retries lacks — a rising
                # retry rate with healthy latencies means a mis-set
                # per-attempt deadline, not a slow server
                _histogram("rpc.latency_ms", method=kind).observe(
                    (time.perf_counter() - t0) * 1e3)
                if msg.get("trace_id"):
                    _dtrace.record_span(
                        "rpc.client." + kind, t0, cat="rpc",
                        trace_id=msg["trace_id"],
                        parent_span=msg.get("parent_span"),
                        endpoint=self._endpoint, seq=msg.get("seq"))
                if not quiet:
                    _flight.record("rpc.recv", kind=kind,
                                   seq=msg.get("seq"),
                                   ok=bool(resp.get("ok"))
                                   if isinstance(resp, dict) else None)
                return resp, resp_raw
        except socket.timeout:
            self._drop_sock()
            _counter("rpc.timeouts", method=kind).inc()
            if not quiet:
                _flight.record("rpc.timeout", kind=kind,
                               seq=msg.get("seq"), ep=self._endpoint)
            raise _RPCTimeout(
                "pserver %s did not reply within the %.0fs RPC deadline "
                "(kind=%s)" % (self._endpoint, self._rpc_deadline,
                               msg.get("kind"))) from None
        except _RPCConnLost:
            self._drop_sock()
            if not quiet:
                _flight.record("rpc.conn_lost", kind=kind,
                               seq=msg.get("seq"), ep=self._endpoint)
            raise
        except OSError as e:
            self._drop_sock()
            if not quiet:
                _flight.record("rpc.conn_lost", kind=kind,
                               seq=msg.get("seq"), ep=self._endpoint)
            raise _RPCConnLost("pserver %s connection failed: %s"
                               % (self._endpoint, e)) from e

    def _drop_sock(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None

    def _stamp_trace(self, msg: dict) -> None:
        """Propagate trace context on the rpc header (Dapper-style: it
        rides the existing JSON frame; old-frame peers ignore the extra
        fields). An ambient context — a serving request span, a
        server-side handler issuing replication — wins; otherwise the
        client keeps one trace per sync round so every rpc, retry, and
        failover of the round lands in a single cross-process trace.
        No-op (no id generation) while the span layer is disarmed."""
        from ..observability import tracing as _tracing

        if not _tracing.active():
            return
        ctx = _dtrace.current()
        if ctx is None:
            if self._trace_ctx is None \
                    or self._trace_round != self._round:
                self._trace_ctx = _dtrace.TraceContext.new()
                self._trace_round = self._round
            ctx = self._trace_ctx
        _dtrace.inject(msg, ctx)

    def _call(self, msg: dict, raw: bytes = b""):
        if self._trainer_id is not None:
            msg.setdefault("trainer_id", self._trainer_id)
        with self._io_lock:
            self._seq += 1
            msg["seq"] = self._seq
            msg["cid"] = self._cid
            msg["round"] = self._round
            msg["fo"] = self._failover_count
            self._stamp_trace(msg)
            if (len(self._endpoints) > 1 and msg["kind"] in
                    ("send_grad", "send_barrier", "push_sparse")):
                self._replay_log.append((dict(msg), bytes(raw)))
                if len(self._replay_log) > self._replay_cap:
                    self._replay_log.pop(0)
                    if not self._replay_overflowed:
                        self._replay_overflowed = True
                        print("[ps_rpc] replay log exceeded %d entries"
                              " (async mode?); oldest rpcs age out — a"
                              " failover replay will be PARTIAL (raise"
                              " PADDLE_PS_REPLAY_LOG_CAP if sync"
                              " rounds are really this large)"
                              % self._replay_cap,
                              file=sys.stderr, flush=True)
            resp, resp_raw = self._issue(msg, raw)
            if msg["kind"] == "send_barrier" and resp.get("ok"):
                # the barrier returned => the round is applied AND
                # replicated: its effects survive a primary death, so
                # nothing before this point ever needs replaying
                self._replay_log.clear()
        ea = resp.get("evict_after") if isinstance(resp, dict) else None
        if ea and self._auto_heartbeat and (
                self._hb_thread is None or not self._hb_thread.is_alive()):
            # the server evicts silent trainers: keep this one alive
            # while its main socket blocks in a barrier, even when the
            # operator forgot PADDLE_PS_HEARTBEAT_MS
            self.start_heartbeat(max(0.05, float(ea) / 4.0))
        if not resp.get("ok"):
            raise RuntimeError("pserver error: %s" % resp.get("error"))
        return resp, resp_raw

    def _issue(self, msg: dict, raw: bytes):
        """Bounded retry on the current endpoint; on exhaustion (or a
        ``not_primary`` redirect) advance along the endpoint list,
        replay the round log, and reissue — bounded by the failover
        budget. io-locked by caller."""
        kind = msg.get("kind", "?")
        attempts = 0
        failovers = 0
        delay = self._backoff_base
        last_err: Optional[Exception] = None
        while True:
            try:
                resp, resp_raw = self._attempt(msg, raw)
                if isinstance(resp, dict) and resp.get("not_primary"):
                    raise _NotPrimary(
                        "pserver %s is not the primary (%s)"
                        % (self._endpoint, resp.get("error")))
                return resp, resp_raw
            except _NotPrimary as e:
                # a redirect, not a transport failure: advance without
                # burning the retry budget
                last_err = e
                failovers += 1
                if failovers > self._max_failovers:
                    raise RuntimeError(
                        "%s — no endpoint in %s accepted the dataplane "
                        "after %d failover(s)"
                        % (e, self._endpoints, failovers - 1)) from e
                self._failover(e, msg, redirect=True)
                attempts, delay = 0, self._backoff_base
            except _RetryableRPC as e:
                attempts += 1
                last_err = e
                if attempts > self._max_retries:
                    failovers += 1
                    if failovers > self._max_failovers:
                        raise RuntimeError(
                            "%s — gave up after %d attempt(s); the "
                            "server is dead or hung (raise "
                            "PADDLE_PS_RPC_DEADLINE / "
                            "PADDLE_PS_RPC_RETRIES if rounds "
                            "legitimately run longer)"
                            % (e, attempts)) from e
                    self._failover(e, msg)
                    attempts, delay = 0, self._backoff_base
                    continue
                _counter("rpc.retries", method=kind).inc()
                # exponential backoff + jitter (grpc_client.cc
                # retry semantics); the dedup token makes the
                # reissue safe even for non-idempotent kinds
                time.sleep(delay * (0.5 + self._jitter.random()))
                delay = min(delay * 2.0, self._backoff_cap)
            except RuntimeError as e:
                # the RECONNECT inside a retry failed (server gone
                # or its backlog full of our own dead sockets)
                failovers += 1
                if failovers > self._max_failovers:
                    # keep the error that started the retrying — "why
                    # it failed" beats "why the retry failed"
                    if last_err is not None:
                        raise RuntimeError(
                            "%s (while reconnecting after: %s)"
                            % (e, last_err)) from e
                    raise
                self._failover(last_err if last_err is not None else e,
                               msg)
                attempts, delay = 0, self._backoff_base

    def _failover(self, cause: Exception, msg: dict,
                  redirect: bool = False) -> None:
        """Advance to the next endpoint that accepts a connection and
        the round-log replay (deterministic list order — the
        lowest-index live endpoint ends up promoted). Raises
        RuntimeError when no endpoint works."""
        n = len(self._endpoints)
        start = self._ep_idx
        self._failover_count += 1
        msg["fo"] = self._failover_count
        t0 = time.perf_counter()
        _flight.record("rpc.failover.begin",
                       frm=self._endpoints[start], fo=self._failover_count,
                       cause=type(cause).__name__,
                       redirect=bool(redirect))
        last: Exception = cause
        for k in range(1, n):
            self._ep_idx = (start + k) % n
            self._drop_sock()
            try:
                self._sock = self._connect(
                    timeout=self._failover_connect)
                self._replay()
            except (_RetryableRPC, RuntimeError, OSError) as e:
                last = e
                self._drop_sock()
                continue
            _counter("ps.failovers",
                     cause="redirect" if redirect else "transport").inc()
            _flight.record("rpc.failover", frm=self._endpoints[start],
                           to=self._endpoint, fo=self._failover_count,
                           replayed=len(self._replay_log))
            # the span the merged timeline shows the failover as (ISSUE
            # 5 acceptance): parented into the round trace the failed
            # rpc belongs to, covering connect + replay
            _dtrace.record_span(
                "ps.failovers", t0, cat="rpc",
                trace_id=msg.get("trace_id"),
                parent_span=msg.get("parent_span"),
                cause="redirect" if redirect else "transport",
                frm=self._endpoints[start], to=self._endpoint)
            print("[ps_rpc] trainer %s failed over %s -> %s "
                  "(replayed %d rpc(s); after: %s)"
                  % (self._trainer_id,
                     self._endpoints[start], self._endpoint,
                     len(self._replay_log), cause),
                  file=sys.stderr, flush=True)
            return
        self._ep_idx = start
        _flight.record("rpc.failover.failed", frm=self._endpoints[start],
                       fo=self._failover_count)
        raise RuntimeError(
            "no reachable pserver among %s (last failover error: %s; "
            "failing over after: %s)" % (self._endpoints, last, cause))

    def _replay(self) -> None:
        """Reissue the round log on the endpoint just connected, with
        the ORIGINAL dedup tokens: rpcs the new primary already holds
        (via replication) are acknowledged as ``replayed`` without
        re-executing; the rest rebuild the in-flight round."""
        _flight.record("rpc.replay", n=len(self._replay_log),
                       ep=self._endpoint)
        for m, r in list(self._replay_log):
            m["fo"] = self._failover_count
            delay = self._backoff_base
            for attempt in range(self._max_retries + 1):
                try:
                    resp, _ = self._attempt(m, r)
                    break
                except _RetryableRPC:
                    # transient fault on an otherwise-healthy new
                    # endpoint (e.g. an injected drop): retry HERE —
                    # advancing past it would abandon a live primary
                    if attempt >= self._max_retries:
                        raise
                    _counter("rpc.retries",
                             method=m.get("kind", "?")).inc()
                    time.sleep(delay * (0.5 + self._jitter.random()))
                    delay = min(delay * 2.0, self._backoff_cap)
            if resp.get("not_primary"):
                raise _NotPrimary(
                    "pserver %s refused the failover replay"
                    % self._endpoint)
            if not (resp.get("ok") or resp.get("replayed")
                    or resp.get("stale")):
                raise RuntimeError(
                    "pserver error during failover replay of %s: %s"
                    % (m.get("kind"), resp.get("error")))

    def send_grad(self, name: str, value) -> None:
        arr = np.ascontiguousarray(np.asarray(value))
        self._call({"kind": "send_grad", "name": name,
                    "array": _array_header(arr)}, arr.tobytes())

    def send_barrier(self) -> None:
        self._call({"kind": "send_barrier"})
        self._round += 1

    def get_param(self, name: str) -> np.ndarray:
        resp, raw = self._call({"kind": "get_param", "name": name})
        return _array_from(resp["array"], raw)

    def fetch_barrier(self) -> None:
        self._call({"kind": "fetch_barrier"})

    def pull_sparse(self, name: str, row_ids) -> np.ndarray:
        """Pull value rows for LOCAL row ids from this server's table
        shard (pslib PullSparseVarsSync counterpart)."""
        ids = np.ascontiguousarray(np.asarray(row_ids, dtype=np.int64))
        resp, raw = self._call({"kind": "pull_sparse", "name": name,
                                "array": _array_header(ids)},
                               ids.tobytes())
        return _array_from(resp["array"], raw)

    def push_sparse(self, name: str, rows, values, param: str = "") -> None:
        """Push (local row ids, grad rows) to this server's shard; the
        server applies its optimize block immediately (async, pslib
        PushSparseVarsAsync counterpart). ``param`` names the table var
        so the server can size the SelectedRows height."""
        rows = np.ascontiguousarray(np.asarray(rows, dtype=np.int64))
        vals = np.ascontiguousarray(np.asarray(values))
        self._call({"kind": "push_sparse", "name": name,
                    "param": param,
                    "rows": _array_header(rows),
                    "array": _array_header(vals)},
                   rows.tobytes() + vals.tobytes())

    def checkpoint(self, dirname: str) -> None:
        """Ask the server to snapshot its vars (checkpoint_notify)."""
        self._call({"kind": "checkpoint", "dir": dirname})

    def replicate(self, round_no: int, var_headers: List[dict],
                  raw: bytes, watermark: Dict[str, int]) -> None:
        """Primary-side: ship one applied round (post-round blobs +
        dedup watermark) to the backup this client points at; returns
        only on the backup's ack."""
        self._call({"kind": "replicate", "repl_round": int(round_no),
                    "vars": var_headers, "watermark": watermark}, raw)

    def repl_status(self) -> dict:
        """role/round probe: ``{"active":, "caught_up":, "round":}``."""
        resp, _ = self._call({"kind": "repl_status"})
        return resp

    def heartbeat(self) -> Dict[int, float]:
        resp, _ = self._call({"kind": "heartbeat"})
        return {int(k): v for k, v in resp["status"].items()}

    def heartbeat_full(self) -> dict:
        """Full heartbeat response: per-trainer ages plus ``evicted``
        / ``fanin`` / ``effective_fanin`` (the log-and-continue signal
        for survivors)."""
        resp, _ = self._call({"kind": "heartbeat"})
        return resp

    def shutdown_server(self) -> None:
        self._call({"kind": "shutdown"})
