"""Chaos drill: seeded randomized fault schedules against the
replicated (and sharded) PS job, gated on the bit-for-bit dedup
invariant.

Each drill derives, from one seed, a randomized schedule:

- a random ``PADDLE_TPU_FAULTS`` plan (``fault.random_plan`` — the
  recoverable drop/dup/delay menu),
- a random SIGKILL of one trainer at a random round (supervised
  relaunch + checkpoint resume), and
- a random SIGKILL of a PRIMARY pserver at a random round
  (lease expiry -> quorum election on the backup + client failover +
  replay + server rejoin).

It then runs the sync job under the launch supervisor and asserts the
final params match the CLEAN single-server computation bit-for-bit:
retry + ``(cid, round, seq)`` dedup + replication watermark must make
every gradient count exactly once, no matter which frames the
injector ate and which processes died.

ISSUE 8 modes:

- ``--shards 2`` — 2 key-range shard groups x (primary+backup); the
  schedule picks WHICH shard's primary dies. The two-phase round
  barrier must keep the sister shard's rounds intact (bit-for-bit per
  shard var), and the merged telemetry must show DELTA replication
  actually ran with ``ps.replication_bytes{mode=delta}`` strictly
  below the full-anchor bytes for the same workload.
- ``--partition`` (requires ``--shards 2``) — additionally severs the
  OTHER shard's primary<->backup pair with the ``partition`` fault
  primitive for the whole run. That shard's backup must see its lease
  expire and LOSE its elections (no quorum through a partition —
  ``ps.lease_expiries`` without a promotion), its primary must keep
  applying every round, and the job still exits 0 bit-for-bit:
  exactly one writable primary per shard, no split brain, no lost
  rounds — while the killed shard next door still promotes. This is
  the ISSUE 8 acceptance drill (SIGKILL + partition in one run).

The schedule is a pure function of the seed (``make_schedule``), so a
failing drill replays exactly: rerun with the printed seed.

Each drill also runs with ``PADDLE_TPU_METRICS_DIR`` armed and gates
on the job's merged telemetry: metrics.json + trace.json must exist,
the injected faults and the promotion must be visible, and the kill ->
failover -> promotion -> first-applied-round chain must read in causal
order across >= 3 processes (``check_telemetry``; the human-readable
version is printed via ``tools/ft_timeline.py``).

Usage: python tools/chaos_drill.py [--rounds 1] [--sync-rounds 6]
       [--seed 1234] [--shards N] [--partition]
"""
from __future__ import annotations

import argparse
import json
import os
import random
import socket
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dist_worker_ft.py")
if REPO not in sys.path:  # script-dir sys.path[0] is tools/
    sys.path.insert(0, REPO)
_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:  # imported by tests, not only run directly
    sys.path.insert(0, _TOOLS)

import ft_timeline  # noqa: E402 — the cross-process postmortem
from ft_smoke import oracle_w  # noqa: E402 — ONE bit-for-bit oracle


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def make_schedule(seed: int, sync_rounds: int = 6, shards: int = 1,
                  partition: bool = False) -> dict:
    """The randomized fault schedule as a pure function of the seed —
    two calls with the same args MUST return the same dict (asserted
    by tests/test_fault_tolerance.py). The legacy draws keep their
    order, so legacy schedules replay identically; shard draws come
    after."""
    from paddle_tpu.distributed import fault

    rng = random.Random(int(seed))
    hi = max(1, int(sync_rounds) - 1)
    sched = {
        "seed": int(seed),
        "sync_rounds": int(sync_rounds),
        "plan": fault.random_plan(rng),
        "trainer_kill_rank": rng.randint(0, 1),
        "trainer_kill_round": rng.randint(1, hi),
        "server_kill_round": rng.randint(1, hi),
        "shards": max(1, int(shards)),
        "partition": bool(partition),
    }
    sched["die_shard"] = (rng.randrange(sched["shards"])
                          if sched["shards"] > 1 else 0)
    # the partitioned pair must belong to a SURVIVING shard: the drill
    # separates "promotion must happen" (killed shard) from "promotion
    # must be quorum-denied" (partitioned shard)
    sched["partition_shard"] = (
        (sched["die_shard"] + 1) % sched["shards"]
        if sched["partition"] and sched["shards"] > 1 else None)
    return sched


def _groups(sched: dict, eps: list) -> list:
    """The shard -> endpoint-group mapping, from the ONE slicing
    implementation launch.py hands the servers — the drill's partition
    pair and telemetry gates must name exactly the processes the
    launcher built."""
    from paddle_tpu.distributed.ps_shard import split_endpoint_groups

    return split_endpoint_groups(eps, sched["shards"])


def _env(sched: dict, tmp: str, eps: list) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PADDLE_PS_HEARTBEAT_MS", None)
    plan = sched["plan"]
    if sched["partition_shard"] is not None:
        pg = _groups(sched, eps)[sched["partition_shard"]]
        # hard both-ways partition between that shard's primary and
        # backup for the WHOLE run: the backup must never win quorum
        plan = "%s,partition:1:%s|%s" % (plan, pg[0], pg[1])
    env.update({
        "FT_ROLE": "trainer",
        "PSERVER_ENDPOINT": ",".join(eps),
        "FT_ROUNDS": str(sched["sync_rounds"]),
        "FT_DIE_AT_ROUND": str(sched["trainer_kill_round"]),
        "FT_DIE_RANK": str(sched["trainer_kill_rank"]),
        "FT_SERVER_DIE_AT_ROUND": str(sched["server_kill_round"]),
        "FT_DIE_SHARD": str(sched["die_shard"]),
        "FT_OUT": os.path.join(tmp, "out"),
        "FT_CKPT_ROOT": os.path.join(tmp, "ckpt"),
        "PADDLE_TPU_FAULTS": plan,
        "PADDLE_TPU_FAULT_SEED": str(sched["seed"]),
        # the drill is gated on BIT-FOR-BIT parity with the clean run:
        # eviction deliberately trades exactness for availability
        # (survivor-only rounds diverge from the 2-trainer oracle), so
        # it is OFF here — the supervisor guarantees every death is
        # followed by a relaunch, and the sync barrier simply waits
        # for the relaunched rank to re-send its round (the dedup
        # keyed pending buffer makes the re-send idempotent)
        "PADDLE_PS_EVICT_AFTER": "0",
        # faults must be absorbed by RETRY, never converted into a
        # spurious failover off a healthy primary: a deep per-endpoint
        # retry budget keeps P(exhaustion by injected drops) ~ 0 while
        # a genuinely dead server still fails fast (conn refused)
        "PADDLE_PS_RPC_RETRIES": "12",
        "PADDLE_PS_RPC_BACKOFF_MS": "30",
        # short per-attempt deadline: a server-side recv.drop eats the
        # request frame, and only this deadline converts that silence
        # into a retry — at the default (round timeout + 30s) one
        # dropped frame would stall the whole round into eviction
        # territory. Retried barriers are safe: the dedup cache parks
        # the duplicate on the in-flight original. 12 x 8s also covers
        # every LEGITIMATE block (a barrier waiting out a ~3s relaunch)
        "PADDLE_PS_RPC_DEADLINE": "8",
        "PADDLE_PS_CONNECT_TIMEOUT": "4",
        "PADDLE_PS_FAILOVER_CONNECT_TIMEOUT": "3",
        "PADDLE_PS_REPL_DEADLINE": "5",
        # a short lease keeps the SIGKILLed shard's failover inside
        # the drill budget while still being >> one renewal period;
        # the partitioned shard's backup gets plenty of failed
        # elections to prove quorum denial
        "PADDLE_PS_LEASE_MS": "1200",
        # job-level telemetry: every process dumps registry + spans +
        # flight ring here (dir implies metrics armed); a short cadence
        # so even a SIGKILLed process leaves a fresh black box, and the
        # launch supervisor merges the lot into metrics.json +
        # trace.json at job end
        "PADDLE_TPU_METRICS_DIR": os.path.join(tmp, "metrics"),
        "PADDLE_TPU_DUMP_PERIOD": "0.5",
    })
    return env


def _rerun_hint(sched: dict) -> str:
    return ("tools/chaos_drill.py --seed %d --sync-rounds %d"
            "%s%s" % (sched["seed"], sched["sync_rounds"],
                      " --shards %d" % sched["shards"]
                      if sched["shards"] > 1 else "",
                      " --partition" if sched["partition"] else ""))


def run_drill(sched: dict) -> int:
    tmp = tempfile.mkdtemp(prefix="chaos_drill_")
    eps = ["127.0.0.1:%d" % _free_port()
           for _ in range(2 * sched["shards"])]
    print("[chaos] schedule %s" % json.dumps(sched, sort_keys=True))
    sup = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node=2", "--max_restarts=3",
         "--started_port=%d" % _free_port(),
         "--server_script=%s" % WORKER,
         "--pserver_shards=%d" % sched["shards"],
         "--pserver_endpoints=%s" % ",".join(eps), WORKER],
        env=_env(sched, tmp, eps), timeout=420, cwd=REPO)
    if sup.returncode != 0:
        print("[chaos] FAIL: job exited %d under schedule seed=%d "
              "(rerun: %s)" % (sup.returncode, sched["seed"],
                               _rerun_hint(sched)))
        return 1
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from dist_worker_ft import var_names

    names = var_names(sched["shards"])
    ok = True
    for tid in (0, 1):
        r = json.load(open(os.path.join(tmp, "out.t%d.json" % tid)))
        for vi, name in enumerate(names):
            expected = oracle_w(sched["sync_rounds"], var=vi)
            got = np.asarray(r["vars"][name], dtype=np.float32)
            bitwise = got.tobytes() == expected.tobytes()
            print("[chaos] %s: trainer %d var %s %s the clean run "
                  "(failovers=%s, evictions=%s)"
                  % ("PASS" if bitwise else "FAIL", tid, name,
                     "matches" if bitwise else "DIVERGES FROM",
                     r.get("failovers"), r.get("evictions")))
            ok = ok and bitwise
    ok = check_telemetry(sched, os.path.join(tmp, "metrics"), eps) and ok
    if not ok:
        print("[chaos] reproduce with: %s" % _rerun_hint(sched))
    return 0 if ok else 1


def check_telemetry(sched: dict, mdir: str, eps: list) -> bool:
    """The drill's second gate: the job must leave ONE merged picture
    in which the killed primary's SIGKILL, the trainers' failover, and
    the promoted backup's first applied round are visible in causal
    order across >= 3 processes; the injected faults must show up; and
    (ISSUE 8) delta replication must have carried the job with its
    bytes strictly below the full anchors', while a partitioned
    shard's backup shows lease expiries but NO promotion — at most one
    writable primary per shard."""
    ok = True

    def chk(what, passed):
        nonlocal ok
        print("[chaos] %s: %s" % ("PASS" if passed else "FAIL", what))
        ok = ok and passed

    # the postmortem itself (also re-merges metrics.json + trace.json)
    ft_timeline.print_postmortem(mdir, limit=40)
    mpath = os.path.join(mdir, "metrics.json")
    tpath = os.path.join(mdir, "trace.json")
    chk("job-level metrics.json + trace.json merged",
        os.path.exists(mpath) and os.path.exists(tpath))
    if not ok:
        return False
    merged = json.load(open(mpath))
    totals = merged["counters_total"]
    chk("merged metrics preserve per-rank sections (%d processes)"
        % len(merged["processes"]), len(merged["processes"]) >= 4)
    n_faults = sum(v for k, v in totals.items()
                   if k.startswith("fault.injected"))
    chk("injected faults visible in merged counters (%d)" % n_faults,
        n_faults > 0)
    trace = json.load(open(tpath))
    names = {}
    for ev in trace.get("traceEvents", []):
        names.setdefault(ev.get("name"), []).append(ev)
    chk("merged timeline has injected-fault events",
        bool(names.get("fault.injected")))
    chk("merged timeline has the promotion event",
        bool(names.get("ps.promotion")))

    # -- delta replication actually carried the job (ISSUE 8) ----------
    delta_b = totals.get("ps.replication_bytes{mode=delta}", 0)
    full_b = totals.get("ps.replication_bytes{mode=full}", 0)
    chk("delta rounds ran (ps.delta_rounds=%s)"
        % totals.get("ps.delta_rounds"),
        totals.get("ps.delta_rounds", 0) > 0)
    chk("delta bytes (%d) strictly below full-anchor bytes (%d)"
        % (delta_b, full_b), 0 < delta_b < full_b)

    # causal chain: kill -> failover -> promotion -> first applied
    # round on the promoted backup, across >= 3 distinct processes
    events = ft_timeline.load_events(mdir)

    def first(pred):
        for e in events:
            if pred(e):
                return e
        return None

    groups = _groups(sched, eps)
    died = set(groups[sched["die_shard"]])
    kill = first(lambda e: e["kind"] == "launch.exit"
                 and e["fields"].get("role") == "pserver"
                 and e["fields"].get("signal") == 9)
    fo = first(lambda e: e["kind"] == "rpc.failover.begin"
               and e["proc"].startswith("trainer"))
    promo = first(lambda e: e["kind"] == "ps.promotion"
                  and e["fields"].get("endpoint") in died)
    chk("supervisor observed the primary's SIGKILL", kill is not None)
    chk("a trainer failed over", fo is not None)
    chk("the killed shard's backup was promoted", promo is not None)
    if not ok:
        return False
    applied = first(lambda e: e["kind"] == "ps.round_applied"
                    and e["proc"] == promo["proc"]
                    and e["fields"].get("round")
                    == sched["server_kill_round"]
                    and e["t_us"] > promo["t_us"])
    chk("promoted backup (%s) applied the killed round %d"
        % (promo["proc"], sched["server_kill_round"]),
        applied is not None)
    if applied is not None:
        # lease-based promotion is PROACTIVE: the backup may win its
        # election (kill + ~one lease) before any trainer reaches it,
        # so failover and promotion are not ordered — but both must
        # precede the promoted backup re-applying the killed round
        chk("causal order: kill < promotion < first applied round",
            kill["t_us"] < promo["t_us"] < applied["t_us"])
        chk("trainers failed over before the round was rebuilt",
            fo["t_us"] < applied["t_us"])
        procs = {fo["proc"], promo["proc"], applied["proc"],
                 kill["proc"]}
        chk("chain spans >= 3 processes (%s)" % sorted(procs),
            len(procs) >= 3)

    # -- partition: quorum denied, exactly one writable primary --------
    if sched["partition_shard"] is not None:
        part = set(groups[sched["partition_shard"]])
        part_promos = [e for e in events if e["kind"] == "ps.promotion"
                       and e["fields"].get("endpoint") in part]
        lost = [e for e in events if e["kind"] == "ps.election"
                and e["fields"].get("endpoint") in part
                and not e["fields"].get("won")]
        expired = [e for e in events if e["kind"] == "ps.lease_expired"
                   and e["fields"].get("endpoint") in part]
        n_part = sum(v for k, v in totals.items()
                     if k.startswith("fault.injected{")
                     and "kind=partition" in k)
        chk("partition frames were actually eaten (%d)" % n_part,
            n_part > 0)
        chk("partitioned backup's lease expired (%d events)"
            % len(expired), len(expired) >= 1)
        chk("partitioned backup lost every election (%d lost, 0 won)"
            % len(lost), len(lost) >= 1)
        chk("NO promotion in the partitioned shard (split brain)",
            not part_promos)
        # no lost rounds: the partitioned shard's PRIMARY kept
        # applying to the end (its backup simply fell off the stream)
        part_applied = [e for e in events
                        if e["kind"] == "ps.round_applied"
                        and e["fields"].get("round")
                        == sched["sync_rounds"]]
        chk("final round %d applied on every shard (%d appliers)"
            % (sched["sync_rounds"], len(part_applied)),
            len(part_applied) >= sched["shards"])
    return ok


def main() -> int:
    ap = argparse.ArgumentParser("chaos_drill")
    ap.add_argument("--rounds", type=int, default=1,
                    help="number of randomized drills to run")
    ap.add_argument("--sync-rounds", type=int, default=6,
                    help="training rounds per drill")
    ap.add_argument("--shards", type=int, default=1,
                    help="key-range PS shard groups (each "
                         "primary+backup)")
    ap.add_argument("--partition", action="store_true",
                    help="also sever a surviving shard's "
                         "primary<->backup pair for the whole run "
                         "(requires --shards >= 2)")
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("PADDLE_TPU_FAULT_SEED",
                                               "1234")),
                    help="base seed (drill i uses seed + i)")
    args = ap.parse_args()
    if args.partition and args.shards < 2:
        ap.error("--partition needs --shards >= 2 (the partitioned "
                 "pair must belong to a shard that keeps training)")
    rc = 0
    for i in range(args.rounds):
        rc |= run_drill(make_schedule(args.seed + i, args.sync_rounds,
                                      shards=args.shards,
                                      partition=args.partition))
    if rc == 0:
        print("[chaos] ALL %d DRILL(S) PASS" % args.rounds)
    return rc


if __name__ == "__main__":
    sys.exit(main())
