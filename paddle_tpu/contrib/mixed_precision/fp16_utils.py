"""Program rewriting for mixed precision.

Parity: /root/reference/python/paddle/fluid/contrib/mixed_precision/
fp16_utils.py:156 (rewrite_program — cast insertion driven by the op
lists). TPU-native differences: the low dtype is bfloat16; parameters
stay float32 master copies with in-graph casts at their first bf16 use
(XLA folds/fuses the casts, and optimizer updates run on the f32
masters — no cast_parameters pass, no separate master-weight copies).
"""
from __future__ import annotations

from ... import framework
from ...core import dtypes as _dt

_FLOATS = ("float32", "bfloat16", "float16")


def _is_float(dtype_name: str) -> bool:
    return dtype_name in _FLOATS


def _cast_name(name: str, dest: str) -> str:
    return name + ".cast_" + dest


def insert_cast_op(block, new_ops, var, dest, cast_cache):
    """Emit (once per var) a cast of `var` to `dest`; return new name."""
    key = (var.name, dest)
    hit = cast_cache.get(key)
    if hit is not None:
        return hit
    out_name = _cast_name(var.name, dest)
    out = block.create_var(
        name=out_name, shape=var.shape, dtype=dest,
        stop_gradient=var.stop_gradient)
    op = framework.Operator(
        block, "cast",
        inputs={"X": [var.name]},
        outputs={"Out": [out_name]},
        attrs={"in_dtype": _dt.dtype_to_enum(var.dtype),
               "out_dtype": _dt.dtype_to_enum(dest)})
    op._id = block.program._next_op_id()
    new_ops.append(op)
    cast_cache[key] = out_name
    return out_name


def rewrite_program(main_prog, amp_lists, dest_dtype: str = "bfloat16"):
    """Walk the forward block, casting white-list op inputs to
    ``dest_dtype`` and black-list op inputs back to float32; gray ops
    follow their producers. Output var dtypes are updated in place."""
    block = main_prog.global_block()
    ops = list(block.ops)
    new_ops = []
    cast_cache = {}
    for op in ops:
        t = op.type
        if t in ("feed", "fetch", "cast"):
            new_ops.append(op)
            continue
        if t in amp_lists.black_list:
            target = "float32"
        elif t in amp_lists.white_list:
            target = dest_dtype
        elif t in amp_lists.gray_list:
            # follow inputs: low precision if ANY float input already is
            # (bf16 policy: keep the low-precision chain unbroken; params
            # riding along — e.g. fc bias — cast down at use. The
            # reference's fp16 rule is the conservative "all", guarding
            # fp16 overflow that bf16 does not have.)
            any_low = False
            for name in op.input_arg_names:
                v = block._find_var_recursive(name)
                if v is not None and v.dtype == dest_dtype:
                    any_low = True
                    break
            target = dest_dtype if any_low else "float32"
        else:
            # unknown/unsupported op: force float32 like reference black
            target = "float32"

        for slot, names in op.inputs.items():
            for i, name in enumerate(names):
                v = block._find_var_recursive(name)
                if v is None or not _is_float(v.dtype):
                    continue
                if v.dtype != target:
                    names[i] = insert_cast_op(block, new_ops, v, target,
                                              cast_cache)
        for name in op.output_arg_names:
            v = block._find_var_recursive(name)
            if v is not None and _is_float(v.dtype):
                v.dtype = _dt.convert_dtype(target)
        new_ops.append(op)
    block.ops = new_ops
    return main_prog
