"""Device places.

TPU-native analogue of the reference's tagged place variant
(/root/reference/paddle/fluid/platform/place.h). Instead of a C++ boost
variant dispatched per kernel, a Place here simply selects the JAX device
an op's arrays live on; XLA owns streams/layout so no DeviceContext pool
is needed.
"""
from __future__ import annotations

import functools


class Place:
    """Base place. Equality is (kind, device_id)."""

    kind = "undefined"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    # JAX integration -----------------------------------------------------
    @property
    def jax_platform(self) -> str:
        raise NotImplementedError

    def jax_device(self):
        """Resolve to a concrete jax.Device (lazily; import-cheap)."""
        import jax

        devs = _devices_for_platform(self.jax_platform)
        if not devs:
            raise RuntimeError(
                "No %s device available (jax backends: %s)"
                % (self.jax_platform, [d.platform for d in jax.devices()])
            )
        return devs[self._device_id % len(devs)]

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.kind == other.kind
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self.kind, self._device_id))

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self._device_id)


@functools.lru_cache(maxsize=None)
def _devices_for_platform(platform: str):
    """THIS process's devices only: under multi-process jax the global
    list includes other processes' (non-addressable) devices, and
    placing computation there produces arrays the process cannot read
    (every process's Place(0) must be its own first local chip)."""
    import jax

    if platform == "any_accelerator":
        # Prefer the default backend's devices (TPU if present).
        return tuple(jax.local_devices())
    try:
        # backend= keeps non-default backends reachable (CPUPlace on a
        # TPU host); plain local_devices() lists only the default one
        return tuple(jax.local_devices(backend=platform))
    except RuntimeError:
        return ()


class CPUPlace(Place):
    kind = "cpu"

    def __init__(self):
        super().__init__(0)

    @property
    def jax_platform(self):
        return "cpu"


class TPUPlace(Place):
    """The accelerator place. On hosts without a real TPU (unit tests on a

    virtual CPU mesh) it resolves to the default JAX backend, so programs
    written against TPUPlace run everywhere.
    """

    kind = "tpu"

    @property
    def jax_platform(self):
        return "any_accelerator"


# The reference exposes CUDAPlace; scripts being migrated may still name it.
# It is an alias of the accelerator place here.
CUDAPlace = TPUPlace


class CUDAPinnedPlace(CPUPlace):
    kind = "cpu_pinned"


def is_cpu_place(p):
    return isinstance(p, CPUPlace)


def is_tpu_place(p):
    return isinstance(p, TPUPlace)


def _current_expected_place_default():
    import jax

    dev = jax.devices()[0]
    return CPUPlace() if dev.platform == "cpu" else TPUPlace(0)
