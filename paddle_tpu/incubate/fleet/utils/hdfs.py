"""HDFS client for fleet jobs.

Parity: /root/reference/python/paddle/fluid/incubate/fleet/utils/
hdfs.py — the implementation lives in core/fs.py (the framework's
filesystem layer, reference framework/io/fs.cc); this module keeps the
reference import path."""
from ....core.fs import HDFSClient, LocalFS, split_files  # noqa: F401

__all__ = ["HDFSClient", "LocalFS", "split_files"]
