"""Profiler — thin compatibility shim over ``paddle_tpu.observability``.

Parity: /root/reference/python/paddle/fluid/profiler.py (:253 profiler
context manager, :129 start_profiler, :196 stop_profiler) + the C++
RecordEvent/DeviceTracer pair (platform/profiler.cc, device_tracer.cc).

The host-event machinery that used to live here (event table, trace
tuples, enable flag) moved into ``observability/tracing.py`` where every
execution path shares it; this module keeps the fluid API surface:
``RecordEvent`` spans feed the same buffer as all other runtime spans,
``start_profiler``/``stop_profiler`` bracket a *session* whose events
are drained into a snapshot on stop (sessions never bleed), and
``profiler(...)`` still prints the per-op host summary table.
Device-side tracing still delegates to jax.profiler (XPlane ->
TensorBoard / Perfetto), replacing the CUPTI DeviceTracer +
chrome-trace toolchain (tools/timeline.py).
"""
from __future__ import annotations

import contextlib

from .observability import tracing as _tracing

__all__ = ["cuda_profiler", "reset_profiler", "profiler", "start_profiler",
           "stop_profiler"]

_last_trace = []  # (name, ts_us, dur_us) snapshot of the finished session
_trace_dir = None


class RecordEvent:
    """RAII op-phase annotation (reference platform/profiler.cc:66) —
    now an observability span with cat='op'."""

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        self._span = _tracing.span(self.name, cat="op")
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        return self._span.__exit__(*exc)


def record_event(name):
    return RecordEvent(name)


def is_profiler_enabled():
    return _tracing.profiler_session_active()


def get_trace_events():
    """(name, ts_us, dur_us) host events for timeline export: the live
    session while profiling, else the last finished session's snapshot
    (stop_profiler drains live state so sessions never bleed)."""
    if _tracing.profiler_session_active():
        return [(n, ts, dur)
                for (n, ts, dur, _tid, _cat, _a)
                in _tracing.profiler_session_events()]
    return list(_last_trace)


def reset_profiler():
    # session-scoped: metrics-mode spans recorded by other subsystems
    # are not this API's to destroy
    _tracing.profiler_session_reset()


def start_profiler(state="All", tracer_option=None, trace_dir=None):
    global _trace_dir
    _trace_dir = trace_dir
    _tracing.profiler_session_start()
    if trace_dir:
        import jax

        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key="total", profile_path="/tmp/profile"):
    if _trace_dir:
        import jax

        jax.profiler.stop_trace()
    session, agg = _tracing.profiler_session_stop()
    # the aggregate side stays exact even when buffer pressure dropped
    # old spans mid-session; the timeline snapshot below is best-effort
    rows = sorted(((name, (count, total_us / 1e6))
                   for name, (count, total_us) in agg.items()),
                  key=lambda kv: -kv[1][1])
    if rows:
        print("%-40s %10s %14s %14s" % ("Event", "Calls", "Total(ms)", "Avg(ms)"))
        for name, (count, total) in rows[:50]:
            print("%-40s %10d %14.3f %14.3f"
                  % (name, count, total * 1e3, total * 1e3 / max(count, 1)))
    # snapshot so get_trace_events() after stop still serves the
    # finished session (the reference's DisableProfiler resets after
    # emitting)
    del _last_trace[:]
    _last_trace.extend((n, ts, dur) for (n, ts, dur, _t, _c, _a)
                       in session)


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path="/tmp/profile",
             tracer_option=None):
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    # name kept for API compatibility; delegates to the XLA trace
    with profiler():
        yield
