"""Developer tools (reference tools/)."""
