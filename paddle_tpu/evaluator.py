"""Graph-side evaluators (legacy surface).

Parity: /root/reference/python/paddle/fluid/evaluator.py — the
deprecated-but-shipped Evaluator classes (the reference's own docstring
points users at fluid.metrics). Each builds accumulation STATE VARS in
the program and appends update ops; ``eval()`` returns the aggregate.
Here ChunkEvaluator and EditDistance keep the same contract over the
chunk_eval / edit_distance ops; DetectionMAP lives in
layers/detection.py (stateful mAP) as the reference's detection variant
does.
"""
from __future__ import annotations

import numpy as np

from . import layers
from .layer_helper import LayerHelper

__all__ = ["ChunkEvaluator", "EditDistance"]


def _state_value(var, scope=None):
    """Read one accumulator state var. Pass the scope the program ran
    under when it was not the (default) global scope."""
    import paddle_tpu as fluid

    scope = scope or fluid.global_scope()
    v = scope.find_var(var.name)
    if v is None or not v.is_initialized():
        raise RuntimeError(
            "evaluator state %r not found in the scope; pass the scope "
            "the program ran under via eval(..., scope=...)" % var.name)
    return float(np.asarray(v.get_tensor().array).reshape(-1)[0])


class Evaluator:
    """Base: tracks metric state vars created in the main program
    (reference evaluator.py:41)."""

    def __init__(self, name, **kwargs):
        self.helper = LayerHelper(name, **kwargs)
        self.states = []
        self.metrics = []

    def reset(self, executor, reset_program=None):
        import paddle_tpu as fluid

        if reset_program is None:
            reset_program = fluid.Program()
        with fluid.program_guard(reset_program):
            for var in self.states:
                zeros = layers.fill_constant(
                    shape=[int(s) for s in (var.shape or (1,))],
                    dtype=var.dtype, value=0.0)
                layers.tensor.assign(zeros, var)
        executor.run(reset_program)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError

    def _create_state(self, suffix, dtype, shape):
        from . import framework

        var = self.helper.main_program.current_block().create_var(
            name=framework.unique_name.generate(
                "_".join([self.helper.layer_type, suffix])),
            dtype=dtype, persistable=True)
        var.shape = tuple(shape)
        self.states.append(var)
        return var


class ChunkEvaluator(Evaluator):
    """Accumulated chunk P/R/F1 across minibatches (reference
    evaluator.py:ChunkEvaluator over chunk_eval_op)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super().__init__("chunk_eval")
        main_program = self.helper.main_program
        if main_program.current_block().idx != 0:
            raise ValueError("You can only invoke Evaluator in root block")
        self.num_infer_chunks = self._create_state(
            "num_infer_chunks", "int64", (1,))
        self.num_label_chunks = self._create_state(
            "num_label_chunks", "int64", (1,))
        self.num_correct_chunks = self._create_state(
            "num_correct_chunks", "int64", (1,))
        (precision, recall, f1, num_infer, num_label,
         num_correct) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        layers.sums(
            input=[self.num_infer_chunks, num_infer],
            out=self.num_infer_chunks)
        layers.sums(
            input=[self.num_label_chunks, num_label],
            out=self.num_label_chunks)
        layers.sums(
            input=[self.num_correct_chunks, num_correct],
            out=self.num_correct_chunks)
        self.metrics.extend((precision, recall, f1))

    def eval(self, executor, eval_program=None, scope=None):
        ni = _state_value(self.num_infer_chunks, scope)
        nl = _state_value(self.num_label_chunks, scope)
        nc = _state_value(self.num_correct_chunks, scope)
        precision = nc / ni if ni else 0.0
        recall = nc / nl if nl else 0.0
        f1 = 2 * precision * recall / (precision + recall) if nc else 0.0
        return np.array([precision], np.float32), \
            np.array([recall], np.float32), np.array([f1], np.float32)


class EditDistance(Evaluator):
    """Accumulated average edit distance + instance error rate
    (reference evaluator.py:EditDistance over edit_distance_op)."""

    def __init__(self, input, label, ignored_tokens=None):
        super().__init__("edit_distance")
        self.total_distance = self._create_state(
            "total_distance", "float32", (1,))
        self.seq_num = self._create_state("seq_num", "int64", (1,))
        self.instance_error = self._create_state(
            "instance_error", "int64", (1,))
        distances, seq_num = layers.edit_distance(
            input=input, label=label, normalized=False,
            ignored_tokens=ignored_tokens)
        zero = layers.fill_constant(shape=[1], value=0.0, dtype="float32")
        compare_result = layers.greater_than(distances, zero)
        compare_result = layers.cast(compare_result, dtype="int64")
        instance_error = layers.reduce_sum(compare_result)
        instance_error = layers.reshape(instance_error, shape=[1])
        layers.sums(input=[self.total_distance,
                           layers.reshape(layers.reduce_sum(distances),
                                          shape=[1])],
                    out=self.total_distance)
        layers.sums(input=[self.seq_num, seq_num], out=self.seq_num)
        layers.sums(input=[self.instance_error, instance_error],
                    out=self.instance_error)

    def eval(self, executor, eval_program=None, scope=None):
        n = _state_value(self.seq_num, scope)
        avg = _state_value(self.total_distance, scope) / n if n else 0.0
        err = _state_value(self.instance_error, scope) / n if n else 0.0
        return np.array([avg], np.float32), np.array([err], np.float32)
