"""fluid.install_check.run_check() smoke test.

Parity: /root/reference/python/paddle/fluid/install_check.py — trains a
one-layer model for a couple of steps (single device, and a mesh run when
multiple devices are visible).
"""
from __future__ import annotations

import numpy as np


def run_check():
    import paddle_tpu as fluid

    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[2], dtype="float32")
        y = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    place = fluid.TPUPlace(0)
    exe = fluid.Executor(place)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(2):
            out = exe.run(prog, feed={"x": np.ones((4, 2), np.float32)},
                          fetch_list=[loss])
    print("Your paddle_tpu works well on SINGLE device.")
    import jax

    n = len(jax.devices())
    if n > 1:
        # a REAL mesh step: data-parallel compiled program on all
        # devices, loss must come back finite from every shard
        compiled = fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        scope2 = fluid.Scope()
        with fluid.scope_guard(scope2):
            exe.run(startup)
            (l,) = exe.run(compiled,
                           feed={"x": np.ones((4 * n, 2), np.float32)},
                           fetch_list=[loss])
        if not np.all(np.isfinite(np.asarray(l))):
            raise RuntimeError("multi-device check produced non-finite "
                               "loss: %r" % l)
        print("Your paddle_tpu works well on %d devices." % n)
    else:
        print("Multi-device check skipped: only one device visible.")
    print("install check passed.")
    return True
