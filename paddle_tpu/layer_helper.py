"""LayerHelper: the bridge from ``fluid.layers.*`` calls to Block ops.

Parity: /root/reference/python/paddle/fluid/layer_helper.py +
layer_helper_base.py — creates parameters (wired with initializer ops in
the startup program), temp variables, and appends ops to the current main
program. Dygraph mode routes through the eager tracer instead.
"""
from __future__ import annotations

from . import framework
from .core import dtypes as _dt
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr
from .utils import unique_name


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self._name_prefix = name if name is not None else layer_type

    # -- programs ---------------------------------------------------------
    @property
    def main_program(self):
        return framework.default_main_program()

    @property
    def startup_program(self):
        return framework.default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    def unique_var_name(self, key="tmp"):
        return unique_name.generate("%s_%s.%s" % (self._name_prefix, "", key)).replace(
            "_.", ".")

    # -- inputs -----------------------------------------------------------
    def input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if inputs is None:
            return []
        if isinstance(inputs, (list, tuple)):
            return list(inputs)
        # single tensor (static Variable or dygraph VarBase). Anything
        # else would otherwise be iterated — a VarBase iterates into
        # per-row traced slices, which is both wrong and pathological.
        return [inputs]

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def multiple_param_attr(self, length):
        attr = self.param_attr
        if isinstance(attr, ParamAttr):
            attr = [attr] + [ParamAttr(**attr.__dict__.copy()) for _ in range(length - 1)]
        return attr

    def input_dtype(self, input_param_name="input"):
        inputs = self.input(input_param_name)
        dtype = None
        for v in inputs:
            if dtype is None:
                dtype = v.dtype
            elif dtype != v.dtype:
                raise ValueError("mismatched input dtypes %s vs %s" % (dtype, v.dtype))
        return dtype

    # -- parameters / vars ------------------------------------------------
    def create_parameter(self, attr, shape, dtype=None, is_bias=False,
                         default_initializer=None, stop_gradient=False):
        if attr is False:
            return None
        attr = ParamAttr._to_attr(attr)
        if default_initializer is None:
            default_initializer = (
                ConstantInitializer(0.0) if is_bias else XavierInitializer()
            )
        attr._with_initializer(default_initializer)
        name = attr.name or unique_name.generate("%s.w" % self._name_prefix)

        if framework.in_dygraph_mode():
            from .dygraph.varbase import ParamBase

            tracer = framework._dygraph_tracer()
            existing = tracer.get_parameter(name)
            if existing is not None:
                return existing
            p = ParamBase.create(name, shape, dtype or "float32",
                                 attr.initializer, trainable=attr.trainable)
            tracer.register_parameter(p)
            return p

        startup_block = self.startup_program.global_block()
        main_block = self.main_program.global_block()
        if main_block.has_var_local(name):
            return main_block.vars[name]
        # declare in startup program + init op
        sp = startup_block.create_parameter(
            name=name,
            shape=shape,
            dtype=_dt.convert_dtype(dtype or "float32"),
            **{k: v for k, v in attr._to_kwargs().items() if k != "name"},
        )
        attr.initializer(sp, startup_block)
        # mirror into main program
        p = main_block.create_parameter(
            name=name,
            shape=shape,
            dtype=_dt.convert_dtype(dtype or "float32"),
            **{k: v for k, v in attr._to_kwargs().items() if k != "name"},
        )
        return p

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        if framework.in_dygraph_mode():
            from .dygraph.varbase import VarBase

            return VarBase(None, stop_gradient=stop_gradient)
        return self.block.create_var(
            name=unique_name.generate(".".join([self._name_prefix, "tmp"])),
            dtype=_dt.convert_dtype(dtype or "float32"),
            shape=None,
            persistable=False,
            stop_gradient=stop_gradient,
        )

    create_tmp_variable = create_variable_for_type_inference

    def create_global_variable(self, persistable=True, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, **kwargs
        )

    def create_or_get_global_variable(self, name, dtype, shape, persistable=True,
                                      belong_to_optimizer=False):
        gb = self.main_program.global_block()
        if gb.has_var_local(name):
            return gb.vars[name]
        return gb.create_var(name=name, dtype=dtype, shape=shape,
                             persistable=persistable)

    def set_variable_initializer(self, var, initializer):
        if framework.in_dygraph_mode():
            from .dygraph import base as dy_base

            return dy_base._init_eager_var(var, initializer)
        sb = self.startup_program.global_block()
        sv = sb.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                           persistable=True)
        return initializer(sv, sb)

    # -- ops --------------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None,
                  infer_shape=True):
        if framework.in_dygraph_mode():
            tracer = framework._dygraph_tracer()
            return tracer.trace_op(type, inputs or {}, outputs or {}, attrs or {})
        return self.block.append_op(type, inputs, outputs, attrs,
                                    infer_shape=infer_shape)

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.bias_attr
        if not bias_attr:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        b = self.create_parameter(attr=bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True)
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            "elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start},
        )
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(act_type, inputs={"X": [input_var]},
                       outputs={"Out": [tmp]}, attrs=act)
        return tmp
