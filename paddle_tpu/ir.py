"""Program-level IR graph + pass infrastructure.

Parity: /root/reference/paddle/fluid/framework/ir/ (Graph graph.h, Pass
pass.h, pass registry) and the Python ``IrGraph`` wrapper
(python/paddle/fluid/framework.py:3212).

TPU-native stance: the reference's 60+ C++ fusion passes exist because
its executor runs ops 1:1 — fusion must happen in the graph. Here XLA
fuses the compiled program, so this module is NOT a performance layer;
it is the *rewriting* substrate that program-transformation features
need (quantization-aware training, inference graph surgery, transpiler
tooling) with the same mutate-then-``to_program`` contract as the
reference. Nodes wrap the native Python IR directly — there is no
separate proto graph to round-trip through.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from . import framework


class IrVarNode:
    """Variable node (reference IrVarNode framework.py:2966)."""

    def __init__(self, graph, name: str, shape=None, dtype="float32",
                 persistable: bool = False, is_parameter: bool = False,
                 trainable: bool = True, stop_gradient: bool = False,
                 is_data: bool = False):
        self._graph = graph
        self._name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.persistable = persistable
        self.is_parameter = is_parameter
        self.trainable = trainable
        self.stop_gradient = stop_gradient
        self.is_data = is_data

    def name(self) -> str:
        return self._name

    def is_var(self) -> bool:
        return True

    def is_op(self) -> bool:
        return False

    @property
    def inputs(self) -> List["IrOpNode"]:
        """Ops that write this var."""
        return [op for op in self._graph.all_op_nodes()
                if self._name in op.output_arg_names()]

    @property
    def outputs(self) -> List["IrOpNode"]:
        """Ops that read this var."""
        return [op for op in self._graph.all_op_nodes()
                if self._name in op.input_arg_names()]

    def __repr__(self):
        return "IrVarNode(%s)" % self._name


class IrOpNode:
    """Operator node (reference IrOpNode framework.py:3059)."""

    def __init__(self, graph, op_type: str, inputs: Dict, outputs: Dict,
                 attrs: Optional[Dict] = None):
        self._graph = graph
        self._type = op_type
        self._inputs = {k: list(v) for k, v in inputs.items()}
        self._outputs = {k: list(v) for k, v in outputs.items()}
        self._attrs = dict(attrs or {})

    def name(self) -> str:
        return self._type

    def op_type(self) -> str:
        return self._type

    def is_var(self) -> bool:
        return False

    def is_op(self) -> bool:
        return True

    def input(self, slot: str) -> List[str]:
        return list(self._inputs.get(slot, []))

    def output(self, slot: str) -> List[str]:
        return list(self._outputs.get(slot, []))

    def input_slots(self):
        return dict(self._inputs)

    def output_slots(self):
        return dict(self._outputs)

    def input_arg_names(self) -> List[str]:
        return [n for v in self._inputs.values() for n in v]

    def output_arg_names(self) -> List[str]:
        return [n for v in self._outputs.values() for n in v]

    def attr(self, name: str):
        return self._attrs.get(name)

    def set_attr(self, name: str, value):
        self._attrs[name] = value

    def rename_input(self, old: str, new: str):
        for slot, names in self._inputs.items():
            self._inputs[slot] = [new if n == old else n for n in names]

    def rename_output(self, old: str, new: str):
        for slot, names in self._outputs.items():
            self._outputs[slot] = [new if n == old else n for n in names]

    @property
    def inputs(self) -> List[IrVarNode]:
        return [self._graph.var_node(n) for n in self.input_arg_names()
                if self._graph.has_var_node(n)]

    @property
    def outputs(self) -> List[IrVarNode]:
        return [self._graph.var_node(n) for n in self.output_arg_names()
                if self._graph.has_var_node(n)]

    def __repr__(self):
        return "IrOpNode(%s)" % self._type


class IrGraph:
    """Mutable graph view over a Program (reference framework.py:3212).

    Build with ``IrGraph(program)`` (or ``IrGraph.from_program``); mutate
    with create_*/safe_remove_nodes/rename; materialize back with
    ``to_program()`` — op order is the preserved program order with
    created ops appended before their first consumer.
    """

    def __init__(self, program=None, for_test: bool = False):
        self._for_test = for_test
        self._ops: List[IrOpNode] = []
        self._vars: Dict[str, IrVarNode] = {}
        self._startup_inits: List = []
        if program is not None:
            self._load(program)

    # -- construction -----------------------------------------------------
    @classmethod
    def from_program(cls, program, for_test: bool = False) -> "IrGraph":
        return cls(program, for_test=for_test)

    def _load(self, program):
        if len(program.blocks) > 1:
            raise NotImplementedError(
                "IrGraph covers single-block programs; this one has %d "
                "blocks (control-flow sub-blocks). Apply passes before "
                "adding While/cond, or rewrite sub-blocks explicitly."
                % len(program.blocks))
        block = program.global_block()
        for name, var in block.vars.items():
            self._vars[name] = IrVarNode(
                self, name, getattr(var, "shape", None),
                getattr(var, "dtype", "float32"),
                bool(getattr(var, "persistable", False)),
                is_parameter=isinstance(var, framework.Parameter),
                trainable=bool(getattr(var, "trainable", True)),
                stop_gradient=bool(getattr(var, "stop_gradient", False)),
                is_data=bool(getattr(var, "is_data", False)))
        for op in block.ops:
            self._ops.append(IrOpNode(self, op.type, dict(op.inputs),
                                      dict(op.outputs), dict(op.attrs)))

    # -- queries ----------------------------------------------------------
    def all_op_nodes(self) -> List[IrOpNode]:
        return list(self._ops)

    def all_var_nodes(self) -> List[IrVarNode]:
        return list(self._vars.values())

    def all_persistable_nodes(self) -> List[IrVarNode]:
        return [v for v in self._vars.values() if v.persistable]

    def has_var_node(self, name: str) -> bool:
        return name in self._vars

    def var_node(self, name: str) -> IrVarNode:
        if name not in self._vars:
            raise ValueError("var node %r not in graph" % name)
        return self._vars[name]

    # -- mutation ---------------------------------------------------------
    def create_var_node(self, name, var_type=None, shape=None,
                        var_dtype="float32") -> IrVarNode:
        node = IrVarNode(self, name, shape, var_dtype, persistable=False)
        self._vars[name] = node
        return node

    def create_persistable_node(self, name, var_type=None, shape=None,
                                var_dtype="float32") -> IrVarNode:
        node = IrVarNode(self, name, shape, var_dtype, persistable=True)
        self._vars[name] = node
        return node

    def create_op_node(self, op_type, attrs, inputs, outputs,
                       before: Optional[IrOpNode] = None) -> IrOpNode:
        """Insert an op node; by default right before the earliest
        consumer of any of its outputs (keeps def-before-use)."""
        node = IrOpNode(self, op_type, inputs, outputs, attrs)
        pos = len(self._ops)
        if before is not None:
            pos = self._ops.index(before)
        else:
            produced = set(node.output_arg_names())
            for i, op in enumerate(self._ops):
                if produced & set(op.input_arg_names()):
                    pos = i
                    break
        self._ops.insert(pos, node)
        return node

    def safe_remove_nodes(self, remove_nodes: Sequence):
        for n in remove_nodes:
            if isinstance(n, IrOpNode):
                if n in self._ops:
                    self._ops.remove(n)
            else:
                self._vars.pop(n.name(), None)

    def link_to(self, node_in, node_out):
        """Edges derive from op input/output names here — kept as a
        no-op for reference-API compatibility (passes call it after
        create_op_node)."""

    # -- init values for created persistables ------------------------------
    def set_initializer(self, var_name: str, value):
        """Record a host value for a created persistable; applied to the
        scope by Pass users / to_program callers."""
        self._startup_inits.append((var_name, value))

    @property
    def startup_inits(self):
        return list(self._startup_inits)

    # -- materialize -------------------------------------------------------
    def to_program(self):
        prog = framework.Program()
        block = prog.global_block()
        for name, v in self._vars.items():
            if v.is_parameter:
                var = block.create_parameter(
                    name=name, shape=v.shape, dtype=v.dtype,
                    trainable=v.trainable)
            else:
                var = block.create_var(name=name, dtype=v.dtype,
                                       persistable=v.persistable,
                                       stop_gradient=v.stop_gradient,
                                       is_data=v.is_data)
            if v.shape is not None:
                var.shape = tuple(v.shape)
        for op in self._ops:
            block.append_op(op.op_type(), op.input_slots(),
                            op.output_slots(), dict(op._attrs),
                            infer_shape=False)
        return prog

    def draw(self, save_path, name, marked_nodes=None,
             remove_ctr_var=True):
        """Graphviz dot export (reference uses the graph_viz_pass +
        dot binary; here we always write the .dot text)."""
        lines = ["digraph %s {" % name]
        for i, op in enumerate(self._ops):
            lines.append('  op%d [label="%s" shape=box];' % (i,
                                                             op.op_type()))
            for n in op.input_arg_names():
                lines.append('  "%s" -> op%d;' % (n, i))
            for n in op.output_arg_names():
                lines.append('  op%d -> "%s";' % (i, n))
        lines.append("}")
        import os

        path = os.path.join(save_path, "%s.dot" % name)
        with open(path, "w") as f:
            f.write("\n".join(lines))
        return path


class Pass:
    """Graph-rewriting pass base (reference ir/pass.h)."""

    name = "pass"

    def apply(self, graph: IrGraph) -> IrGraph:
        raise NotImplementedError

    def __call__(self, graph: IrGraph) -> IrGraph:
        return self.apply(graph)


class PassRegistry:
    _passes: Dict[str, type] = {}

    @classmethod
    def register(cls, pass_cls):
        cls._passes[pass_cls.name] = pass_cls
        return pass_cls

    @classmethod
    def get(cls, name: str) -> Pass:
        if name not in cls._passes:
            raise KeyError("pass %r not registered (have: %s)"
                           % (name, sorted(cls._passes)))
        return cls._passes[name]()

    @classmethod
    def has(cls, name: str) -> bool:
        return name in cls._passes


@PassRegistry.register
class GraphVizPass(Pass):
    """reference ir/graph_viz_pass.cc"""

    name = "graph_viz_pass"

    def __init__(self, save_path=".", graph_name="graph"):
        self.save_path = save_path
        self.graph_name = graph_name

    def apply(self, graph: IrGraph) -> IrGraph:
        graph.draw(self.save_path, self.graph_name)
        return graph


@PassRegistry.register
class FcFusePass(Pass):
    """mul + elementwise_add (+ activation) -> fc
    (reference ir/fc_fuse_pass.cc). Under XLA this is cosmetic — the
    compiler fuses the dot+add anyway — but inference-graph surgery and
    tests exercise the same rewrite contract as the reference."""

    name = "fc_fuse_pass"

    _ACTS = ("relu",)

    @staticmethod
    def _consumer_index(graph):
        idx: Dict[str, List[IrOpNode]] = {}
        for o in graph._ops:
            for n in o.input_arg_names():
                idx.setdefault(n, []).append(o)
        return idx

    def _is_fc_bias(self, graph, name) -> bool:
        """Only a persistable rank-1-ish bias qualifies (reference
        fc_fuse_pass matches a persistable [N] / [1, N] addend) —
        residual adds of activation tensors must NOT fuse."""
        if not graph.has_var_node(name):
            return False
        v = graph.var_node(name)
        if not v.persistable or v.shape is None:
            return False
        non_unit = [s for s in v.shape if s != 1]
        return len(non_unit) <= 1

    def apply(self, graph: IrGraph) -> IrGraph:
        consumers_of = self._consumer_index(graph)
        i = 0
        while i < len(graph._ops):
            op = graph._ops[i]
            if op.op_type() != "mul":
                i += 1
                continue
            out = op.output("Out")[0]
            consumers = consumers_of.get(out, [])
            if len(consumers) != 1 or \
                    consumers[0].op_type() != "elementwise_add":
                i += 1
                continue
            add = consumers[0]
            bias = (add.input("Y") if add.input("X") == [out]
                    else add.input("X"))[0]
            if not self._is_fc_bias(graph, bias):
                i += 1
                continue
            add_out = add.output("Out")[0]
            act = None
            act_consumers = consumers_of.get(add_out, [])
            if len(act_consumers) == 1 and \
                    act_consumers[0].op_type() in self._ACTS:
                act = act_consumers[0]
            final_out = act.output("Out")[0] if act else add_out
            fc = IrOpNode(graph, "fc",
                          {"Input": op.input("X"), "W": op.input("Y"),
                           "Bias": [bias]},
                          {"Out": [final_out]},
                          {"in_num_col_dims": op.attr("x_num_col_dims")
                           or 1,
                           "activation_type": act.op_type() if act
                           else ""})
            graph._ops[i] = fc
            graph.safe_remove_nodes([add] + ([act] if act else []))
            consumers_of = self._consumer_index(graph)
            i += 1
        return graph


class GraphPatternDetector:
    """Declarative subgraph matcher (reference
    ir/graph_pattern_detector.h PDPattern/PDNode + GraphPatternDetector).

    The reference builds a pattern of PDNodes with assert_is_op /
    LinksTo edges and runs subgraph isomorphism; here a pattern is a
    set of keyed op nodes plus slot-level edges, matched by
    backtracking (patterns are 2-5 ops, so the search is trivial)::

        d = GraphPatternDetector()
        d.op_node("conv", "conv2d")
        d.op_node("bn", "batch_norm")
        d.edge_out("conv", "Output", "conv_out")
        d.edge_in("bn", "X", "conv_out")
        for m in d.detect(graph):
            m["conv"], m["bn"]   # IrOpNodes
            m["conv_out"]        # var name
    """

    def __init__(self):
        self._op_nodes = []   # (key, op_type, predicate)
        self._edges = []      # (op_key, direction, slot, var_key)
        self._var_preds = {}  # var_key -> predicate(graph, name)

    # -- pattern construction ---------------------------------------------

    def op_node(self, key, op_type, predicate=None):
        self._op_nodes.append((key, op_type, predicate))
        return key

    def var_node(self, key, predicate=None):
        if predicate is not None:
            self._var_preds[key] = predicate
        return key

    def edge_out(self, op_key, slot, var_key):
        """op_key's output slot produces var_key (first name in slot)."""
        self._edges.append((op_key, "out", slot, var_key))

    def edge_in(self, op_key, slot, var_key):
        """op_key consumes var_key at input slot (first name)."""
        self._edges.append((op_key, "in", slot, var_key))

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def consumer_index(graph) -> Dict[str, List[IrOpNode]]:
        idx: Dict[str, List[IrOpNode]] = {}
        for o in graph.all_op_nodes():
            for n in o.input_arg_names():
                idx.setdefault(n, []).append(o)
        return idx

    # -- matching ----------------------------------------------------------

    def detect(self, graph: IrGraph):
        """Yield match dicts {key -> IrOpNode | var name}. Ops bind
        injectively; overlapping matches are all yielded — rewriting
        passes must skip ops they already consumed."""
        ops = graph.all_op_nodes()
        by_type: Dict[str, List[IrOpNode]] = {}
        for o in ops:
            by_type.setdefault(o.op_type(), []).append(o)

        def backtrack(i, bound_ops, bound_vars):
            if i == len(self._op_nodes):
                m = dict(bound_ops)
                m.update(bound_vars)
                yield m
                return
            key, op_type, pred = self._op_nodes[i]
            for cand in by_type.get(op_type, []):
                if cand in bound_ops.values():
                    continue
                if pred is not None and not pred(cand):
                    continue
                new_vars = dict(bound_vars)
                ok = True
                for op_key, direction, slot, var_key in self._edges:
                    if op_key != key:
                        continue
                    names = (cand.output(slot) if direction == "out"
                             else cand.input(slot))
                    if not names:
                        ok = False
                        break
                    name = names[0]
                    if var_key in new_vars and new_vars[var_key] != name:
                        ok = False
                        break
                    vp = self._var_preds.get(var_key)
                    if vp is not None and not vp(graph, name):
                        ok = False
                        break
                    new_vars[var_key] = name
                if not ok:
                    continue
                # edges whose op is already bound must agree too
                for op_key, direction, slot, var_key in self._edges:
                    if op_key == key or op_key not in bound_ops:
                        continue
                    other = bound_ops[op_key]
                    names = (other.output(slot) if direction == "out"
                             else other.input(slot))
                    if names and var_key in new_vars \
                            and new_vars[var_key] != names[0]:
                        ok = False
                        break
                if not ok:
                    continue
                bound_ops[key] = cand
                yield from backtrack(i + 1, bound_ops, new_vars)
                del bound_ops[key]

        yield from backtrack(0, {}, {})


@PassRegistry.register
class ConvBnFusePass(Pass):
    """conv2d + batch_norm (inference) -> conv2d with folded weights
    (reference ir/conv_bn_fuse_pass.cc). The BN affine transform is
    folded into the conv filter and a bias:

        W' = W * gamma / sqrt(var + eps)      (per out-channel)
        b' = (b - mean) * gamma / sqrt(var + eps) + beta

    Requires the scope holding the parameter values (like the
    reference, which rewrites the weight tensors in place). Only valid
    on a for_test graph — training BN updates running stats.
    """

    name = "conv_bn_fuse_pass"

    def __init__(self, scope=None):
        self.scope = scope

    def apply(self, graph: IrGraph) -> IrGraph:
        import numpy as np

        if self.scope is None:
            raise ValueError("conv_bn_fuse_pass needs the scope holding "
                             "parameter values")
        d = GraphPatternDetector()
        d.op_node("conv", "conv2d")
        d.op_node("bn", "batch_norm",
                  predicate=lambda op: bool(op.attr("is_test")))
        d.edge_out("conv", "Output", "conv_out")
        d.edge_in("bn", "X", "conv_out")
        consumed = set()
        folded_filters = set()
        consumers_of = GraphPatternDetector.consumer_index(graph)
        for m in list(d.detect(graph)):
            conv, bn = m["conv"], m["bn"]
            if id(conv) in consumed or id(bn) in consumed:
                continue
            # conv_out must feed ONLY the bn (else the pre-BN value is
            # still live and folding would corrupt it)
            if len(consumers_of.get(m["conv_out"], [])) != 1:
                continue
            # a filter shared by >1 op must not be folded (in-place
            # scope rewrite would corrupt the other consumer / fold
            # twice)
            filt = conv.input("Filter")[0]
            if filt in folded_filters or \
                    len(consumers_of.get(filt, [])) != 1:
                continue

            def _val(slot_names):
                v = self.scope.find_var(slot_names[0])
                return None if v is None else np.asarray(
                    v.get_tensor().array)

            w = _val(conv.input("Filter"))
            gamma = _val(bn.input("Scale"))
            beta = _val(bn.input("Bias"))
            mean = _val(bn.input("Mean"))
            var = _val(bn.input("Variance"))
            if any(x is None for x in (w, gamma, beta, mean, var)):
                continue
            eps = bn.attr("epsilon")
            eps = 1e-5 if eps is None else float(eps)
            std = np.sqrt(var + eps)
            factor = gamma / std
            # Filter layout is OIHW for either data_format (the
            # reference keeps OIHW too): scale along axis 0
            w_new = w * factor.reshape((-1,) + (1,) * (w.ndim - 1))
            conv_bias = conv.input("Bias")
            b = _val(conv_bias) if conv_bias else np.zeros_like(mean)
            if b is None:
                b = np.zeros_like(mean)
            b_new = (b - mean) * factor + beta

            import jax.numpy as jnp

            self.scope.find_var(conv.input("Filter")[0]) \
                .get_tensor()._array = jnp.asarray(w_new)
            bias_name = conv.input("Filter")[0] + ".bn_fold_bias"
            graph.create_persistable_node(bias_name, shape=b_new.shape,
                                          var_dtype=str(b_new.dtype))
            # write the value straight into the scope (to_program
            # callers never see the graph's startup_inits)
            self.scope.var(bias_name).get_tensor()._array = \
                jnp.asarray(b_new)
            graph.set_initializer(bias_name, b_new)
            bn_out = bn.output("Y")[0]
            fused = IrOpNode(
                graph, "conv2d",
                {**conv.input_slots(), "Bias": [bias_name]},
                {"Output": [bn_out]}, dict(conv._attrs))
            graph._ops[graph._ops.index(conv)] = fused
            graph.safe_remove_nodes([bn])
            consumed.update((id(conv), id(bn)))
            folded_filters.add(filt)
            consumers_of = GraphPatternDetector.consumer_index(graph)
        return graph


@PassRegistry.register
class GraphCheckPass(Pass):
    """Graph consistency validator (reference
    ir/multi_devices_graph_check_pass + the SSA sanity checks): every
    op input must be produced by an earlier op, fed (is_data), or
    persistable — a def-before-use audit over the op order the
    executor/compiler will run."""

    name = "graph_check_pass"

    def apply(self, graph: IrGraph) -> IrGraph:
        defined = set()
        for v in graph.all_var_nodes():
            if v.persistable or v.is_parameter or v.is_data:
                defined.add(v.name())
        for op in graph.all_op_nodes():
            if op.op_type() in ("feed", "read", "create_py_reader"):
                defined.update(op.output_arg_names())
                continue
            for n in op.input_arg_names():
                if n not in defined:
                    raise ValueError(
                        "graph_check_pass: op %r reads %r which no "
                        "earlier op produces and which is not "
                        "persistable/fed" % (op.op_type(), n))
            defined.update(op.output_arg_names())
        return graph


@PassRegistry.register
class MemoryEstimationPass(Pass):
    """Liveness-based memory diagnostic (reference
    ir/memory_optimize_pass/*: the reference REWRITES the graph for
    buffer reuse; under XLA, buffer assignment is the compiler's job,
    so this pass only DIAGNOSES — per-var live ranges, peak concurrent
    bytes, and reuse opportunities — for memory debugging parity with
    memory_usage_calc.py + the inplace pass reports)."""

    name = "memory_estimation_pass"

    def __init__(self, batch_size=1):
        self.batch_size = batch_size
        self.report = None

    def _nbytes(self, v) -> int:
        import numpy as np

        if v.shape is None:
            return 0
        n = 1
        for d in v.shape:
            n *= self.batch_size if d in (-1, None) else int(d)
        return int(n) * np.dtype(str(v.dtype)).itemsize

    def apply(self, graph: IrGraph) -> IrGraph:
        ops = graph.all_op_nodes()
        first_def: Dict[str, int] = {}
        last_use: Dict[str, int] = {}
        for i, op in enumerate(ops):
            for n in op.output_arg_names():
                first_def.setdefault(n, i)
                last_use[n] = i
            for n in op.input_arg_names():
                last_use[n] = i
        persistable_bytes = 0
        events = []  # (step, +bytes/-bytes)
        var_bytes = {}
        for name in set(first_def) | set(last_use):
            if not graph.has_var_node(name):
                continue
            v = graph.var_node(name)
            b = self._nbytes(v)
            var_bytes[name] = b
            if v.persistable:
                persistable_bytes += b
                continue
            start = first_def.get(name, 0)
            end = last_use.get(name, start)
            events.append((start, b))
            events.append((end + 1, -b))
        peak = cur = 0
        for _, delta in sorted(events, key=lambda e: (e[0], -e[1])):
            cur += delta
            peak = max(peak, cur)
        self.report = {
            "persistable_bytes": persistable_bytes,
            "peak_activation_bytes": peak,
            "n_vars": len(var_bytes),
            "live_ranges": {n: (first_def.get(n, 0), last_use.get(n, 0))
                            for n in var_bytes},
        }
        return graph


def apply_pass(program, pass_name: str, **kwargs):
    """Convenience: program -> pass -> program."""
    cls = PassRegistry._passes[pass_name]
    p = cls(**kwargs) if kwargs else cls()
    return p.apply(IrGraph(program)).to_program()


def apply_passes(program, pass_names, **common_kwargs):
    """Pass-pipeline runner (reference PassBuilder / ir_pass_manager):
    threads one IrGraph through the named passes, then materializes."""
    graph = IrGraph(program)
    applied = []
    for name in pass_names:
        cls = PassRegistry._passes[name]
        import inspect as _inspect

        sig = _inspect.signature(cls.__init__)
        kw = {k: v for k, v in common_kwargs.items()
              if k in sig.parameters}
        p = cls(**kw)
        graph = p.apply(graph)
        applied.append(p)
    prog = graph.to_program()
    return prog, applied
