"""RecomputeOptimizer / EMA / ModelAverage / Lookahead tests.

Contracts from the reference suite (test_recompute_optimizer.py:
recompute training matches plain training; test_ema.py;
test_lookahead.py)."""
import numpy as np

import paddle_tpu as fluid


def _mlp_program(lr=0.1, recompute=False, depth=4):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[32, 16], dtype="float32")
        y = fluid.data(name="y", shape=[32, 1], dtype="float32")
        h = x
        checkpoints = []
        for i in range(depth):
            h = fluid.layers.fc(h, 32, act="relu")
            if i % 2 == 1:
                checkpoints.append(h)
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        opt = fluid.optimizer.SGD(lr)
        if recompute:
            opt = fluid.optimizer.RecomputeOptimizer(opt)
            opt._set_checkpoints(checkpoints)
        opt.minimize(loss)
    return main, startup, loss


class TestRecompute:
    def test_program_contains_recomputed_segment(self):
        main, startup, loss = _mlp_program(recompute=True)
        ops = main.global_block().ops
        rec_ops = [op for op in ops
                   if any(n.endswith("@RECOMPUTE")
                          for n in op.output_arg_names)]
        assert rec_ops, "no recompute ops emitted"
        # recompute ops carry the Backward role (pruned by for_test)
        from paddle_tpu.framework import OpRole

        assert all(op._role & OpRole.Backward for op in rec_ops)
        test_prog = main.clone(for_test=True)
        assert not any(
            n.endswith("@RECOMPUTE")
            for op in test_prog.global_block().ops
            for n in op.output_arg_names)

    def test_training_parity_with_plain(self):
        """From identical inits, recompute training matches plain
        training exactly (the reference test_recompute_optimizer
        contract): recomputed activations are the same values."""
        import jax.numpy as jnp

        rng = np.random.RandomState(0)
        batches = [(rng.randn(32, 16).astype("float32"),
                    rng.randn(32, 1).astype("float32")) for _ in range(3)]
        inits = {}
        traces = {}
        for rc in (False, True):
            main, startup, loss = _mlp_program(recompute=rc)
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                for i, p in enumerate(main.global_block().all_parameters):
                    if i not in inits:
                        inits[i] = np.random.RandomState(100 + i).randn(
                            *p.shape).astype("float32") * 0.3
                    scope.var(p.name).get_tensor()._array = \
                        jnp.asarray(inits[i])
                ls = []
                for xb, yb in batches:
                    (l,) = exe.run(main, feed={"x": xb, "y": yb},
                                   fetch_list=[loss])
                    ls.append(float(np.asarray(l).ravel()[0]))
                traces[rc] = ls
        np.testing.assert_allclose(traces[True], traces[False],
                                   rtol=1e-5, atol=1e-6)


class TestEMA:
    def test_shadow_tracks_params(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[8, 4], dtype="float32")
            y = fluid.data(name="y", shape=[8, 1], dtype="float32")
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.2).minimize(loss)
            ema = fluid.optimizer.ExponentialMovingAverage(0.5)
            ema.update()
        rng = np.random.RandomState(1)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            for i in range(5):
                xb = rng.randn(8, 4).astype("float32")
                exe.run(main, feed={"x": xb, "y": np.ones((8, 1), "float32")},
                        fetch_list=[loss])
            w_name = main.global_block().all_parameters[0].name
            w_now = np.asarray(scope.find_var(w_name).raw().array).copy()
            with ema.apply(exe):
                w_ema = np.asarray(scope.find_var(w_name).raw().array).copy()
            w_back = np.asarray(scope.find_var(w_name).raw().array)
        assert not np.allclose(w_ema, w_now)  # shadow differs mid-training
        np.testing.assert_array_equal(w_back, w_now)  # restored


class TestLookahead:
    def test_slow_weights_sync_every_k(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[8, 4], dtype="float32")
            y = fluid.data(name="y", shape=[8, 1], dtype="float32")
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            opt = fluid.optimizer.LookaheadOptimizer(
                fluid.optimizer.SGD(0.3), alpha=0.5, k=3)
            opt.minimize(loss)
        rng = np.random.RandomState(2)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            w_name = main.global_block().all_parameters[0].name
            slow_name = w_name + ".slow"
            w0 = np.asarray(scope.find_var(w_name).raw().array).copy()
            np.testing.assert_array_equal(
                np.asarray(scope.find_var(slow_name).raw().array), w0)
            losses = []
            for i in range(6):
                xb = rng.randn(8, 4).astype("float32")
                (l,) = exe.run(main,
                               feed={"x": xb, "y": np.ones((8, 1),
                                                           "float32")},
                               fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
            slow_end = np.asarray(scope.find_var(slow_name).raw().array)
        assert not np.allclose(slow_end, w0)  # synced at steps 3 and 6
        assert losses[-1] < losses[0]


class TestModelAverage:
    def test_average_applied_and_restored(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[8, 4], dtype="float32")
            y = fluid.data(name="y", shape=[8, 1], dtype="float32")
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.2).minimize(loss)
            avg = fluid.optimizer.ModelAverage(0.15)
        rng = np.random.RandomState(3)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            for i in range(4):
                xb = rng.randn(8, 4).astype("float32")
                exe.run(main, feed={"x": xb, "y": np.ones((8, 1),
                                                          "float32")},
                        fetch_list=[loss])
            w_name = main.global_block().all_parameters[0].name
            w_now = np.asarray(scope.find_var(w_name).raw().array).copy()
            with avg.apply(exe):
                w_avg = np.asarray(
                    scope.find_var(w_name).raw().array).copy()
            w_back = np.asarray(scope.find_var(w_name).raw().array)
        assert not np.allclose(w_avg, w_now)
        np.testing.assert_array_equal(w_back, w_now)


class TestPipeline:
    def test_microbatches_equal_full_batch_step(self):
        """K microbatches through PipelineOptimizer == one full-batch
        SGD step, exactly (sync-pipeline/GPipe math)."""
        import jax.numpy as jnp

        K, B = 4, 8
        rng = np.random.RandomState(0)
        Xfull = rng.randn(B * K, 4).astype("float32")
        Yfull = rng.randn(B * K, 1).astype("float32")

        def build(pipeline):
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                bs = B if pipeline else B * K
                x = fluid.data(name="x", shape=[bs, 4], dtype="float32")
                y = fluid.data(name="y", shape=[bs, 1], dtype="float32")
                pred = fluid.layers.fc(x, 1)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                opt = fluid.optimizer.SGD(0.1)
                if pipeline:
                    opt = fluid.optimizer.PipelineOptimizer(
                        opt, num_microbatches=K)
                opt.minimize(loss)
            return main, startup, loss

        init, w = {}, {}
        for pipe in (False, True):
            main, startup, loss = build(pipe)
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                for i, p in enumerate(
                        main.global_block().all_parameters):
                    if i not in init:
                        init[i] = np.random.RandomState(50 + i).randn(
                            *p.shape).astype("float32") * 0.3
                    scope.var(p.name).get_tensor()._array = \
                        jnp.asarray(init[i])
                if pipe:
                    for m in range(K):
                        exe.run(main,
                                feed={"x": Xfull[m * B:(m + 1) * B],
                                      "y": Yfull[m * B:(m + 1) * B]},
                                fetch_list=[loss])
                else:
                    exe.run(main, feed={"x": Xfull, "y": Yfull},
                            fetch_list=[loss])
                pname = main.global_block().all_parameters[0].name
                w[pipe] = np.asarray(
                    scope.find_var(pname).raw().array)
        np.testing.assert_allclose(w[True], w[False], rtol=1e-5,
                                   atol=1e-6)


class TestStateOpsPrunedForTest:
    def test_clone_for_test_drops_ema_lookahead_avg_ops(self):
        """EMA/ModelAverage/Lookahead machinery carries the Optimize
        role, so evaluation clones must not mutate training state."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[8, 4], dtype="float32")
            y = fluid.data(name="y", shape=[8, 1], dtype="float32")
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            opt = fluid.optimizer.LookaheadOptimizer(
                fluid.optimizer.SGD(0.1), alpha=0.5, k=2)
            opt.minimize(loss)
            ema = fluid.optimizer.ExponentialMovingAverage(0.9)
            ema.update()
            fluid.optimizer.ModelAverage(0.15)
        test_types = [op.type for op in
                      main.clone(for_test=True).global_block().ops]
        for t in ("increment", "lookahead_update", "ema_accumulate",
                  "model_average_accumulate", "sgd"):
            assert t not in test_types, t

    def test_ema_thres_steps_adaptive_decay(self):
        """With thres_steps, early decay follows (1+t)/(10+t) so the
        shadow warms up from the params instead of zero-bias."""
        from paddle_tpu.layers import tensor as layers_tensor

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[8, 4], dtype="float32")
            y = fluid.data(name="y", shape=[8, 1], dtype="float32")
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.0).minimize(loss)  # params frozen
            step = layers_tensor.create_global_var(
                name="ema_t", shape=[1], value=0, dtype="int64",
                persistable=True)
            main.global_block().append_op(
                "increment", inputs={"X": [step]},
                outputs={"Out": [step]}, attrs={"step": 1.0},
                infer_shape=False)
            ema = fluid.optimizer.ExponentialMovingAverage(
                0.999, thres_steps=step)
            ema.update()
        rng = np.random.RandomState(0)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            for i in range(3):
                exe.run(main, feed={
                    "x": rng.rand(8, 4).astype("float32"),
                    "y": np.ones((8, 1), "float32")}, fetch_list=[loss])
            w_name = main.global_block().all_parameters[0].name
            w = np.asarray(scope.find_var(w_name).raw().array)
            with ema.apply(exe):
                w_ema = np.asarray(scope.find_var(w_name).raw().array)
        # frozen params + bias-corrected warm-up EMA ~= params
        np.testing.assert_allclose(w_ema, w, rtol=1e-4, atol=1e-5)


class TestDGCMomentum:
    def test_small_grads_accumulate_until_selected(self):
        """DGC semantics: with high sparsity only the largest-velocity
        entries update immediately; suppressed entries accumulate and
        apply later — long-run training still converges."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[16, 8], dtype="float32")
            y = fluid.data(name="y", shape=[16, 1], dtype="float32")
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            opt = fluid.optimizer.DGCMomentumOptimizer(
                learning_rate=0.05, momentum=0.9, rampup_begin_step=0,
                sparsity=[0.75])
            opt.minimize(loss)
        types = [op.type for op in main.global_block().ops]
        assert "dgc" in types and "sgd" in types
        rng = np.random.RandomState(0)
        W = rng.randn(8, 1).astype("float32")
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            losses = []
            for i in range(60):
                xb = rng.randn(16, 8).astype("float32")
                (l,) = exe.run(main, feed={"x": xb, "y": xb @ W},
                               fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
        assert losses[-1] < 0.3 * losses[0], (losses[0], losses[-1])


class TestDistributions:
    def test_normal_log_prob_and_kl(self):
        from paddle_tpu.distribution import Normal

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            n1 = Normal(0.0, 1.0)
            n2 = Normal(1.0, 2.0)
            v = fluid.layers.fill_constant([1], "float32", 0.5)
            lp = n1.log_prob(v)
            kl = n1.kl_divergence(n2)
            ent = n1.entropy()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            lp_v, kl_v, ent_v = exe.run(main, feed={},
                                        fetch_list=[lp, kl, ent])
        import math

        np.testing.assert_allclose(
            float(np.asarray(lp_v).ravel()[0]),
            -0.5 * 0.25 - 0.5 * math.log(2 * math.pi), rtol=1e-5)
        # KL(N(0,1) || N(1,2)) = log(2) + (1+1)/(2*4) - 0.5
        np.testing.assert_allclose(
            float(np.asarray(kl_v).ravel()[0]),
            math.log(2.0) + 2.0 / 8.0 - 0.5, rtol=1e-5)
        np.testing.assert_allclose(
            float(np.asarray(ent_v).ravel()[0]),
            0.5 + 0.5 * math.log(2 * math.pi), rtol=1e-5)

    def test_categorical_entropy_uniform(self):
        from paddle_tpu.distribution import Categorical

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            logits = fluid.layers.fill_constant([1, 4], "float32", 0.0)
            ent = Categorical(logits).entropy()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            (e,) = exe.run(main, feed={}, fetch_list=[ent])
        np.testing.assert_allclose(float(np.asarray(e).ravel()[0]),
                                   np.log(4.0), rtol=1e-5)
