#!/bin/sh
# Build the native components (reference counterpart: cmake targets for
# the data-feed library, the inference C API, and the C++ train demo).
set -e
cd "$(dirname "$0")"
PYFLAGS="$(python3-config --includes) $(python3-config --ldflags --embed)"

g++ -O2 -std=c++17 -shared -fPIC data_feed.cc -o libptfeed.so
g++ -O2 -std=c++17 -shared -fPIC capi.cc -o libptcapi.so $PYFLAGS
gcc -O2 capi_smoke.c -o capi_smoke -L. -lptcapi -Wl,-rpath,'$ORIGIN'
g++ -O2 -std=c++17 train_demo.cc -o train_demo $PYFLAGS
echo "built: libptfeed.so libptcapi.so capi_smoke train_demo"
