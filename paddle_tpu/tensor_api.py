"""paddle.tensor 2.0-alpha namespace (reference python/paddle/tensor):
thin re-exports of the tensor-manipulation surface, like the
reference's early namespace stubs."""
from .layers import (  # noqa: F401
    abs, argmax, argmin, argsort, assign, cast, ceil, concat, cos, diag,
    exp, eye, fill_constant, floor, gather, gather_nd, linspace, log,
    matmul, ones, pow, range, reshape, rsqrt, scale, scatter, shape, sin,
    slice, split, sqrt, square, squeeze, stack, tanh, topk, transpose,
    unsqueeze, unstack, where, zeros,
)
