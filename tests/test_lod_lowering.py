"""LoD -> padded lowering onto the whole-compile path (round-4 VERDICT
item #6, SURVEY §7 hard part (a)): a ragged-text program (LoD ids ->
embedding -> sequence_pool -> fc -> loss -> sgd, the sentiment/word2vec
book shape) must compile whole-program via the padded twins instead of
interpreting op-by-op, with LoD kept as host metadata; bucketed padding
bounds recompiles; results match the interpreter exactly."""
import time

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.tensor import LoDTensor

V, E, C = 30, 8, 4


def _build(pool="AVERAGE"):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data(name="ids", shape=[-1, 1], dtype="int64",
                         lod_level=1)
        lab = fluid.data(name="lab", shape=[-1, 1], dtype="int64")
        emb = fluid.layers.embedding(
            ids, size=[V, E], param_attr=fluid.ParamAttr(name="emb_w"))
        pooled = fluid.layers.sequence_pool(emb, pool_type=pool)
        pred = fluid.layers.fc(pooled, size=C, act="softmax",
                               param_attr=fluid.ParamAttr(name="fc_w"))
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, lab))
        fluid.optimizer.SGDOptimizer(0.2).minimize(loss)
    return main, startup, loss


def _ragged_batch(rng, n_seq, max_len=12):
    lens = rng.randint(1, max_len + 1, n_seq)
    offs = np.concatenate([[0], np.cumsum(lens)])
    vals = rng.randint(0, V, (offs[-1], 1)).astype("int64")
    t = LoDTensor(vals)
    t.set_lod([offs.tolist()])
    lab = rng.randint(0, C, (n_seq, 1)).astype("int64")
    return {"ids": t, "lab": lab}, lens


def _run_steps(exe, main, startup, loss, batches, scope, init=None):
    """Returns (losses, final_params, initial_params). ``init`` (if
    given) overwrites the startup values so two executors compare from
    identical parameters (compiled and interpreted startup derive
    different per-op RNG streams by design)."""
    import jax.numpy as jnp

    with fluid.scope_guard(scope):
        exe.run(startup)
        if init is not None:
            for n, arr in init.items():
                scope.var(n).get_tensor()._array = jnp.asarray(arr)

        def snap():
            return {n: np.asarray(scope.find_var(n).raw().array)
                    for n in ("emb_w", "fc_w")}

        init_params = snap()
        losses = []
        for feed in batches:
            (l,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.ravel(l)[0]))
        params = snap()
    return losses, params, init_params


def test_lod_program_compiles_and_matches_interpreter():
    for pool in ("AVERAGE", "MAX", "LAST"):
        main, startup, loss = _build(pool)
        rng = np.random.RandomState(1)
        batches = [_ragged_batch(rng, 6)[0] for _ in range(3)]

        exe_c = fluid.Executor(fluid.CPUPlace())
        l_c, p_c, init = _run_steps(exe_c, main, startup, loss, batches,
                                    fluid.Scope())
        # the lowering engaged (not the silent interpreter)
        assert any(v not in (None, False)
                   for v in exe_c._lod_lowered_cache.values()), pool
        assert not exe_c._compile_fallbacks

        exe_i = fluid.Executor(fluid.CPUPlace())
        exe_i._can_whole_compile = lambda p: False
        exe_i._lod_lowered = lambda *a, **k: None
        l_i, p_i, _ = _run_steps(exe_i, main, startup, loss, batches,
                                 fluid.Scope(), init=init)

        np.testing.assert_allclose(l_c, l_i, rtol=1e-6, atol=1e-7,
                                   err_msg=pool)
        for n in p_c:
            np.testing.assert_allclose(p_c[n], p_i[n], rtol=1e-6,
                                       atol=1e-7, err_msg=pool)


def test_bucketing_bounds_recompiles():
    """Batches whose max length lands in the same power-of-two bucket
    share one compiled executable."""
    from paddle_tpu.core import compiler_engine

    main, startup, loss = _build()
    rng = np.random.RandomState(2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        keys_before = set(compiler_engine._cache)
        for max_len in (9, 11, 14, 16):  # all bucket to T=16
            feed, _ = _ragged_batch(rng, 6, max_len=max_len)
            exe.run(main, feed=feed, fetch_list=[loss])
        # count NEW keys (a plain size delta breaks when the LRU cap
        # evicts an unrelated entry mid-test in a long suite run)
        new = [k for k in compiler_engine._cache if k not in keys_before]
    assert len(new) == 1, new


def test_compiled_beats_interpreter():
    """The point of the lowering: measured speedup over op-by-op
    interpretation on repeat steps (compile excluded via warmup)."""
    main, startup, loss = _build()
    rng = np.random.RandomState(3)
    feed, _ = _ragged_batch(rng, 8, max_len=8)
    N = 30

    def timed(exe):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(3):  # warmup/compile
                exe.run(main, feed=feed, fetch_list=[loss])
            t0 = time.time()
            for _ in range(N):
                exe.run(main, feed=feed, fetch_list=[loss])
        return time.time() - t0

    for attempt in range(3):  # best-of-3 guards against host noise
        t_compiled = timed(fluid.Executor(fluid.CPUPlace()))
        exe_i = fluid.Executor(fluid.CPUPlace())
        exe_i._lod_lowered = lambda *a, **k: None
        t_interp = timed(exe_i)
        if t_compiled < t_interp:
            break
    assert t_compiled < t_interp, (t_compiled, t_interp)


def test_softmax_raggedness_guard():
    """sequence_softmax PRESERVES raggedness: a non-rank-safe consumer
    (mean over the padded tensor would count the pads) must keep the
    program on the interpreter, with correct ragged numerics."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[-1, 1], dtype="float32",
                       lod_level=1)
        sm = fluid.layers.sequence_softmax(x)
        out = fluid.layers.mean(sm)
    vals = np.asarray([1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0],
                      "float32").reshape(-1, 1)
    t = LoDTensor(vals)
    t.set_lod([[0, 3, 7]])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (v,) = exe.run(main, feed={"x": t}, fetch_list=[out])
    # ragged mean over 7 rows (each segment sums to 1 -> mean 2/7)
    np.testing.assert_allclose(float(np.ravel(v)[0]), 2.0 / 7.0,
                               rtol=1e-5)


def test_multilevel_lod_stays_on_interpreter():
    """lod_level >= 2 feeds (sub-sequences) cannot pad on level 0 —
    the lowering must decline and the interpreter result stands."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[-1, 1], dtype="float32",
                       lod_level=2)
        pooled = fluid.layers.sequence_pool(x, pool_type="SUM")
        out = fluid.layers.mean(pooled)
    vals = np.arange(1, 12, dtype="float32").reshape(-1, 1)
    t = LoDTensor(vals)
    t.set_lod([[0, 2, 4], [0, 3, 5, 9, 11]])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (v,) = exe.run(main, feed={"x": t}, fetch_list=[out])
    assert not any(h not in (None, False)
                   for h in exe._lod_lowered_cache.values())
    # interpreter pools on the LAST level: segments sum to
    # (6, 9, 30, 21) -> mean 16.5
    np.testing.assert_allclose(float(np.ravel(v)[0]), 16.5, rtol=1e-5)


def _compare_compiled_vs_interp(build_fn, feeds_fn, param_names,
                                steps=3, seed=1):
    """Run the same LoD program compiled (lowered) and interpreted from
    identical params; assert the lowering ENGAGED and outputs match."""
    main, startup, loss = build_fn()
    rng = np.random.RandomState(seed)
    batches = [feeds_fn(rng) for _ in range(steps)]

    def run(exe, init=None):
        import jax.numpy as jnp

        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            if init is not None:
                for n, arr in init.items():
                    scope.var(n).get_tensor()._array = jnp.asarray(arr)
            init_params = {n: np.asarray(
                scope.find_var(n).raw().array) for n in param_names}
            losses = []
            for feed in batches:
                (l,) = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.ravel(l)[0]))
            params = {n: np.asarray(scope.find_var(n).raw().array)
                      for n in param_names}
        return losses, params, init_params

    exe_c = fluid.Executor(fluid.CPUPlace())
    l_c, p_c, init = run(exe_c)
    assert any(v not in (None, False)
               for v in exe_c._lod_lowered_cache.values()), \
        "lowering did not engage"
    assert not exe_c._compile_fallbacks

    exe_i = fluid.Executor(fluid.CPUPlace())
    exe_i._can_whole_compile = lambda p: False
    exe_i._lod_lowered = lambda *a, **k: None
    l_i, p_i, _ = run(exe_i, init=init)
    np.testing.assert_allclose(l_c, l_i, rtol=1e-5, atol=1e-6)
    for n in param_names:
        # grads must FLOW (a param frozen on both paths would pass
        # parity trivially)
        assert not np.allclose(p_c[n], init[n]), \
            "param %s never updated" % n
        np.testing.assert_allclose(p_c[n], p_i[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


def _lod_ids(rng, n_seq, max_len=10, name="ids"):
    lens = rng.randint(2, max_len + 1, n_seq)
    offs = np.concatenate([[0], np.cumsum(lens)])
    vals = rng.randint(0, V, (offs[-1], 1)).astype("int64")
    t = LoDTensor(vals)
    t.set_lod([offs.tolist()])
    return t


def test_sequence_conv_program_whole_compiles():
    """The reference sentiment CONV config (understand_sentiment
    conv-pool): emb -> sequence_conv -> sequence_pool(MAX) -> fc."""
    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup), \
                fluid.unique_name.guard():
            ids = fluid.data(name="ids", shape=[-1, 1], dtype="int64",
                             lod_level=1)
            lab = fluid.data(name="lab", shape=[-1, 1], dtype="int64")
            emb = fluid.layers.embedding(
                ids, size=[V, E],
                param_attr=fluid.ParamAttr(name="emb_w"))
            conv = fluid.layers.sequence_conv(
                emb, num_filters=6, filter_size=3,
                param_attr=fluid.ParamAttr(name="conv_w"))
            pooled = fluid.layers.sequence_pool(conv, pool_type="MAX")
            pred = fluid.layers.fc(
                pooled, size=C, act="softmax",
                param_attr=fluid.ParamAttr(name="fc_w"))
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, lab))
            fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
        return main, startup, loss

    def feeds(rng):
        return {"ids": _lod_ids(rng, 6),
                "lab": rng.randint(0, C, (6, 1)).astype("int64")}

    _compare_compiled_vs_interp(build, feeds,
                                ["emb_w", "conv_w", "fc_w"])


def test_mt_style_expand_pad_unpad_chain_whole_compiles():
    """The book-MT decoder shape: dense encoder state expanded over
    the ragged target (sequence_expand), added to target embeddings,
    re-padded (sequence_pad), unpadded (sequence_unpad), pooled —
    the 4-op chain whole-compiles and trains to interpreter parity."""
    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup), \
                fluid.unique_name.guard():
            src = fluid.data(name="src", shape=[-1, 1], dtype="int64",
                             lod_level=1)
            tgt = fluid.data(name="tgt", shape=[-1, 1], dtype="int64",
                             lod_level=1)
            lab = fluid.data(name="lab", shape=[-1, 1], dtype="int64")
            semb = fluid.layers.embedding(
                src, size=[V, E],
                param_attr=fluid.ParamAttr(name="semb_w"))
            enc = fluid.layers.sequence_pool(semb, pool_type="LAST")
            temb = fluid.layers.embedding(
                tgt, size=[V, E],
                param_attr=fluid.ParamAttr(name="temb_w"))
            expanded = fluid.layers.sequence_expand(enc, temb)
            mix = fluid.layers.elementwise_add(temb, expanded)
            padded = fluid.layers.sequence_pad(
                mix, fluid.layers.fill_constant([1], "float32", 0.0),
                maxlen=16)
            unpadded = fluid.layers.sequence_unpad(padded[0], padded[1])
            pooled = fluid.layers.sequence_pool(unpadded,
                                                pool_type="AVERAGE")
            pred = fluid.layers.fc(
                pooled, size=C, act="softmax",
                param_attr=fluid.ParamAttr(name="fc_w"))
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, lab))
            fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
        return main, startup, loss

    def feeds(rng):
        return {"src": _lod_ids(rng, 6), "tgt": _lod_ids(rng, 6),
                "lab": rng.randint(0, C, (6, 1)).astype("int64")}

    _compare_compiled_vs_interp(build, feeds,
                                ["semb_w", "temb_w", "fc_w"])


def test_sequence_concat_program_whole_compiles():
    """Two ragged features time-concatenated per sequence (the derived
    length var = len_a + len_b flows into the pool)."""
    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup), \
                fluid.unique_name.guard():
            a = fluid.data(name="a", shape=[-1, 1], dtype="int64",
                           lod_level=1)
            b = fluid.data(name="b", shape=[-1, 1], dtype="int64",
                           lod_level=1)
            lab = fluid.data(name="lab", shape=[-1, 1], dtype="int64")
            ea = fluid.layers.embedding(
                a, size=[V, E], param_attr=fluid.ParamAttr(name="ea_w"))
            eb = fluid.layers.embedding(
                b, size=[V, E], param_attr=fluid.ParamAttr(name="eb_w"))
            cat = fluid.layers.sequence_concat([ea, eb])
            pooled = fluid.layers.sequence_pool(cat,
                                                pool_type="AVERAGE")
            pred = fluid.layers.fc(
                pooled, size=C, act="softmax",
                param_attr=fluid.ParamAttr(name="fc_w"))
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, lab))
            fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
        return main, startup, loss

    def feeds(rng):
        return {"a": _lod_ids(rng, 6), "b": _lod_ids(rng, 6),
                "lab": rng.randint(0, C, (6, 1)).astype("int64")}

    _compare_compiled_vs_interp(build, feeds, ["ea_w", "eb_w", "fc_w"])


def test_param_never_carries_sequence_lod():
    """Round-5 verify-drive find: when a batch's token total HAPPENS to
    equal the vocab size, the table grad's propagated lod passed the
    row-count guard, stamped a sequence lod onto the PARAM, and poisoned
    later batches' lod propagation (embedding outputs lost their lod
    and sequence_pool crashed). Persistable vars never carry lod."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        ids = fluid.data(name="ids", shape=[-1, 1], dtype="int64",
                         lod_level=1)
        lab = fluid.data(name="lab", shape=[-1, 1], dtype="int64")
        emb = fluid.layers.embedding(
            ids, size=[V, E], param_attr=fluid.ParamAttr(name="emb_w"))
        pooled = fluid.layers.sequence_pool(emb, pool_type="AVERAGE")
        pred = fluid.layers.fc(pooled, size=C, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, lab))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe._lod_lowered = lambda *a, **k: None   # interpreter path
    rng = np.random.RandomState(4)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        # batch whose TOTAL equals V (30): two seqs 15+15
        vals = rng.randint(0, V, (V, 1)).astype("int64")
        t = LoDTensor(vals)
        t.set_lod([[0, 15, V]])
        exe.run(main, feed={"ids": t,
                            "lab": rng.randint(0, C, (2, 1)
                                               ).astype("int64")},
                fetch_list=[loss])
        w = scope.find_var("emb_w").raw()
        assert not w.lod(), "param got stamped with a sequence lod"
        # different-total batch must still run (this crashed before)
        feed2, _ = _ragged_batch(rng, 5, max_len=7)
        (l,) = exe.run(main, feed=feed2, fetch_list=[loss])
    assert np.isfinite(float(np.ravel(l)[0]))
