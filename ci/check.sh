#!/usr/bin/env bash
# CI invariant gate (reference: paddle/scripts/paddle_build.sh +
# tools/check_op_register_type.py + tools/print_signatures.py +
# tools/check_api_approvals.sh — the reference wires these into CI; this
# script is the equivalent single entry point).
#
# Usage:
#   ci/check.sh            # run all gates
#   ci/check.sh --update   # refresh the committed API fingerprint
#   SKIP_TESTS=1 ci/check.sh   # invariants only (fast)
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export PALLAS_AXON_POOL_IPS=
# ISSUE 12: force the static IR verifier ON for every CI gate (it
# defaults OFF in prod). Gate 4 measures the DEFAULT-off path and
# un-sets it explicitly.
export PADDLE_TPU_VERIFY_IR=1

if [[ "${1:-}" == "--update" ]]; then
    python -m paddle_tpu.tools.print_signatures > ci/api_fingerprint.txt
    echo "ci/api_fingerprint.txt refreshed ($(wc -l < ci/api_fingerprint.txt) entries)"
    exit 0
fi

echo "== gate 0: repo lint =="
# bare/silent excepts, metric-naming convention, unlocked module state
# in serving//distributed/ — new violations fail; grandfathered ones
# live in tools/lint_allowlist.txt
python tools/lint.py

echo "== gate 1: op-registry parity (diff must be 0 vs allowlist) =="
python -m paddle_tpu.tools.check_op_registry --parity

echo "== gate 2: public API signature freeze =="
FP_TMP="$(mktemp)"
trap 'rm -f "$FP_TMP"' EXIT
python -m paddle_tpu.tools.print_signatures > "$FP_TMP"
if ! diff -u ci/api_fingerprint.txt "$FP_TMP"; then
    echo "API surface changed. If intentional: ci/check.sh --update" >&2
    exit 1
fi
echo "API surface unchanged ($(wc -l < ci/api_fingerprint.txt) entries)"

echo "== gate 2b: IR-verifier mutation self-test =="
# ISSUE 12 acceptance: >= 12 seeded IR corruption kinds (dangling
# refs, use-before-def, dtype/shape flips, rank-divergent collective
# schedules, broken rewrite contracts, ...) must each be rejected by
# paddle_tpu/analysis with a structured finding; a clean transpiled
# program must verify clean. This is the verifier's own regression
# suite.
python tools/ir_mutate.py

echo "== gate 3: native artifacts build =="
if command -v g++ >/dev/null; then
    (cd csrc && ./build.sh >/dev/null)
    echo "csrc build OK"
else
    echo "g++ unavailable, skipped"
fi

echo "== gate 4: observability =="
# 4a: the observability layer's own tests (registry, spans, exporters,
# executor/lazy counters, profiler shim). Skipped when the full suite
# runs below — gate 6 collects the same file; running it twice buys
# nothing
if [[ "${SKIP_TESTS:-0}" == "1" ]]; then
    python -m pytest tests/test_observability.py -q
fi
# 4b: PADDLE_TPU_METRICS unset (default-off) must add no measurable
# overhead to a tiny executor microbench, and the ISSUE-5 additions —
# trace-context propagation (header stamp / child spans) and the
# flight-recorder ring — must stay sub-microsecond on their disabled /
# always-on paths (guard threshold, not exact timing — see
# tools/obs_overhead.py)
#    ... and (ISSUE 12) the default-off IR-verify hook must stay <1us
#    per program run — PADDLE_TPU_VERIFY_IR is un-set here because
#    this gate measures the DEFAULT path
#    ... and (ISSUE 16) sampled in-production capture must default
#    off with its per-step hook under the same <1us budget —
#    PADDLE_TPU_SAMPLE_EVERY is un-set for the same reason
#    ... and (ISSUE 20) the windowed time-series sampler must default
#    off (it arms off PADDLE_TPU_METRICS_DIR) with its hooks under
#    the same budget — PADDLE_TPU_TIMESERIES is un-set likewise
env -u PADDLE_TPU_METRICS -u FLAGS_tpu_metrics \
    -u PADDLE_TPU_METRICS_DIR -u PADDLE_TPU_DEVICE_TRACE \
    -u PADDLE_TPU_VERIFY_IR -u PADDLE_TPU_SAMPLE_EVERY \
    -u PADDLE_TPU_TIMESERIES -u PADDLE_TPU_TIMESERIES_WINDOWS \
    python -m paddle_tpu.tools.obs_overhead

echo "== gate 5: serving =="
# 5a: serving tests (batcher/engine/http contracts). Same dedup as
# gate 4a — the full suite below collects the same file
if [[ "${SKIP_TESTS:-0}" == "1" ]]; then
    python -m pytest tests/test_serving.py -q
fi
# 5b: end-to-end smoke — ServingEngine on a tiny MLP, 64 concurrent
# ragged requests: zero errors, jit compiles == warmed bucket count
# (NOT the number of distinct observed batch sizes), and an
# undersized queue must actually reject (backpressure engages).
# --out also writes the bench_diff-compatible serving record
SRV_OUT="$(mktemp)"
DEC_OUT="$(mktemp)"
trap 'rm -f "$FP_TMP" "$SRV_OUT" "$DEC_OUT"' EXIT
python tools/serving_bench.py --smoke --out "$SRV_OUT"
# 5b-decode: continuous-batching decode smoke — mixed-length token
# streams through the DecodeEngine, every stream exactly-once, zero
# stream errors, and tokens/s must beat the static wait-for-all
# baseline measured in the same record (ISSUE 17 acceptance)
python tools/serving_bench.py --decode --out "$DEC_OUT"

echo "== gate 5c: serving perf regression vs previous run =="
# same machine-local run-over-run scheme as gate 7b: queue-wait /
# batch-size / padding-waste / compile-count regressions (and any
# serving.errors growth) fail CI exactly like training regressions.
# Timing gates loose (CI jitter); the counters are the strict half.
SRV_BASELINE="ci/baseline/serving_smoke.json"
mkdir -p ci/baseline
if [[ -f "$SRV_BASELINE" ]]; then
    srv_rc=0
    python tools/bench_diff.py "$SRV_BASELINE" "$SRV_OUT" \
        --threshold 0.5 --counters-threshold 0.5 || srv_rc=$?
    if [[ "$srv_rc" == "0" ]]; then
        echo "serving perf gate: no regression vs previous run"
    elif [[ "$srv_rc" == "2" ]]; then
        echo "serving perf gate: baseline unreadable (rc=2) — reseeding $SRV_BASELINE"
    elif [[ "${PERF_BASELINE_ACCEPT:-0}" == "1" ]]; then
        echo "serving perf gate: regression ACCEPTED (PERF_BASELINE_ACCEPT=1)"
    else
        echo "serving perf gate: regression vs $SRV_BASELINE — intentional? re-run with PERF_BASELINE_ACCEPT=1" >&2
        exit 1
    fi
else
    echo "serving perf gate: no previous run on this machine — seeding $SRV_BASELINE"
fi
cp "$SRV_OUT" "$SRV_BASELINE"
# decode record: TTFT/ITL percentiles, the continuous-vs-static
# speedup margin, KV occupancy and preemptions, run-over-run
DEC_BASELINE="ci/baseline/decode_smoke.json"
if [[ -f "$DEC_BASELINE" ]]; then
    dec_rc=0
    python tools/bench_diff.py "$DEC_BASELINE" "$DEC_OUT" \
        --threshold 0.5 --counters-threshold 0.5 || dec_rc=$?
    if [[ "$dec_rc" == "0" ]]; then
        echo "decode perf gate: no regression vs previous run"
    elif [[ "$dec_rc" == "2" ]]; then
        echo "decode perf gate: baseline unreadable (rc=2) — reseeding $DEC_BASELINE"
    elif [[ "${PERF_BASELINE_ACCEPT:-0}" == "1" ]]; then
        echo "decode perf gate: regression ACCEPTED (PERF_BASELINE_ACCEPT=1)"
    else
        echo "decode perf gate: regression vs $DEC_BASELINE — intentional? re-run with PERF_BASELINE_ACCEPT=1" >&2
        exit 1
    fi
else
    echo "decode perf gate: no previous run on this machine — seeding $DEC_BASELINE"
fi
cp "$DEC_OUT" "$DEC_BASELINE"

echo "== gate 6: fault tolerance =="
# 6a: the fault-tolerance suite (injection grammar/determinism, retry
# + dedup exactly-once, eviction, atomic checkpoints, port hygiene,
# /healthz drain). Same dedup as gates 4a/5a — the full suite below
# collects the same file
if [[ "${SKIP_TESTS:-0}" == "1" ]]; then
    python -m pytest tests/test_fault_tolerance.py -q
fi
# 6b: multiprocess recovery drill — 2-trainer sync PS under the launch
# supervisor, one trainer SIGKILLed at round 3: the job must complete
# (eviction unblocks the survivor, the relaunch resumes from the
# newest manifest-verified checkpoint) and the final checkpoint must
# re-verify
python tools/ft_smoke.py
# 6c: SERVER-death drill — 2 trainers, 2 replicated pservers, the
# PRIMARY SIGKILLs itself while applying round 3: the job must exit 0
# with every trainer failed over to the promoted backup AND the final
# params matching the clean single-server run bit-for-bit (failover
# replay + replicated dedup watermark); the killed server must rejoin
# as a catching-up backup under the supervisor, and the merged
# telemetry must show DELTA replication actually carried the job
# (ps.delta_rounds > 0 — a silent regression to full-blob shipping
# fails here)
python tools/ft_smoke.py --server-kill
# 6d: bounded chaos drill — one seeded randomized schedule (random
# fault plan + random trainer kill + random primary-pserver kill),
# gated on bit-for-bit parity with the clean run PLUS the merged-
# telemetry invariants (job-level metrics.json + trace.json exist;
# injected faults, the quorum promotion, and the promoted backup's
# first applied round are visible in causal order across >= 3
# processes; delta replication ran with its bytes strictly below the
# full anchors'); a failure prints the seed that replays it
python tools/chaos_drill.py --rounds 1
# 6e: ISSUE-19 acceptance drill (~2x2min) — WHOLE-JOB CRASH
# consistency: two seeded schedules each SIGKILL every process
# (launcher, trainers, every pserver — the process group dies) at a
# seeded durable round, relaunch the IDENTICAL command from the
# durable store, and gate on final params bit-for-bit vs the
# uninterrupted oracle PLUS the kill -> cold-start (restore_round at
# the newest globally-complete cut) -> per-shard restore-at-the-cut
# -> first-applied-round == cut+1 causal chain in the merged
# cross-incarnation trace.json (stale re-sends from the dead
# incarnation dropped, never re-applied)
python tools/chaos_drill.py --rounds 2 --total-loss --shards 2
# ... and the torn-tail variant: the newest durable round is torn on
# disk between kill and relaunch — restore must fall back exactly one
# globally-complete round and still land bit-for-bit
python tools/chaos_drill.py --rounds 1 --total-loss --corrupt-newest --shards 2
# 6f: ISSUE-8 acceptance drill — 2 key-range shards x (primary +
# backup), the schedule's shard loses its primary to SIGKILL (lease
# expiry -> tombstone-quorum election -> promotion) while the OTHER
# shard's primary<->backup pair is network-partitioned for the whole
# run (the backup's lease expires but every election is quorum-DENIED
# — exactly one writable primary per shard, no split brain, no lost
# rounds). Exit 0, per-shard params bit-for-bit, and
# ps.replication_bytes{mode=delta} strictly below the full-anchor
# bytes in the merged job metrics.json
python tools/chaos_drill.py --rounds 1 --shards 2 --partition
# 6g: ISSUE-13 acceptance drill (~45s) — LIVE KEY-RANGE MIGRATION
# under fire: a seeded schedule migrates one shard's var to the
# sister shard mid-training, the donor primary is SIGKILLed in the
# worst spot (range installed on the recipient, nothing committed or
# replicated), and the drill gates on exit 0, params bit-for-bit vs
# the clean run (zero lost or double-applied rounds), the rollback of
# attempt 1 + kill -> promotion -> migration-commit causal chain in
# the merged trace.json, every trainer adopting the bumped shard map,
# external-witness votes in the election, and clock-jitter chaos
# armed throughout
python tools/chaos_drill.py --rounds 1 --shards 2 --migrate
# 6h: ISSUE-18 acceptance drill (~90s) — SELF-STEERED row-range
# rebalance under fire: trainers hammer the hot quarter of one
# shard's slice of a sparse row-partitioned table, trainer 0's
# SteeringDaemon watches the job's own merged ps.row_heat census,
# proposes a migrate_range plan at the sustained skew breach, and
# the canary applies it through the LIVE protocol — with the donor
# primary SIGKILLed mid-apply (rows staged on the recipient, nothing
# committed) so the re-trigger completes on its promoted backup.
# Gated on exit 0; the sparse table bit-for-bit vs the pure
# push-schedule oracle on BOTH trainers (exactly-once across the
# kill, the abandoned install, and the wrong_shard redirects); the
# plan carving a tail of the hot quarter; install < kill < promotion
# < replicated range-commit in causal order; range bytes on
# ps.migration_bytes{kind=range}; every trainer routing the moved
# rows to the recipient; and the full audit chain (proposal
# artifact, audit trail, active-plan pointer, flight order) with
# bit-equal plan digests end to end
python tools/chaos_drill.py --rounds 1 --shards 2 --migrate-range --sync-rounds 18
# 6i: sharded eviction drill (~30s) — per-shard effective fanin
# disagreeing mid-round (the dying trainer's phase-1 barrier reaches
# shard 0 only; eviction armed on shard 1 alone): the two-phase
# barrier + the stale-round guard must reconcile DETERMINISTICALLY
# (per-shard bit-for-bit oracles, trainers agreeing, stale re-sends
# dropped not re-applied)
python tools/chaos_drill.py --rounds 1 --shards 2 --evict

echo "== gate 7: multichip fast-path smoke =="
# dp=8 CPU host mesh, mlp config, ~2 min: the bucketed/sharded
# collective path must STRICTLY reduce per-step collective ops vs a
# forced per-grad run; ONE profile-guided replan cycle must close the
# measurement loop (plan -> measure -> feed the profile report back
# via PADDLE_TPU_BUCKET_PLAN=profile -> the bucket plan demonstrably
# changes and measured overlap_frac does not decrease, with parity
# bit-for-bit via pytest); every dp=8 record must carry BOTH the
# host- and device-measured phase breakdowns plus their agreement
# ratio; and tools/bench_diff.py must answer --help and pass its
# --self-test (the mechanical perf gate bench artifacts diff through)
MC_OUT="$(mktemp)"
trap 'rm -f "$FP_TMP" "$SRV_OUT" "$MC_OUT"' EXIT
python tools/mc_smoke.py --out "$MC_OUT"

echo "== gate 7b: perf regression vs previous run =="
# ci/baseline/ keeps the PREVIOUS run's smoke artifact on this machine
# (gitignored: step_ms across different hosts is meaningless, so the
# comparison is same-host run-over-run). First run seeds the baseline;
# later runs diff automatically — the per-step collective counters are
# deterministic (static program rewrite), so they gate at 1%; timing
# metrics gate at a loose 50% (CI-box jitter is real; the counters are
# the strict half). Intentional perf-profile changes:
# PERF_BASELINE_ACCEPT=1 ci/check.sh records the new numbers as the
# next baseline instead of failing.
BASELINE="ci/baseline/mc_smoke.json"
mkdir -p ci/baseline
if [[ -f "$BASELINE" ]]; then
    diff_rc=0
    python tools/bench_diff.py "$BASELINE" "$MC_OUT" \
        --threshold 0.5 --counters-threshold 0.01 || diff_rc=$?
    if [[ "$diff_rc" == "0" ]]; then
        echo "perf gate: no regression vs previous run"
    elif [[ "$diff_rc" == "2" ]]; then
        # load error (torn/corrupt baseline, schema drift) is NOT a
        # regression — reseed rather than fail or silently "accept"
        echo "perf gate: baseline unreadable/incomparable (rc=2) — reseeding $BASELINE"
    elif [[ "${PERF_BASELINE_ACCEPT:-0}" == "1" ]]; then
        echo "perf gate: regression ACCEPTED (PERF_BASELINE_ACCEPT=1) — new baseline recorded"
    else
        echo "perf gate: regression vs $BASELINE — intentional? re-run with PERF_BASELINE_ACCEPT=1" >&2
        exit 1
    fi
else
    echo "perf gate: no previous run on this machine — seeding $BASELINE"
fi
cp "$MC_OUT" "$BASELINE"

echo "== gate 7c: single-chip fusion smoke =="
# ISSUE-14 acceptance: the fused-optimizer pass must STRICTLY cut the
# per-step op count for an mlp + conv smoke with step-1 parity vs the
# per-param path (bitwise where XLA's FMA contraction matches,
# <=4 ULP otherwise) and full-run trajectory agreement; the async
# feeder's critical-path cost must not exceed the sync H2D it hides.
SC_OUT="$(mktemp)"
trap 'rm -f "$FP_TMP" "$SRV_OUT" "$MC_OUT" "$SC_OUT"' EXIT
python tools/sc_smoke.py --out "$SC_OUT"

echo "== gate 7d: single-chip perf regression vs previous run =="
# same run-over-run scheme as gates 5c/7b: timings gate loose (50%),
# but sc.program_ops — the fused programs' op count — is DETERMINISTIC
# and gates at 1%: growth means the fusion passes silently regressed.
SC_BASELINE="ci/baseline/sc_smoke.json"
mkdir -p ci/baseline
if [[ -f "$SC_BASELINE" ]]; then
    sc_rc=0
    python tools/bench_diff.py "$SC_BASELINE" "$SC_OUT" \
        --threshold 0.5 --counters-threshold 0.01 || sc_rc=$?
    if [[ "$sc_rc" == "0" ]]; then
        echo "single-chip perf gate: no regression vs previous run"
    elif [[ "$sc_rc" == "2" ]]; then
        echo "single-chip perf gate: baseline unreadable (rc=2) — reseeding $SC_BASELINE"
    elif [[ "${PERF_BASELINE_ACCEPT:-0}" == "1" ]]; then
        echo "single-chip perf gate: regression ACCEPTED (PERF_BASELINE_ACCEPT=1)"
    else
        echo "single-chip perf gate: regression vs $SC_BASELINE — intentional? re-run with PERF_BASELINE_ACCEPT=1" >&2
        exit 1
    fi
else
    echo "single-chip perf gate: no previous run on this machine — seeding $SC_BASELINE"
fi
cp "$SC_OUT" "$SC_BASELINE"

echo "== gate 7e: placement-synthesis smoke =="
# ISSUE-15 acceptance (~60s): the dp=8 mlp placement search must emit
# a verifier-clean plan artifact — every enumerated candidate gated
# through verify_program + check_cross_rank BEFORE anything could
# trace it (zero rejected, zero traced-before-verify), deterministic
# winner digest from the same report+seed, canonical round-trip
# through PADDLE_TPU_PLACEMENT_PLAN — and the winner's measured
# step_ms must beat (<=) the size-plan baseline, with the bench
# record carrying the plan digest + predicted-vs-measured agreement
# that bench_diff watches for drift.
python tools/placement_smoke.py

echo "== gate 8: serving-fleet chaos drill =="
# the ISSUE-11 acceptance drill (~45s): 2 supervised serving replicas
# + a closed-loop FleetRouter driver under an RPC fault plan
# (drop/delay/close on the fleet dispatch path); replica 0 SIGKILLs
# itself mid-dispatch. Gated on the DRIVER's accounting (zero lost
# accepted requests, every response value-verified, shed strictly by
# cost class under the synthetic overload burst, the relaunched
# replica demonstrably serving again) AND on the merged job telemetry
# (p99 serving.queue_ms within budget, serving.hedges > 0,
# serving.replica_ejections >= 1, the kill -> ejection -> relaunch ->
# rejoin chain in causal order, per-replica serving spans joining ONE
# job trace) — not on logs.
python tools/serving_chaos.py --smoke

echo "== gate 8-decode: streaming-decode chaos drill =="
# the ISSUE-17 acceptance drill (~10s): 2 supervised DecodeEngine
# replicas, 8 concurrent token streams through FleetRouter.generate();
# replica 0 SIGKILLs itself mid-stream. Zero lost accepted streams,
# zero duplicated token indices, every delivered token value-verified
# against local regeneration (exactly-once resume after the kill),
# serving.stream_resumes >= 1 / stream_errors == 0 in merged
# counters, and the kill -> eject -> resume -> relaunch -> rejoin
# chain in causal order from the merged timeline.
python tools/serving_chaos.py --decode

echo "== gate 8b: steering drill =="
# the ISSUE-16 acceptance drill (seeded, in-process, ~10s): sampled
# capture fires on exactly every Nth executor step and surfaces in
# the merged metrics.json; the steering daemon proposes exactly ONCE
# for a sustained breach (hysteresis resets on a clean poll, the
# cooldown prevents a replan storm); a planted serving-ladder
# regression ROLLS BACK and a planted improvement PROMOTES under the
# shared comparator; and the audit closes — plan digests bit-match
# across steering_audit.json, the flight ring, the proposal artifact
# and the active-plan pointer, with installs == promoted entries
# (zero un-audited plan switches, the PlanStore refuses structurally).
env -u PADDLE_TPU_METRICS_DIR -u PADDLE_TPU_SAMPLE_EVERY \
    -u PADDLE_TPU_TIMESERIES \
    python tools/steering_drill.py

echo "== gate 8c: drifting-load A/B objective drill =="
# the ISSUE-20 acceptance drill (seeded, in-process, ~5s): under
# injected monotone load drift (+4%/window), the LEGACY flat
# comparator run against a stale incumbent record PROMOTES an
# objectively-worse serving ladder (drift masquerades as a +40%
# throughput win, every true regression hides under the flat noise
# floors) while the interleaved A/B canary — adjacent incumbent/
# candidate windows scored pairwise under a weighted objective —
# ROLLS BACK the same plan 0/3 AND PROMOTES a genuinely-better plan
# 3/3 in the same run; every window, pairwise verdict and objective
# term is asserted present in steering_audit.json, and ft_timeline
# renders the A/B window timeline from that trail.
env -u PADDLE_TPU_METRICS_DIR -u PADDLE_TPU_SAMPLE_EVERY \
    -u PADDLE_TPU_TIMESERIES -u PADDLE_TPU_AB_PAIRS \
    python tools/steering_drill.py --drift

if [[ "${SKIP_TESTS:-0}" != "1" ]]; then
    echo "== gate 9: test suite =="
    python -m pytest tests/ -q
fi
echo "ALL CI GATES PASS"
