#!/usr/bin/env python
"""Mechanical perf gate: diff two bench / multichip / metrics JSON files.

Compares per-workload numbers between a BASE and a HEAD run and exits
nonzero when any watched higher-is-better metric regresses by more than
the threshold (or a lower-is-better one grows by more than it). This is
the regression gate the ROADMAP observability item asks for: CI diffs
the merged counters instead of a human eyeballing two JSON blobs.

Understands all three record shapes this repo emits:

- ``bench.py`` output           (``{"extras": {workload: {...}}}``)
- ``bench.py --multichip``      (``{"configs": {config: {...}}}``)
- merged job ``metrics.json``   (``{"counters_total": {counter: value}}``
                                from observability.distributed.merge_job_dir)

Single- and multi-chip records diff under one schema: every record
carries ``step_ms`` and a throughput field, and single-chip diags
carry an explicit ``collective_bytes: 0``.

Usage:
  tools/bench_diff.py BASE.json HEAD.json [--threshold 0.10]
      [--counters-threshold 0.25]

Exit codes: 0 = within threshold, 1 = regression past threshold,
2 = usage/load error.
"""
from __future__ import annotations

import argparse
import json
import sys

# per-workload metrics worth gating; direction: +1 higher is better,
# -1 lower is better. The profile-block metrics (bench.py `profile`:
# flops-derived mfu_est, measured overlap_frac / critical_path_ms)
# resolve through the record's "profile" sub-dict — _lookup descends.
WATCHED = (
    ("images_per_sec", +1), ("tokens_per_sec", +1),
    ("examples_per_sec", +1), ("steps_per_sec", +1),
    ("tokens_or_images_per_sec", +1),
    ("step_ms", -1), ("collective_bytes", -1),
    ("mfu_est", +1), ("overlap_frac", +1),
    ("critical_path_ms", -1), ("exposed_collective_ms", -1),
    # ISSUE-14 single-chip phase attribution: the fused-optimizer /
    # fused-epilogue / async-feed wins must show up HERE (optimizer
    # phase time and critical-path feed cost strictly down) — and a
    # change that silently regresses them fails the gate
    ("feed_ms", -1), ("optimizer_ms", -1),
    # device-truth counterparts (XPlane-folded; observability/
    # device_trace.py) + the host-vs-device agreement ratio — a
    # silently-diverging host estimate (the number the bucket planner
    # steers by) regresses agreement even when every host metric holds
    ("device_overlap_frac", +1), ("device_critical_path_ms", -1),
    ("host_device_agreement", +1),
    # serving records (tools/serving_bench.py --out): closed-loop
    # throughput/latency, queue wait, real batch size, padding waste,
    # and the compile count the bucket ladder exists to bound — a
    # serving regression fails CI exactly like a training one
    ("rows_per_s", +1), ("p50_ms", -1), ("p99_ms", -1),
    ("serving_queue_ms_p50", -1), ("serving_queue_ms_p99", -1),
    ("serving_batch_size_mean", +1),
    ("serving_padding_waste_frac", -1), ("jit_traces", -1),
    # PS scale records (tools/ps_scale_bench.py): the per-round
    # blake2b bill under incremental chunk digesting, and the delta
    # wire bytes for the same touched-rows workload — a change that
    # silently regresses incremental digesting back toward full
    # re-hashing (or row slices back toward whole-table ships) fails
    # here run-over-run
    ("ps_digest_ms", -1), ("rounds_per_s", +1),
    ("repl_delta_bytes_per_round", -1),
    # placement records (ISSUE 15, bench `placement` block): how well
    # the searched plan's PREDICTED step time tracks the measured one
    # (min/max ratio). A collapse means the cost model drifted off the
    # machine — the plan may still "work" while steering wrong.
    ("placement_agreement", +1),
)

# absolute noise floors for measured-timing metrics: a relative
# threshold alone turns sub-millisecond jitter on a near-zero base
# (0.2ms -> 0.5ms exposed time on a tiny CI smoke) into a +150%
# "regression". A delta must clear BOTH the relative threshold and
# this absolute floor to flag. Deterministic metrics have no floor.
ABS_NOISE_FLOOR = {
    "step_ms": 2.0, "critical_path_ms": 2.0,
    "exposed_collective_ms": 2.0, "overlap_frac": 0.1,
    # feed staging on a loaded box jitters at the ~ms level; the
    # optimizer phase is a measured re-execution slice
    "feed_ms": 1.0, "optimizer_ms": 2.0,
    "device_overlap_frac": 0.1, "device_critical_path_ms": 2.0,
    "host_device_agreement": 0.1,
    # serving latencies on a loaded CI box jitter in the single-digit
    # ms; batch size / padding waste depend on thread-arrival raggedness
    "p50_ms": 5.0, "p99_ms": 10.0,
    "serving_queue_ms_p50": 5.0, "serving_queue_ms_p99": 10.0,
    "serving_batch_size_mean": 1.0, "serving_padding_waste_frac": 0.15,
    # hashing time on a loaded CI box jitters; byte counts do not
    "ps_digest_ms": 5.0,
    # predicted-vs-measured ratio moves with CI-box timing noise
    "placement_agreement": 0.15,
}

# counter totals (metrics.json) where growth is a regression.
# ps.replication_bytes guards the ISSUE-8 delta-replication win: a
# code change that silently regresses the PS back to full-blob
# shipping shows up as growth of the byte counters (and of the
# mode=full series specifically) for the same drilled workload.
COUNTER_WATCH_GROWS_BAD = ("parallel.collective_bytes",
                           "parallel.collective_ops",
                           "executor.compile_fallbacks",
                           "ps.replication_bytes",
                           # fused single-chip program op count
                           # (tools/sc_smoke.py): deterministic —
                           # growth means the fusion passes regressed
                           "sc.program_ops",
                           # the serving smoke must stay error-free:
                           # any growth (including 0 -> n) is a bug
                           # the functional assertions may have missed
                           "serving.errors", "serving.batch_errors")


def load(path):
    with open(path) as f:
        doc = json.load(f)
    # the bench driver wraps bench.py's JSON line as {"parsed": {...}}
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return doc


def workloads(doc):
    """{workload: record} from any of the three supported shapes."""
    if "configs" in doc and isinstance(doc["configs"], dict):
        return dict(doc["configs"])  # multichip bench
    if "extras" in doc and isinstance(doc["extras"], dict):
        return {k: v for k, v in doc["extras"].items()
                if isinstance(v, dict) and not k.endswith("_error")}
    return {}


def counter_totals(doc):
    # merged job metrics.json (merge_job_dir) names the key
    # counters_total; accept the plain spelling too
    for key in ("counters_total", "totals"):
        if isinstance(doc.get(key), dict):
            return doc[key]
    if isinstance(doc.get("metrics_totals"), dict):
        return doc["metrics_totals"]  # multichip bench embeds them
    return {}


def _fmt(v):
    if isinstance(v, float):
        return "%.4g" % v
    return str(v)


def diff_records(base, head, threshold):
    """Yield (workload, metric, base, head, rel_delta, regressed)."""
    b_wl, h_wl = workloads(base), workloads(head)
    for name in sorted(set(b_wl) & set(h_wl)):
        b, h = b_wl[name], h_wl[name]
        for metric, direction in WATCHED:
            bv, hv = _lookup(b, metric), _lookup(h, metric)
            if bv is None or hv is None:
                continue
            if not bv:
                # growth from a zero base has no relative delta: show
                # the row (rel=inf) but don't hard-fail — a single-chip
                # BASE vs multichip HEAD legitimately goes 0 -> N
                # collective bytes, and the watched counter totals
                # below still gate structural from-zero growth
                if not hv:
                    continue
                yield name, metric, bv, hv, float("inf"), False
                continue
            rel = (hv - bv) / abs(bv)
            regressed = (-direction * rel) > threshold and \
                abs(hv - bv) > ABS_NOISE_FLOOR.get(metric, 0.0)
            yield name, metric, bv, hv, rel, regressed
        # a SILENT placement-plan change between runs is a regression:
        # same workload, same knobs, different plan digest means the
        # search (or its report) drifted without anyone deciding it
        bd = _plan_digest(b)
        hd = _plan_digest(h)
        if bd and hd and bd != hd:
            yield (name, "placement.plan_digest", bd[:12], hd[:12],
                   float("inf"), True)


def _plan_digest(rec):
    p = rec.get("placement")
    if isinstance(p, dict):
        d = p.get("plan_digest")
        if isinstance(d, str):
            return d
    return None


def _lookup(rec, metric):
    """A metric straight off the record, or from its profile block
    (mfu_est / overlap_frac / critical_path_ms), its diag (single-chip
    collective_bytes lives there), or its placement block
    (placement_agreement)."""
    v = rec.get(metric)
    if v is None and isinstance(rec.get("profile"), dict):
        v = rec["profile"].get(metric)
    if v is None and isinstance(rec.get("diag"), dict):
        v = rec["diag"].get(metric)
    if v is None and isinstance(rec.get("placement"), dict):
        v = rec["placement"].get(metric)
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    return None


def diff_counters(base, head, threshold):
    b_t, h_t = counter_totals(base), counter_totals(head)
    for key in sorted(set(b_t) & set(h_t)):
        bv, hv = b_t[key], h_t[key]
        if not isinstance(bv, (int, float)):
            continue
        # exact key or its labeled series ("...{kind=...}") — a bare
        # prefix test would also catch parallel.collective_bytes_saved,
        # whose growth is an improvement
        grows_bad = any(key == w or key.startswith(w + "{")
                        for w in COUNTER_WATCH_GROWS_BAD)
        if not bv:
            if not hv:
                continue
            # zero -> nonzero growth of a watched counter is always a
            # regression (e.g. the first compile fallback appearing)
            yield key, bv, hv, float("inf"), grows_bad
            continue
        rel = (hv - bv) / abs(bv)
        yield key, bv, hv, rel, grows_bad and rel > threshold


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        epilog="Exit codes: 0 ok, 1 regression, 2 load error.")
    ap.add_argument("base", nargs="?", help="BASE json (bench / "
                    "multichip / merged metrics.json)")
    ap.add_argument("head", nargs="?",
                    help="HEAD json to compare against BASE")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="max relative regression per workload metric "
                         "(default 0.10 = 10%%)")
    ap.add_argument("--counters-threshold", type=float, default=0.25,
                    help="max relative growth for watched counter "
                         "totals (default 0.25)")
    ap.add_argument("--self-test", action="store_true",
                    help="run the built-in self test and exit")
    args = ap.parse_args(argv)

    if args.self_test:
        return _self_test()
    if not args.base or not args.head:
        ap.error("base and head are required (unless --self-test)")

    try:
        base, head = load(args.base), load(args.head)
    except (OSError, ValueError) as e:
        print("bench_diff: cannot load inputs: %s" % e, file=sys.stderr)
        return 2

    regressions = 0
    rows = list(diff_records(base, head, args.threshold))
    for name, metric, bv, hv, rel, bad in rows:
        mark = " REGRESSION" if bad else ""
        print("%-24s %-26s %12s -> %-12s %+7.2f%%%s"
              % (name, metric, _fmt(bv), _fmt(hv), rel * 100, mark))
        regressions += bad
    crows = list(diff_counters(base, head, args.counters_threshold))
    for key, bv, hv, rel, bad in crows:
        mark = " REGRESSION" if bad else ""
        print("%-51s %12s -> %-12s %+7.2f%%%s"
              % (key, _fmt(bv), _fmt(hv), rel * 100, mark))
        regressions += bad
    if not rows and not crows:
        print("bench_diff: no common workloads or counters between "
              "inputs", file=sys.stderr)
        return 2
    if regressions:
        print("bench_diff: %d metric(s) regressed past threshold"
              % regressions, file=sys.stderr)
        return 1
    print("bench_diff: ok (%d metrics compared)"
          % (len(rows) + len(crows)))
    return 0


def _self_test():
    """In-process sanity: detects a planted regression, passes a clean
    diff, and diffs a single-chip record against a multichip one."""
    single = {"extras": {"w": {"tokens_per_sec": 100.0, "step_ms": 10.0,
                               "diag": {"collective_bytes": 0}}}}
    multi = {"configs": {"w": {"tokens_per_sec": 100.0, "step_ms": 10.0,
                               "collective_bytes": 0}}}
    ok = list(diff_records(single, multi, 0.10))
    assert ok and not any(r[-1] for r in ok), ok
    # single-chip base (0 collective bytes) vs a multichip head: the
    # 0 -> N growth row shows but must not hard-fail the diff
    went_multi = {"configs": {"w": {"tokens_per_sec": 100.0,
                                    "step_ms": 10.0,
                                    "collective_bytes": 4096}}}
    rows = list(diff_records(single, went_multi, 0.10))
    zrow = [r for r in rows if r[1] == "collective_bytes"]
    assert zrow and not zrow[0][-1], rows
    slow = {"configs": {"w": {"tokens_per_sec": 50.0, "step_ms": 20.0,
                              "collective_bytes": 4096}}}
    bad = list(diff_records(single, slow, 0.10))
    assert any(r[-1] for r in bad), bad
    m0 = {"totals": {"parallel.collective_bytes": 1000,
                     "parallel.steps": 2}}
    m1 = {"totals": {"parallel.collective_bytes": 2000,
                     "parallel.steps": 2}}
    cbad = list(diff_counters(m0, m1, 0.25))
    assert any(r[-1] for r in cbad), cbad
    assert not any(r[-1] for r in diff_counters(m0, m0, 0.25))
    # growth from a ZERO base must still flag (no relative delta exists)
    z0 = {"totals": {"executor.compile_fallbacks": 0}}
    z1 = {"totals": {"executor.compile_fallbacks": 5}}
    zbad = list(diff_counters(z0, z1, 0.25))
    assert zbad and zbad[0][-1], zbad
    assert not list(diff_counters(z0, z0, 0.25))
    # a regression back to full-blob PS replication (delta bytes
    # ballooning for the same drilled workload) must flag
    r0 = {"totals": {"ps.replication_bytes{mode=delta}": 160,
                     "ps.replication_bytes{mode=full}": 16416}}
    r1 = {"totals": {"ps.replication_bytes{mode=delta}": 16416,
                     "ps.replication_bytes{mode=full}": 16416}}
    rbad = [r for r in diff_counters(r0, r1, 0.25) if r[-1]]
    assert rbad and rbad[0][0].startswith("ps.replication_bytes"), rbad
    assert not any(r[-1] for r in diff_counters(r0, r0, 0.25))
    # profile-block metrics: an overlap_frac / mfu_est drop past the
    # threshold is a regression even when raw throughput held
    p0 = {"configs": {"w": {"tokens_per_sec": 100.0, "profile": {
        "mfu_est": 0.40, "overlap_frac": 0.90,
        "critical_path_ms": 10.0}}}}
    p1 = {"configs": {"w": {"tokens_per_sec": 100.0, "profile": {
        "mfu_est": 0.40, "overlap_frac": 0.30,
        "critical_path_ms": 10.0}}}}
    pbad = [r for r in diff_records(p0, p1, 0.10)
            if r[1] == "overlap_frac"]
    assert pbad and pbad[0][-1], pbad
    assert not any(r[-1] for r in diff_records(p0, p0, 0.10))
    # single-chip phase attribution (ISSUE 14): an optimizer_ms /
    # feed_ms blowup past threshold+floor (fused update or async feed
    # silently off) must flag; sub-floor feed jitter must not
    f0 = {"extras": {"resnet50": {"images_per_sec": 100.0, "profile": {
        "mfu_est": 0.2, "optimizer_ms": 5.0, "feed_ms": 0.5}}}}
    f1 = {"extras": {"resnet50": {"images_per_sec": 100.0, "profile": {
        "mfu_est": 0.2, "optimizer_ms": 40.0, "feed_ms": 9.5}}}}
    fbad = {r[1] for r in diff_records(f0, f1, 0.5) if r[-1]}
    assert {"optimizer_ms", "feed_ms"} <= fbad, fbad
    f2 = {"extras": {"resnet50": {"images_per_sec": 100.0, "profile": {
        "mfu_est": 0.2, "optimizer_ms": 5.5, "feed_ms": 0.9}}}}
    assert not any(r[-1] for r in diff_records(f0, f2, 0.5)), \
        list(diff_records(f0, f2, 0.5))
    # a diag-level feed_ms (single-chip timed-loop measurement) also
    # resolves through _lookup
    g0d = {"extras": {"w": {"diag": {"feed_ms": 1.0}}}}
    g1d = {"extras": {"w": {"diag": {"feed_ms": 30.0}}}}
    gdbad = [r for r in diff_records(g0d, g1d, 0.5) if r[-1]]
    assert gdbad and gdbad[0][1] == "feed_ms", gdbad
    # sub-floor jitter on a near-zero timing base must NOT flag
    # (0.2ms -> 0.5ms exposed time is scheduler noise, not a 150%
    # regression), while the same relative delta at real magnitude
    # still does
    n0 = {"configs": {"w": {"profile": {"exposed_collective_ms": 0.2}}}}
    n1 = {"configs": {"w": {"profile": {"exposed_collective_ms": 0.5}}}}
    assert not any(r[-1] for r in diff_records(n0, n1, 0.5))
    n2 = {"configs": {"w": {"profile": {"exposed_collective_ms": 20.0}}}}
    n3 = {"configs": {"w": {"profile": {"exposed_collective_ms": 50.0}}}}
    nbad = list(diff_records(n2, n3, 0.5))
    assert any(r[-1] for r in nbad), nbad
    # device-truth metrics: a host-vs-device agreement collapse (the
    # host estimate silently diverging from the XPlane-folded truth)
    # must flag even when every host-side number held; sub-floor
    # agreement jitter must not
    d0 = {"configs": {"w": {"profile": {
        "overlap_frac": 0.60, "device_overlap_frac": 0.55,
        "host_device_agreement": 0.90}}}}
    d1 = {"configs": {"w": {"profile": {
        "overlap_frac": 0.60, "device_overlap_frac": 0.55,
        "host_device_agreement": 0.40}}}}
    dbad = [r for r in diff_records(d0, d1, 0.10)
            if r[1] == "host_device_agreement"]
    assert dbad and dbad[0][-1], dbad
    d2 = {"configs": {"w": {"profile": {
        "overlap_frac": 0.60, "device_overlap_frac": 0.55,
        "host_device_agreement": 0.85}}}}
    assert not any(r[-1] for r in diff_records(d0, d2, 0.10))
    dov = {"configs": {"w": {"profile": {
        "overlap_frac": 0.60, "device_overlap_frac": 0.10,
        "host_device_agreement": 0.90}}}}
    dovbad = [r for r in diff_records(d0, dov, 0.10)
              if r[1] == "device_overlap_frac"]
    assert dovbad and dovbad[0][-1], dovbad
    assert not any(r[-1] for r in diff_records(d0, d0, 0.10))
    # serving records: a queue-wait blowup or a compile-count leak
    # (the ladder property breaking) must flag; sub-floor latency
    # jitter must not; serving.errors growth from zero must flag
    s0 = {"configs": {"serving_smoke": {
        "rows_per_s": 5000.0, "p99_ms": 40.0,
        "serving_queue_ms_p99": 20.0, "serving_batch_size_mean": 3.0,
        "serving_padding_waste_frac": 0.3, "jit_traces": 4}},
        "counters_total": {"serving.errors": 0}}
    s1 = {"configs": {"serving_smoke": {
        "rows_per_s": 5000.0, "p99_ms": 44.0,
        "serving_queue_ms_p99": 24.0, "serving_batch_size_mean": 3.0,
        "serving_padding_waste_frac": 0.32, "jit_traces": 4}},
        "counters_total": {"serving.errors": 0}}
    assert not any(r[-1] for r in diff_records(s0, s1, 0.5)), \
        list(diff_records(s0, s1, 0.5))
    s2 = {"configs": {"serving_smoke": {
        "rows_per_s": 5000.0, "p99_ms": 40.0,
        "serving_queue_ms_p99": 200.0, "serving_batch_size_mean": 3.0,
        "serving_padding_waste_frac": 0.3, "jit_traces": 12}},
        "counters_total": {"serving.errors": 3}}
    sbad = {r[1] for r in diff_records(s0, s2, 0.5) if r[-1]}
    assert {"serving_queue_ms_p99", "jit_traces"} <= sbad, sbad
    scbad = [r for r in diff_counters(s0, s2, 0.25) if r[-1]]
    assert scbad and scbad[0][0] == "serving.errors", scbad
    # ps_scale records: a digest-cost regression past threshold+floor
    # (incremental digesting broken back toward full re-hash) must
    # flag; sub-floor hashing jitter must not; a delta-bytes blowup
    # (row slices regressing to whole-table ships) must flag
    g0 = {"configs": {"ps_scale": {
        "ps_digest_ms": 8.0, "rounds_per_s": 50.0,
        "repl_delta_bytes_per_round": 4096}}}
    g1 = {"configs": {"ps_scale": {
        "ps_digest_ms": 40.0, "rounds_per_s": 50.0,
        "repl_delta_bytes_per_round": 4096}}}
    gbad = [r for r in diff_records(g0, g1, 0.5)
            if r[1] == "ps_digest_ms"]
    assert gbad and gbad[0][-1], gbad
    g2 = {"configs": {"ps_scale": {
        "ps_digest_ms": 10.0, "rounds_per_s": 50.0,
        "repl_delta_bytes_per_round": 4096}}}
    assert not any(r[-1] for r in diff_records(g0, g2, 0.5))
    g3 = {"configs": {"ps_scale": {
        "ps_digest_ms": 8.0, "rounds_per_s": 50.0,
        "repl_delta_bytes_per_round": 16777216}}}
    g3bad = [r for r in diff_records(g0, g3, 0.5)
             if r[1] == "repl_delta_bytes_per_round"]
    assert g3bad and g3bad[0][-1], g3bad
    # placement records (ISSUE 15): a predicted-vs-measured agreement
    # collapse past threshold+floor must flag; sub-floor drift must
    # not; and a SILENT plan-digest change between runs always flags
    # while an unchanged plan never does
    pl0 = {"configs": {"mlp": {"step_ms": 300.0, "placement": {
        "plan_digest": "aaaa1111", "predicted_step_ms": 290.0,
        "placement_agreement": 0.95}}}}
    pl1 = {"configs": {"mlp": {"step_ms": 300.0, "placement": {
        "plan_digest": "aaaa1111", "predicted_step_ms": 120.0,
        "placement_agreement": 0.40}}}}
    plbad = [r for r in diff_records(pl0, pl1, 0.10)
             if r[1] == "placement_agreement"]
    assert plbad and plbad[0][-1], plbad
    pl2 = {"configs": {"mlp": {"step_ms": 300.0, "placement": {
        "plan_digest": "aaaa1111", "predicted_step_ms": 280.0,
        "placement_agreement": 0.88}}}}
    assert not any(r[-1] for r in diff_records(pl0, pl2, 0.10)), \
        list(diff_records(pl0, pl2, 0.10))
    pl3 = {"configs": {"mlp": {"step_ms": 300.0, "placement": {
        "plan_digest": "bbbb2222", "predicted_step_ms": 290.0,
        "placement_agreement": 0.95}}}}
    digrow = [r for r in diff_records(pl0, pl3, 0.10)
              if r[1] == "placement.plan_digest"]
    assert digrow and digrow[0][-1], digrow
    assert not any(r[1] == "placement.plan_digest"
                   for r in diff_records(pl0, pl0, 0.10))
    # a run WITHOUT a placement block diffs cleanly against one with
    assert not any(r[-1] for r in diff_records(
        {"configs": {"mlp": {"step_ms": 300.0}}}, pl0, 0.10))
    print("bench_diff self-test ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
