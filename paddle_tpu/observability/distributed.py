"""Distributed observability: trace propagation + job-level aggregation.

PR 1 gave every *process* a registry and a span buffer; this module
makes a *fleet* of them tell one story:

- **Trace context propagation** (Dapper-style). A ``TraceContext`` is
  a ``(trace_id, span_id)`` pair riding the existing RPC JSON header
  (``trace_id`` / ``parent_span`` fields — json-safe scalars, so
  old-frame peers simply ignore them). ``PSClient`` stamps one per
  sync round, the serving HTTP front stamps one per request, and the
  receiving side opens **child spans** under the propagated context
  (``child_span`` sets the thread-local current context, so work the
  handler does downstream — an apply, a replication rpc to a backup —
  joins the same trace across a third process). One training round or
  one HTTP request is then a single cross-process trace, retries,
  failovers and injected faults included.

- **Job-level aggregation.** When ``$PADDLE_TPU_METRICS_DIR`` is set,
  every process (trainer, pserver, backup, serving worker, launcher)
  arms a background dumper that periodically — and at exit, on
  SIGTERM, and on a fatal exception — writes its registry snapshot,
  span buffer, and flight-recorder ring to
  ``$PADDLE_TPU_METRICS_DIR/<role>-<rank>[.r<restart>].json``
  (atomically, via the checkpoint tmp+fsync+rename helper — a merge
  never reads a torn dump). ``merge_job_dir`` folds the per-process
  dumps into one job-level ``metrics.json`` (per-rank sections
  preserved + counter totals) and one merged chrome-trace
  ``trace.json`` (spans as "X" events, flight events as instants,
  per-process tracks) — produced by the launch supervisor even when
  children were SIGKILLed, since a killed child's *periodic* dumps
  survive it.

Span timestamps are ``time.perf_counter()`` microseconds; every dump
records ``clock_offset_us = wall_us - perf_us`` at write time, and the
merger rebases each process onto the shared wall clock — on one host
the residual skew is microseconds, far under the event gaps being
ordered.

Setting ``PADDLE_TPU_METRICS_DIR`` also arms the metrics layer itself
(a dump dir without metrics would be an empty dump); with the dir
unset this module costs one env read at import and nothing on any hot
path.
"""
from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import flight, timeseries, tracing

__all__ = ["TraceContext", "current", "set_current", "trace",
           "child_span", "record_span", "inject", "extract",
           "process_identity", "set_identity", "metrics_dir",
           "dump_path", "dump_process", "arm", "arm_from_env",
           "clear_stale_dumps", "job_trace_id", "fleet_round_args",
           "load_dumps", "doc_flight_events", "merge_job_dir",
           "load_sampled_profiles", "sampled_profile_drift",
           "write_clock_ping", "record_clock_offset",
           "load_clock_offsets", "applied_clock_skew_us",
           "CLOCK_PING_ENV",
           "JOB_TRACE_ENV", "MERGED_METRICS_NAME", "MERGED_TRACE_NAME"]

MERGED_METRICS_NAME = "metrics.json"
MERGED_TRACE_NAME = "trace.json"
_DUMP_SCHEMA = 1


def _gen_id(nhex: int) -> str:
    return os.urandom(nhex // 2).hex()


class TraceContext:
    """One node of a distributed trace: every span created under this
    context records ``trace_id`` and parents to ``span_id``."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)

    @classmethod
    def new(cls, parent: Optional["TraceContext"] = None):
        return cls(parent.trace_id if parent is not None else _gen_id(16),
                   _gen_id(8))

    def __repr__(self):
        return "TraceContext(%s/%s)" % (self.trace_id, self.span_id)


_tls = threading.local()


def current() -> Optional[TraceContext]:
    return getattr(_tls, "ctx", None)


def set_current(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Install ``ctx`` as this thread's context; returns the previous
    one (callers restore it — ``child_span`` does this for you)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prev


@contextlib.contextmanager
def trace(name: str, cat: str = "trace", **args):
    """Root span of a NEW trace, installed as the ambient context —
    the application-level entry point: wrap a unit of YOUR work (a
    training step, a batch job) and every rpc issued inside adopts it
    (``PSClient._stamp_trace`` prefers the ambient context over its
    own per-round trace; serving ``submit`` captures it). The runtime
    paths don't need it — ps_rpc mints per-round roots and the HTTP
    front uses ``child_span`` per request. No-op (yields None) when
    the span layer is disarmed — callers never pay for id generation
    on a dark path."""
    if not tracing.active():
        yield None
        return
    ctx = TraceContext.new()
    prev = set_current(ctx)
    t0 = time.perf_counter()
    try:
        yield ctx
    finally:
        set_current(prev)
        if tracing.active():
            a = {"trace_id": ctx.trace_id, "span_id": ctx.span_id}
            a.update(args)
            tracing._record(name, t0 * 1e6,
                            (time.perf_counter() - t0) * 1e6, cat, a)


@contextlib.contextmanager
def child_span(name: str, cat: str = "rpc",
               trace_id: Optional[str] = None,
               parent_span: Optional[str] = None, **args):
    """Span under a propagated or ambient context. Explicit
    ``trace_id``/``parent_span`` (extracted from an rpc header) win;
    otherwise the thread-local current context parents the span; with
    neither, a fresh trace starts. Installs itself as the current
    context for its duration, so nested work — including rpcs ISSUED
    from inside the handler — joins the same trace."""
    if not tracing.active():
        yield None
        return
    if trace_id is None:
        amb = current()
        if amb is not None:
            trace_id, parent_span = amb.trace_id, amb.span_id
        else:
            # fresh trace: a caller-supplied parent WITHOUT its trace
            # id would parent this root into an unrelated trace
            trace_id, parent_span = _gen_id(16), None
    ctx = TraceContext(trace_id, _gen_id(8))
    prev = set_current(ctx)
    t0 = time.perf_counter()
    try:
        yield ctx
    finally:
        set_current(prev)
        if tracing.active():
            a = {"trace_id": ctx.trace_id, "span_id": ctx.span_id}
            if parent_span:
                a["parent_span"] = str(parent_span)
            a.update(args)
            tracing._record(name, t0 * 1e6,
                            (time.perf_counter() - t0) * 1e6, cat, a)


def record_span(name: str, t0_perf: float, cat: str = "rpc",
                ctx: Optional[TraceContext] = None, **args) -> None:
    """Post-hoc span: ``t0_perf`` (a ``time.perf_counter()`` reading)
    to now, recorded under ``ctx`` (or the current context). For call
    sites that cannot wrap their body in a ``with`` — e.g. a latency
    measured across a retry loop."""
    if not tracing.active():
        return
    if ctx is None:
        ctx = current()
    a = dict(args)
    if ctx is not None:
        a.setdefault("trace_id", ctx.trace_id)
        a.setdefault("parent_span", ctx.span_id)
    tracing._record(name, t0_perf * 1e6,
                    (time.perf_counter() - t0_perf) * 1e6, cat,
                    a or None)


def inject(msg: Dict, ctx: Optional[TraceContext] = None) -> Dict:
    """Stamp ``trace_id`` / ``parent_span`` onto an rpc header dict
    (mutates and returns it). No-op when the span layer is disarmed or
    no context is available — absent fields are the old-frame wire
    shape and every peer tolerates them."""
    if tracing.active():
        if ctx is None:
            ctx = current()
        if ctx is not None:
            msg["trace_id"] = ctx.trace_id
            msg["parent_span"] = ctx.span_id
    return msg


def extract(msg: Dict) -> Tuple[Optional[str], Optional[str]]:
    """(trace_id, parent_span) from an rpc header; (None, None) when
    the peer predates propagation (or never armed it)."""
    tid = msg.get("trace_id") if isinstance(msg, dict) else None
    if not tid:
        return None, None
    return str(tid), (str(msg["parent_span"])
                      if msg.get("parent_span") else None)


# -- job trace id (collective-fleet propagation) ----------------------------
#
# The PS/serving paths propagate trace context on an rpc header; the
# collective-fleet path has NO header — ranks talk through compiled
# XLA collectives. Instead the launcher mints ONE job trace id into
# the environment every child inherits, and each rank derives the
# same (trace_id, round span) from it plus its LOCAL sync-round
# counter: data-parallel ranks advance in lockstep (the allreduce IS
# the barrier), so identical derivation needs no coordination message.

JOB_TRACE_ENV = "PADDLE_TPU_TRACE_ID"


def job_trace_id() -> Optional[str]:
    tid = os.environ.get(JOB_TRACE_ENV, "").strip()
    return tid or None


def fleet_round_args(round_no: int) -> Dict:
    """Span args joining one collective sync round to the job trace:
    every rank stamps ``trace_id`` = the job trace id and
    ``parent_span`` = a round id derived from ``round_no``, so the
    merged job ``trace.json`` shows rank 0..n-1's round-N steps as one
    cross-process timeline. Empty when the span layer is disarmed or
    no launcher minted a job trace id (a lone process stays a lone
    trace)."""
    if not tracing.active():
        return {}
    tid = job_trace_id()
    if tid is None:
        return {}
    return {"trace_id": tid, "parent_span": "dpround-%d" % int(round_no)}


# -- process identity -------------------------------------------------------

_identity: Optional[Tuple[str, int]] = None


def set_identity(role: str, rank: int) -> None:
    """Override the env-derived identity (the launch supervisor calls
    ``set_identity("launcher", 0)`` — its own env has no PADDLE_ROLE)."""
    global _identity
    _identity = (str(role), int(rank))
    sp = tracing.spool()
    if sp is not None:
        # the spool armed at import under the env-derived name; spans
        # must land under the name the dump (and thus the merge) will
        # use. Identity changes happen at process start, before any
        # meaningful spans, so re-pointing loses nothing that matters.
        base = os.path.splitext(_dump_basename())[0]
        if base != sp.base:
            from .spool import SpanSpool

            tracing._set_spool(SpanSpool.from_env(sp.dirname, base))


def process_identity() -> Tuple[str, int, int]:
    """(role, rank, restart) for dump naming. Role comes from the
    launch env contract (``PADDLE_ROLE`` / ``FT_ROLE``), rank from
    ``PADDLE_PSERVER_GLOBAL_INDEX`` (sharded jobs: the index in the
    FULL endpoint list — per-group ``PADDLE_PSERVER_INDEX`` repeats
    across shards and two servers must never clobber each other's
    dumps) falling back to ``PADDLE_PSERVER_INDEX`` (servers), or
    ``PADDLE_TRAINER_ID``; a process outside any launcher is
    ``proc-<pid>``."""
    restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0") or 0)
    if _identity is not None:
        return _identity[0], _identity[1], restart
    role = os.environ.get("PADDLE_ROLE") or os.environ.get("FT_ROLE")
    if not role:
        return "proc", os.getpid(), restart
    if role == "pserver":
        rank = int(os.environ.get("PADDLE_PSERVER_GLOBAL_INDEX")
                   or os.environ.get("PADDLE_PSERVER_INDEX", "0")
                   or 0)
    elif role == "serving":
        rank = int(os.environ.get("PADDLE_SERVING_REPLICA_INDEX", "0")
                   or 0)
    else:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
    return str(role), rank, restart


def _dump_basename() -> str:
    role, rank, restart = process_identity()
    base = "%s-%d" % (role, rank)
    if restart:
        # a relaunched incarnation must not overwrite its dead
        # predecessor's final dump — the merge wants both, labeled
        base += ".r%d" % restart
    inc = job_incarnation()
    if inc:
        # whole-JOB incarnation (cold restart, ISSUE 19): the restored
        # job keeps the dead incarnation's dumps as postmortem
        # evidence, so the new ones must not collide with them.
        # Incarnation 0 keeps the bare historical name.
        base += ".i%d" % inc
    return base + ".json"


_INCARNATION_ENV = "PADDLE_INCARNATION"


def job_incarnation() -> int:
    """The whole-job incarnation this process belongs to (0 = first
    launch; the launcher bumps ``PADDLE_INCARNATION`` on every cold
    restart from the durable round store)."""
    try:
        return int(os.environ.get(_INCARNATION_ENV, "0") or 0)
    except ValueError:
        return 0


def metrics_dir() -> Optional[str]:
    d = os.environ.get("PADDLE_TPU_METRICS_DIR", "").strip()
    return d or None


def dump_path() -> Optional[str]:
    """This process's slot in ``$PADDLE_TPU_METRICS_DIR`` (None when
    the dir is unset) — the one derivation ``dump_process`` writes to
    and surfaces like serving ``/healthz`` report."""
    d = metrics_dir()
    return os.path.join(d, _dump_basename()) if d else None


# -- cross-host clock handshake ---------------------------------------------
#
# Span/flight rebasing onto ``wall_us`` assumes every process shares
# one wall clock — true on a single host, wrong across nodes (NTP skew
# is routinely milliseconds, far above the event gaps being ordered).
# The launcher therefore PINGS each child's clock at spawn: the child
# writes its wall-clock reading to a ping file as soon as telemetry
# arms, the launcher brackets the observation between two readings of
# its OWN clock (the newest poll that did NOT see the file, and the
# one that did — one supervision-poll period, ~0.2s) and records
# ``skew_us = child_wall - midpoint`` with ``uncertainty_us =
# window/2`` to ``<proc>.clock.json``. The merge subtracts a skew from
# that process's timestamps only when it exceeds its own uncertainty —
# a same-host handshake (skew ≈ 0 ± poll window) must not INJECT
# poll-latency noise into a timeline that was already
# microsecond-correct. The file handshake's resolution is therefore
# the poll period: it corrects the unsynced-host / seconds-off-NTP
# case; sub-poll-period drift needs a real two-way RPC ping (ROADMAP).

CLOCK_PING_ENV = "PADDLE_TPU_CLOCK_PING"
_CLOCK_SCHEMA = "clock_offset_v1"


def write_clock_ping(path: Optional[str] = None) -> Optional[str]:
    """Child half of the handshake: write this process's wall-clock
    reading to the ping file the launcher named in
    ``$PADDLE_TPU_CLOCK_PING``. Called once when telemetry arms; a
    process outside any launcher (env unset) is a no-op."""
    if path is None:
        path = os.environ.get(CLOCK_PING_ENV, "").strip()
    if not path:
        return None
    try:
        from ..checkpoint import atomic_write_bytes

        atomic_write_bytes(path, json.dumps(
            {"wall_us": time.time() * 1e6,
             "pid": os.getpid()}).encode())
        return path
    except Exception:
        return None   # telemetry must never kill work


def record_clock_offset(dirname: str, proc: str, child_wall_us: float,
                        t0_us: float, t1_us: float) -> Tuple[float, float]:
    """Launcher half: the child reported ``child_wall_us`` at some
    launcher-time inside ``[t0_us, t1_us]`` (spawn .. ping observed).
    Estimate the skew against the window midpoint, bound it by the
    half-window, persist to ``<proc>.clock.json`` for the merge."""
    skew = float(child_wall_us) - (float(t0_us) + float(t1_us)) / 2.0
    unc = max(0.0, (float(t1_us) - float(t0_us)) / 2.0)
    try:
        from ..checkpoint import atomic_write_bytes

        atomic_write_bytes(
            os.path.join(dirname, "%s.clock.json" % proc),
            json.dumps({"schema": _CLOCK_SCHEMA, "proc": proc,
                        "skew_us": skew, "uncertainty_us": unc,
                        "measured_at": time.time()}).encode())
    except Exception:
        pass
    return skew, unc


def load_clock_offsets(dirname: str) -> Dict[str, Tuple[float, float]]:
    """{proc: (skew_us, uncertainty_us)} from every ``*.clock.json``
    the launcher recorded in ``dirname``."""
    out: Dict[str, Tuple[float, float]] = {}
    if not os.path.isdir(dirname):
        return out
    for path in sorted(os.listdir(dirname)):
        if not path.endswith(".clock.json"):
            continue
        try:
            with open(os.path.join(dirname, path), "r",
                      encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and doc.get("schema") == _CLOCK_SCHEMA:
            out[doc.get("proc")
                or path[:-len(".clock.json")]] = (
                float(doc.get("skew_us") or 0.0),
                float(doc.get("uncertainty_us") or 0.0))
    return out


def applied_clock_skew_us(skew: float, uncertainty: float) -> float:
    """The correction the merge actually applies: the measured skew
    when it is distinguishable from the handshake's own noise, else 0
    (see the section comment — a same-host ping must not smear a
    microsecond-accurate timeline by its poll latency)."""
    return skew if abs(skew) > uncertainty else 0.0


# -- per-process dumps ------------------------------------------------------

def dump_process(path: Optional[str] = None) -> Optional[str]:
    """Write this process's registry snapshot + span buffer + flight
    ring to ``path`` (default: its slot in ``$PADDLE_TPU_METRICS_DIR``;
    None and no-op when neither is given). Atomic — a reader never
    sees a torn dump — and safe to call from anywhere, any number of
    times: the newest write wins."""
    from .. import observability as _obs
    from ..checkpoint import atomic_write_bytes

    with _dump_lock:
        return _dump_process_locked(path, _obs, atomic_write_bytes)


def _dump_process_locked(path, _obs, atomic_write_bytes):
    if path is None:
        path = dump_path()
        if path is None:
            return None
    sp = tracing.spool()
    if sp is not None:
        # every dump (periodic/at-exit/on-signal) also drains the span
        # spool: head spans reach their segment file and the reservoir
        # file is rewritten, so a SIGKILL between dumps loses at most
        # one flush period of reservoir churn — never a spooled span
        sp.flush()
    role, rank, restart = process_identity()
    doc = {
        "schema": _DUMP_SCHEMA,
        "proc": os.path.splitext(os.path.basename(path))[0],
        "role": role,
        "rank": rank,
        "restart": restart,
        "incarnation": job_incarnation(),
        "pid": os.getpid(),
        "wrote_at": time.time(),
        # rebases perf_counter-stamped spans/flight events onto the
        # wall clock the whole job shares
        "clock_offset_us": time.time() * 1e6
        - time.perf_counter() * 1e6,
        "metrics": _obs.metrics().snapshot(),
        "spans": [list(ev) for ev in tracing.trace_events()],
        "span_stats": tracing.stats(),
        "flight": [list(ev) for ev in flight.events()],
        "flight_stats": flight.stats(),
    }
    if sp is not None:
        doc["spool"] = sp.stats()
    if timeseries.series_enabled():
        # sample this snapshot into the windowed rings and ship the
        # rings with the dump; older dumps simply lack the key
        timeseries.record_samples(doc["metrics"],
                                  wall_ts=doc["wrote_at"])
        series = timeseries.process_series()
        if series:
            doc["series"] = series
    atomic_write_bytes(path, json.dumps(doc, default=str).encode())
    return path


_arm_lock = threading.Lock()
_arm_state: Dict[str, object] = {}
# serializes dump writes against clear_stale_dumps: without it, a
# dump in flight on the background thread when a job start clears the
# dir could land AFTER the clear under a pre-identity name and
# resurrect a phantom process in the merge. RLock: the SIGTERM dump
# handler may interrupt the main thread mid-dump.
_dump_lock = threading.RLock()


def arm(dirname: Optional[str] = None,
        period_s: Optional[float] = None) -> bool:
    """Arm the periodic + at-exit + on-SIGTERM dumper (idempotent).
    ``dirname`` defaults to ``$PADDLE_TPU_METRICS_DIR``; cadence from
    ``period_s`` / ``$PADDLE_TPU_DUMP_PERIOD`` (seconds, default 5).
    Returns False (and arms nothing) when no directory is known."""
    if dirname is None:
        dirname = metrics_dir()
    if not dirname:
        return False
    with _arm_lock:
        if _arm_state.get("armed"):
            return True
        os.makedirs(dirname, exist_ok=True)
        if os.environ.get("PADDLE_TPU_SPOOL", "").strip().lower() \
                not in ("0", "off", "false", "no"):
            # arm the on-disk span spool (observability/spool.py): the
            # 64k ring stays the live cache, the spool becomes the
            # record a long-run merge reads
            from .spool import SpanSpool

            base = os.path.splitext(_dump_basename())[0]
            tracing._set_spool(SpanSpool.from_env(dirname, base))
        # clock handshake (child half): tell the launcher what this
        # host's wall clock reads, as early as telemetry exists — the
        # narrower the spawn→ping window, the tighter the skew bound
        write_clock_ping()
        if period_s is None:
            period_s = float(os.environ.get("PADDLE_TPU_DUMP_PERIOD",
                                            "5") or 5)
        stop = threading.Event()

        def _loop():
            while not stop.wait(max(0.05, period_s)):
                try:
                    dump_process()
                except Exception:
                    pass  # a failed periodic dump must never kill work

        t = threading.Thread(target=_loop, name="obs-dumper",
                             daemon=True)
        t.start()
        atexit.register(_final_dump)
        flight.install_excepthook()
        _install_sigterm_dump()
        _arm_state.update(armed=True, stop=stop, thread=t,
                          dir=dirname, period=period_s)
    return True


def _final_dump() -> None:
    try:
        dump_process()
    except Exception:
        pass


def _install_sigterm_dump() -> None:
    """The launch supervisor tears servers down with SIGTERM; their
    registries must reach disk first. Chains any existing handler;
    silently skipped off the main thread (signal.signal would raise)."""
    import signal as _signal

    try:
        prev = _signal.getsignal(_signal.SIGTERM)

        def _on_term(signum, frame):
            _final_dump()
            if prev is _signal.SIG_IGN:
                return  # the process chose to survive SIGTERM; a
                # telemetry hook must not change that
            if callable(prev):
                prev(signum, frame)
            else:
                _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
                os.kill(os.getpid(), _signal.SIGTERM)

        _signal.signal(_signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass


def arm_from_env() -> bool:
    """Called by ``observability._init_from_env``: a set
    ``PADDLE_TPU_METRICS_DIR`` arms the dumper (the metrics layer
    itself is enabled by the caller)."""
    return arm()


# -- job-level merge --------------------------------------------------------

def clear_stale_dumps(dirname: str) -> int:
    """Remove every ``*.json`` (per-process dumps AND a previous
    merge) and ``*.jsonl`` (span-spool segments) in ``dirname`` — the
    launch supervisor calls this at job start so a merged job view
    never mixes incarnations of the job itself. Returns the number of
    files removed; a missing dir is 0.

    DURABLE state is never touched (ISSUE 19): ``job.json`` (the
    whole-job restore manifest), ``__manifest__.json`` (checkpoint
    integrity manifests) and ``oplog.jsonl`` (the async op tail) are
    denylisted, and directories (``round-<n>``/``ckpt-<n>``/
    ``shard-<k>``) never match the file suffixes — so a job that
    points its metrics dir into (or at) a checkpoint tree cannot eat
    its own recovery data."""
    if not os.path.isdir(dirname):
        return 0
    keep = ("job.json", "__manifest__.json", "oplog.jsonl")
    n = 0
    with _dump_lock:  # an in-flight dump lands before the clear, and
        # any dump after it uses the caller's already-set identity
        for fn in os.listdir(dirname):
            if fn in keep:
                continue
            if fn.endswith(".json") or fn.endswith(".jsonl") \
                    or fn.endswith(".clockping") \
                    or fn.startswith(".tmp-"):
                try:
                    os.unlink(os.path.join(dirname, fn))
                    n += 1
                except OSError:
                    pass
    return n


def doc_flight_events(doc: Dict):
    """Yield one dump's flight events rebased onto the wall clock:
    ``(t_us, kind, fields)``. The ONE place the flight tuple shape and
    the clock rebase rule live — ``merge_job_dir`` and
    ``tools/ft_timeline.py`` both read through here, so the chrome
    timeline and the postmortem can never disagree about when an event
    happened."""
    off = float(doc.get("clock_offset_us") or 0.0)
    for ev in doc.get("flight") or []:
        ts, kind, fields = (list(ev) + [None] * 3)[:3]
        yield float(ts) + off, kind, fields or {}


def load_dumps(dirname: str) -> List[Dict]:
    """Every readable per-process dump in ``dirname`` (schema-checked;
    merge outputs and foreign json are skipped), sorted by proc name."""
    out = []
    if not os.path.isdir(dirname):
        return out
    for fn in sorted(os.listdir(dirname)):
        if not fn.endswith(".json") or fn in (MERGED_METRICS_NAME,
                                              MERGED_TRACE_NAME):
            continue
        try:
            with open(os.path.join(dirname, fn), "r",
                      encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and doc.get("schema") == _DUMP_SCHEMA \
                and "proc" in doc:
            out.append(doc)
    return out


def load_sampled_profiles(dirname: str) -> Dict[str, Dict]:
    """Every readable rolling sampled-capture report
    (``<proc>.profile.json``, written by ``observability.capture``) in
    ``dirname``, keyed by proc name. Foreign/torn json is skipped."""
    out: Dict[str, Dict] = {}
    if not os.path.isdir(dirname):
        return out
    for fn in sorted(os.listdir(dirname)):
        if not fn.endswith(".profile.json"):
            continue
        try:
            with open(os.path.join(dirname, fn), "r",
                      encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) \
                and doc.get("schema") == "sampled_profile_v1" \
                and "proc" in doc:
            out[doc["proc"]] = doc
    return out


# the per-rank profile numbers whose cross-rank spread the steering
# daemon watches (a straggler rank shows up as step_ms/phase spread,
# a drifting host estimate as agreement spread)
_DRIFT_METRICS = ("step_ms", "overlap_frac", "critical_path_ms",
                  "exposed_collective_ms", "feed_ms", "optimizer_ms",
                  "host_device_agreement")


def sampled_profile_drift(sampled: Dict[str, Dict]) -> Dict[str, Dict]:
    """Per-metric cross-rank spread over the newest sampled reports:
    ``{metric: {per_rank, min, max, spread}}``. Phases fold in as
    ``phase_ms.<name>`` rows."""
    series: Dict[str, Dict[str, float]] = {}
    for proc, doc in sampled.items():
        prof = doc.get("profile")
        if not isinstance(prof, dict):
            continue
        for m in _DRIFT_METRICS:
            v = prof.get(m)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                series.setdefault(m, {})[proc] = float(v)
        ph = prof.get("phase_ms")
        if isinstance(ph, dict):
            for name, v in ph.items():
                if isinstance(v, (int, float)):
                    series.setdefault("phase_ms.%s" % name,
                                      {})[proc] = float(v)
    out: Dict[str, Dict] = {}
    for m, per_rank in series.items():
        vals = list(per_rank.values())
        out[m] = {"per_rank": per_rank, "min": min(vals),
                  "max": max(vals), "spread": max(vals) - min(vals)}
    return out


def merge_job_dir(dirname: str) -> Tuple[Optional[str], Optional[str]]:
    """Fold every per-process dump under ``dirname`` into
    ``metrics.json`` (per-process metric sections preserved under
    their ``<role>-<rank>`` keys + summed counter totals) and
    ``trace.json`` (one chrome-trace timeline: spans as "X" events,
    flight events as instants, one named track per process, all
    rebased onto the wall clock). Returns the two paths, or
    ``(None, None)`` when there is nothing to merge.

    Span source per process: the on-disk spool (head segments + the
    sampled reservoir — the record for long runs) UNIONED with the
    dump's ring snapshot (the exact newest-64k window — the spans a
    crash postmortem needs most, which a reservoir only samples),
    deduplicated; ring-only when the process never spooled."""
    from ..checkpoint import atomic_write_bytes
    from .spool import load_spooled_spans

    docs = load_dumps(dirname)
    if not docs:
        return None, None
    # a restored job (ISSUE 19) merges ONLY its own incarnation's
    # dumps: the dead incarnation's files stay on disk as postmortem
    # evidence (ft_timeline reads them raw) but must never mix into
    # this incarnation's metrics/trace. In-job (env set) that is THIS
    # incarnation; an offline postmortem tool merges the newest one
    # present. Dumps predating the field are incarnation 0.
    raw_inc = (os.environ.get(_INCARNATION_ENV) or "").strip()
    try:
        inc = int(raw_inc)
    except ValueError:
        inc = max((int(d.get("incarnation", 0) or 0) for d in docs),
                  default=0)
    docs = [d for d in docs
            if int(d.get("incarnation", 0) or 0) == inc]
    if not docs:
        return None, None
    clock_offsets = load_clock_offsets(dirname)
    processes: Dict[str, Dict] = {}
    totals: Dict[str, float] = {}
    events: List[Dict] = []
    metas: List[Dict] = []
    per_series: Dict[str, Dict] = {}
    series_skews: Dict[str, float] = {}
    for doc in docs:
        key = doc["proc"]
        # cross-host clock correction: rebase this process onto the
        # LAUNCHER's wall clock when the handshake measured a skew
        # above its own uncertainty (multi-node NTP drift); same-host
        # dumps keep the microsecond-accurate shared-wall assumption
        raw_skew, skew_unc = clock_offsets.get(key, (0.0, 0.0))
        skew = applied_clock_skew_us(raw_skew, skew_unc)
        spooled = load_spooled_spans(dirname, key)
        ring = doc.get("spans") or []
        if spooled is None:
            spans = ring
        else:
            # spool = head + reservoir (bounded, whole-run); ring =
            # exact tail. Most ring spans are also in the spool for
            # short runs — dedup on the full tuple (both sides have
            # json-roundtripped through the same encoding)
            seen = {json.dumps(ev, sort_keys=True, default=str)
                    for ev in spooled}
            spans = spooled + [
                ev for ev in ring
                if json.dumps(list(ev), sort_keys=True,
                              default=str) not in seen]
        processes[key] = {
            "role": doc.get("role"), "rank": doc.get("rank"),
            "restart": doc.get("restart"),
            "incarnation": int(doc.get("incarnation", 0) or 0),
            "pid": doc.get("pid"),
            "wrote_at": doc.get("wrote_at"),
            "metrics": doc.get("metrics") or {},
            "span_stats": doc.get("span_stats"),
            "span_source": "spool" if spooled is not None else "ring",
            "spool": doc.get("spool"),
            "flight_stats": doc.get("flight_stats"),
            "clock_skew_us": {"measured": raw_skew,
                              "uncertainty": skew_unc,
                              "applied": skew} if (key in clock_offsets)
            else None,
        }
        ser = doc.get("series")
        if isinstance(ser, dict) and ser:
            # windowed time-series rings (timeseries.py); ranks whose
            # dumps predate the field just don't contribute windows
            processes[key]["series"] = ser
            per_series[key] = ser
            series_skews[key] = skew
        for qn, v in (doc.get("metrics") or {}).get("counters",
                                                    {}).items():
            totals[qn] = totals.get(qn, 0) + v
        off = float(doc.get("clock_offset_us") or 0.0) - skew
        pid = int(doc.get("pid") or 0)
        metas.append({"name": "process_name", "ph": "M", "pid": pid,
                      "tid": 0, "args": {"name": key}})
        for ev in spans:
            name, ts, dur, tid, cat, args = (list(ev) + [None] * 6)[:6]
            entry = {"name": name, "ph": "X", "ts": ts + off,
                     "dur": dur, "pid": pid, "tid": tid, "cat": cat}
            if args:
                entry["args"] = args
            events.append(entry)
        for ts, kind, fields in doc_flight_events(doc):
            entry = {"name": kind, "ph": "i", "ts": ts - skew,
                     "pid": pid, "tid": 0, "s": "p", "cat": "flight"}
            if fields:
                entry["args"] = fields
            events.append(entry)
    events.sort(key=lambda e: e["ts"])
    # sampled in-production capture (observability/capture.py): attach
    # each process's rolling profile report to its section and surface
    # the cross-rank drift the steering daemon keys on
    sampled = load_sampled_profiles(dirname)
    for key, sdoc in sampled.items():
        if key in processes:
            processes[key]["sampled_profile"] = sdoc
    merged = {"merged_at": time.time(), "incarnation": inc,
              "processes": processes, "counters_total": totals}
    if per_series:
        # job-aligned windows: every rank's timestamps rebased by its
        # APPLIED skew so windowed deltas line up across hosts
        merged["series_windows"] = timeseries.job_windows(
            per_series, skews_us=series_skews)
    if sampled:
        merged["sampled_profiles"] = sampled
        merged["sampled_profile_drift"] = sampled_profile_drift(sampled)
    mpath = os.path.join(dirname, MERGED_METRICS_NAME)
    tpath = os.path.join(dirname, MERGED_TRACE_NAME)
    atomic_write_bytes(mpath, json.dumps(
        merged, default=str, sort_keys=True).encode())
    atomic_write_bytes(tpath, json.dumps(
        {"traceEvents": metas + events, "displayTimeUnit": "ms"},
        default=str).encode())
    return mpath, tpath
