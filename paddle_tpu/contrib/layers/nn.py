"""contrib layer APIs.

Parity: /root/reference/python/paddle/fluid/contrib/layers/nn.py —
the tractable subset (shuffle_batch :747, partial_concat :811,
partial_sum, multiclass_nms2 :501, fused_embedding_seq_pool :435,
fused_elemwise_activation :39). The CTR/NLP LoD specials (var_conv_2d,
match_matrix_tensor, search_pyramid_hash, tree_conv,
sequence_topk_avg_pooling) are intentionally absent — calling them
should fail loudly rather than silently diverge, and their kernels are
16k LoC of niche reference code pending demand.
"""
from __future__ import annotations

from ...layer_helper import LayerHelper

__all__ = ["tree_conv",
           "shuffle_batch", "partial_concat", "partial_sum",
           "multiclass_nms2", "fused_embedding_seq_pool",
           "fused_elemwise_activation"]


def shuffle_batch(x, seed=None):
    """Random row-shuffle of the leading dims (reference :747)."""
    helper = LayerHelper("shuffle_batch", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    idx = helper.create_variable_for_type_inference("int32")
    order = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "shuffle_batch", inputs={"X": [x]},
        outputs={"Out": [out], "ShuffleIdx": [idx], "SeedOut": [order]},
        attrs={"startup_seed": int(seed) if seed is not None else 0},
        infer_shape=False)
    out.shape = tuple(x.shape) if x.shape else None
    return out


def partial_concat(input, start_index=0, length=-1):
    """Concat a [start:start+length] column slice of each input
    (reference :811)."""
    if not isinstance(input, (list, tuple)):
        input = [input]
    helper = LayerHelper("partial_concat", input=input[0])
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("partial_concat", inputs={"X": list(input)},
                     outputs={"Out": [out]},
                     attrs={"start_index": start_index, "length": length},
                     infer_shape=False)
    return out


def partial_sum(input, start_index=0, length=-1):
    """Sum a [start:start+length] column slice across inputs."""
    if not isinstance(input, (list, tuple)):
        input = [input]
    helper = LayerHelper("partial_sum", input=input[0])
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("partial_sum", inputs={"X": list(input)},
                     outputs={"Out": [out]},
                     attrs={"start_index": start_index, "length": length},
                     infer_shape=False)
    return out


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k,
                    keep_top_k, nms_threshold=0.3, normalized=True,
                    nms_eta=1.0, background_label=0, return_index=False,
                    name=None):
    """multiclass_nms that can also return the kept row indices
    (reference :501)."""
    helper = LayerHelper("multiclass_nms2", input=bboxes, name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    outputs = {"Out": [out]}
    if return_index:
        index = helper.create_variable_for_type_inference("int32")
        outputs["Index"] = [index]
    helper.append_op(
        "multiclass_nms", inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs=outputs,
        attrs={"background_label": background_label,
               "score_threshold": score_threshold,
               "nms_top_k": nms_top_k, "nms_threshold": nms_threshold,
               "nms_eta": nms_eta, "keep_top_k": keep_top_k,
               "normalized": normalized},
        infer_shape=False)
    if return_index:
        return out, index
    return out


def fused_embedding_seq_pool(input, size, is_sparse=False,
                             padding_idx=None, combiner="sum",
                             param_attr=None, dtype="float32"):
    """Embedding lookup fused with sequence sum pooling (reference
    :435). Composite here — XLA fuses the gather+segment-sum anyway."""
    from ... import layers

    if combiner != "sum":
        raise NotImplementedError("only combiner='sum' is supported")
    emb = layers.embedding(input, size=size, is_sparse=is_sparse,
                           padding_idx=padding_idx,
                           param_attr=param_attr, dtype=dtype)
    return layers.sequence_pool(emb, pool_type="sum")


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """(reference :39) — over the fused op in ops/fused_ops.py."""
    helper = LayerHelper("fused_elemwise_activation", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    mid = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "fused_elemwise_activation",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out], "IntermediateOut": [mid]},
        attrs={"functor_list": list(functor_list), "axis": axis,
               "scale": scale,
               "save_intermediate_out": save_intermediate_out},
        infer_shape=False)
    return out


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """Tree-based convolution, TBCNN (reference contrib/layers/nn.py:370
    over tree_conv_op.cc): Filter [F, 3, output_size, num_filters]."""
    from ...layer_helper import LayerHelper

    helper = LayerHelper("tree_conv", input=nodes_vector,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = nodes_vector.dtype
    feature_size = int(nodes_vector.shape[2])
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[feature_size, 3, output_size, num_filters], dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "tree_conv",
        inputs={"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                "Filter": [w]},
        outputs={"Out": [out]},
        attrs={"max_depth": max_depth}, infer_shape=False)
    out.shape = (nodes_vector.shape[0], nodes_vector.shape[1],
                 output_size, num_filters)
    out.dtype = dtype
    # reference tree_conv uses the default dim_start=1 bias:
    # shape [max_nodes, output_size, num_filters]
    pre_act = helper.append_bias_op(out)
    return helper.append_activation(pre_act)
